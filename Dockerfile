# Indexer / scoring-service image (reference: Dockerfile).
#
# The indexer is control-plane only — it needs no TPU; vLLM-TPU pods run
# their own image with the offload connector installed. CPU jax keeps
# the image small while sharing the exact hashing/indexing code paths.
FROM python:3.12-slim AS base

RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ libzmq3-dev && \
    rm -rf /var/lib/apt/lists/*

WORKDIR /app
# jax[cpu] first: the pyproject dependency is plain "jax" (TPU hosts
# bring their own accelerator build); the control-plane image pins CPU.
RUN pip install --no-cache-dir "jax[cpu]"

COPY pyproject.toml README.md ./
COPY llm_d_kv_cache_manager_tpu ./llm_d_kv_cache_manager_tpu
RUN pip install --no-cache-dir .
# Build the native engine (hash fast path + offload I/O pool) in-tree.
RUN python -m llm_d_kv_cache_manager_tpu.native.build

EXPOSE 8080 5557
ENV PYTHONUNBUFFERED=1
# PYTHONHASHSEED must match the serving fleet's seed or block hashes
# diverge fleet-wide (SURVEY §5 config invariant).
ENV PYTHONHASHSEED=42

ENTRYPOINT ["python", "-m", "llm_d_kv_cache_manager_tpu.api.http_service"]
