# Developer entry points (reference: Makefile targets unit-test /
# e2e-test / bench, .github/workflows/ci-pr-checks.yaml).

PYTHON ?= python
CPU_ENV = JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8

.PHONY: all lint kvlint racefuzz-smoke lockorder-smoke test unit-test e2e-test examples obs-smoke slo-smoke perf-smoke perf-trend profile-smoke events-smoke cachestats-smoke tiering-smoke transfer-smoke cluster-smoke offload-smoke replay-smoke whatif-smoke bench native native-race proto graft-check chart clean

all: native test

# Same invocation as CI's lint step (.github/workflows/ci.yaml); the
# flags also live in .flake8 so a bare `flake8` agrees.  The native
# format gate is HARD: real clang-format when installed, and always
# the portable subset checker (hack/check_native_format.py) — the
# same pair CI enforces.
lint:
	@if $(PYTHON) -c "import flake8" >/dev/null 2>&1; then \
		$(PYTHON) -m flake8 llm_d_kv_cache_manager_tpu tests examples \
			--max-line-length 100 --extend-ignore E203,W503; \
	else \
		echo "flake8 not installed; skipping python lint (CI runs it)"; \
	fi
	@if command -v clang-format >/dev/null 2>&1; then \
		clang-format --dry-run --Werror \
			llm_d_kv_cache_manager_tpu/native/src/*.cpp \
			llm_d_kv_cache_manager_tpu/native/src/*.hpp; \
	fi
	$(PYTHON) hack/check_native_format.py
	$(MAKE) kvlint

# Project-invariant static analysis (hack/kvlint, stdlib-only; see
# docs/static-analysis.md): per-file rules (lock discipline, tracer
# safety, canonical serialization, blocking-in-async, swallowed
# errors, shutdown discipline, split-lock atomicity, GIL-dependence)
# plus the whole-program pass (lock-order graph, contract-surface
# drift vs docs/) and the raceguard-manifest staleness pin — one
# invocation, same as CI and hooks/pre-commit.sh.
kvlint:
	$(PYTHON) -m hack.kvlint llm_d_kv_cache_manager_tpu --check-manifest

# Preemption-fuzzed storms under guarded-by runtime enforcement
# (hack/racefuzz.py; docs/static-analysis.md): two storms re-run with
# raceguard armed, sys.setswitchinterval(1e-6) and seeded yield
# injection at guarded-access/lock-acquire boundaries, plus the three
# planted defects that prove the harness can see what it claims.
# Bounded time, pinned seed — same invocation as CI's
# "Race-certification smoke" step.
racefuzz-smoke:
	$(PYTHON) -m hack.racefuzz --plant guarded-write --seed 1337
	$(PYTHON) -m hack.racefuzz --plant caller-locked --seed 1337
	$(PYTHON) -m hack.racefuzz --plant check-then-act --seed 1337
	$(PYTHON) -m hack.racefuzz --seed 1337 --time-budget 180 --storms \
		tests/test_concurrency.py::TestBackendStorm \
		tests/test_concurrency.py::TestShardedIndexStorm \
		tests/test_concurrency.py::TestClusterFanoutStorm

# Dynamic half of kvlint KV006 (same invocation as CI's "Lock-order
# watchdog smoke" step): the concurrency storms plus the watchdog unit
# suite with KVTPU_LOCK_ORDER_DEBUG=1, so every tracked lock —
# including ones constructed at import time — asserts the declared
# acquisition order while the storms hammer it (docs/static-analysis.md).
lockorder-smoke:
	KVTPU_LOCK_ORDER_DEBUG=1 $(PYTHON) -m pytest tests/test_concurrency.py tests/test_lockorder.py -q

test: unit-test

unit-test:
	$(PYTHON) -m pytest tests/ -x -q

e2e-test:
	$(PYTHON) -m pytest tests/test_indexer_e2e.py tests/test_zmq_integration.py tests/test_grpc_api.py tests/test_http_service.py tests/test_service_e2e.py tests/test_debug_surface.py -q

examples:
	bash hack/verify-examples.sh

# Tracing debug-surface smoke (same invocation as CI's
# "Observability smoke" step): booted service, traceparent round-trip,
# /debug/traces retrieval, explain=1, /healthz block.
obs-smoke:
	$(PYTHON) hack/verify_observability.py

# Fleet observability smoke (same invocation as CI's "SLO smoke"
# step): 3 strict-wire replicas behind a router service — a scored
# request stitches into ONE cross-replica trace (owner cluster.rpc
# spans + piggybacked replica-side sub-spans, stage sums ±5% of e2e),
# /debug/slo reports healthy under traffic then flags a bounded
# degradation when a replica is killed mid-traffic, with the envelope
# asserted via envelope_violations (docs/observability.md).
slo-smoke:
	$(CPU_ENV) $(PYTHON) hack/slo_smoke.py

# Incident capture & replay smoke (same invocation as CI's "Replay
# smoke" step): booted service under event + scoring traffic with the
# input flight recorder attached — a forced SLO violation writes one
# incident bundle (capture + traces + profile + timeline + slo +
# config fingerprint, listed at /debug/incidents), replaying the
# bundle's capture through a fresh stack reproduces every recorded
# score bit-identically and the final index state exactly, and a
# deliberately mutated capture reports a first-divergence point
# (docs/observability.md "Incident response runbook").
replay-smoke:
	$(CPU_ENV) $(PYTHON) hack/replay_smoke.py

# What-if engine smoke (same invocation as CI's "What-if smoke"
# step): composes a 4x pod-fanout storm from the pinned reference
# capture, proves the shards=1 vs shards=8 A/B deterministically
# agrees (and a flow-control-starved arm measurably sheds with a
# first SLO-divergence point), exercises GET /debug/whatif,
# GET /debug/incidents/<id> and POST /admin/whatif against a live
# bundle, and verifies the perf-trend capacity gate passes honestly
# and fails a planted regression (docs/observability.md "What-if
# engine").
whatif-smoke:
	$(CPU_ENV) $(PYTHON) hack/whatif_smoke.py

# Read-path perf smoke (same invocation as CI's "Read-path perf
# smoke" step): a few seconds of the bench's read_path regime on CPU,
# asserting sane output + fast-lane score parity (docs/performance.md).
perf-smoke:
	$(CPU_ENV) $(PYTHON) hack/perf_smoke.py

# Perf-trend gate (same invocation as CI's "Perf trend" step): parse
# the BENCH_r*.json trajectory at the repo root, print the per-regime
# headline trend table, and exit non-zero when the newest artifact
# regresses a prior higher-is-better headline by >10%
# (docs/benchmarks.md).
perf-trend:
	$(PYTHON) hack/perf_trend.py

# Continuous-profiling smoke (same invocation as CI's "Profiling
# smoke" step): booted service under named-thread traffic — collapsed
# stacks attribute >=90% of samples to kvtpu-* roles, a planted
# two-thread lock fight is visible per lock name in
# /debug/profile?kind=locks AND kvtpu_lock_wait_seconds{lock}, the
# timeline shows the traffic ramp, and the PROFILE_HZ=0 /
# LOCK_CONTENTION_SAMPLE=0 off paths are verified zero-cost
# (docs/observability.md).
profile-smoke:
	$(CPU_ENV) $(PYTHON) hack/profile_smoke.py

# Cache-analytics smoke (same invocation as CI's "Cache analytics
# smoke" step): booted service with the hit-attribution ledger + an
# auditor over a controllable inventory — scored traffic lands in
# /debug/cachestats (totals, windows, family drill-down), a planted
# divergence is detected within one audit cycle, /healthz carries the
# analytics block, and the new metric families are on /metrics
# (docs/observability.md).
cachestats-smoke:
	$(CPU_ENV) $(PYTHON) hack/cachestats_smoke.py

# Tiering smoke (same invocation as CI's "Tiering smoke" step):
# booted service with the policy engine — traffic teaches the
# PolicyFeed, a forced demotion lands in /debug/tiering, /metrics AND
# the live score (1.0 -> 0.8/block), and the compute-or-load advice
# flips when the RTT estimator is inflated (docs/tiering.md).
tiering-smoke:
	$(CPU_ENV) $(PYTHON) hack/tiering_smoke.py

# Transfer smoke (same invocation as CI's "Transfer smoke" step):
# booted service with a TransferEngine — planned scoring yields a
# priced pod-to-pod directive, executing it publishes real KVEvents
# (the target's live score rises 0 -> full chain), and a cold pod
# registering for instant-warm gets the hot family pre-placed by the
# warm-up worker, all visible in /debug/transfer, /metrics and
# /healthz (docs/transfer.md).
transfer-smoke:
	$(CPU_ENV) $(PYTHON) hack/transfer_smoke.py

# Host-offload smoke (same invocation as CI's "Host-offload smoke"
# step): the staging engine moves real bytes — store->evict->load
# round trip bit-identical through the per-chip lanes, a demotion
# cycle pages group bytes hbm->host->shared_storage with the index
# tier AND the live score following each rung, and the advisor's
# read/write RTT estimators show measured transfers in /debug/tiering
# (docs/host-offload.md).
offload-smoke:
	$(CPU_ENV) $(PYTHON) hack/offload_smoke.py

# Cluster smoke (same invocation as CI's "Cluster smoke" step): 3
# in-process replicas + a router HTTP service over the RemoteIndex —
# event-plane traffic routed to slice owners, one replica killed
# mid-traffic, scores keep flowing, the journal-fed follower takes the
# slice over WARM (pre-kill scores reproduced exactly), failover
# visible in /debug/cluster and kvtpu_cluster_* (docs/replication.md).
cluster-smoke:
	$(CPU_ENV) $(PYTHON) hack/cluster_smoke.py

# Event-plane smoke (same invocation as CI's "Event-plane smoke"
# step): consolidated poller over ~64 inproc publishers — throughput
# floor, thread ceiling, zero cross-pod sheds under a chatty flood,
# forced gap -> resync, restart classification (docs/event-plane.md).
events-smoke:
	$(CPU_ENV) $(PYTHON) hack/events_smoke.py

# Fleet-routing benchmark; on TPU hardware drop JAX_PLATFORMS.
bench:
	$(PYTHON) bench.py

# Render the serving-fleet chart: real helm when installed, the
# subset renderer otherwise (same sources, same output).
chart:
	@if command -v helm >/dev/null 2>&1; then \
		helm template kvtpu deploy/chart; \
	else \
		$(PYTHON) hack/render_chart.py deploy/chart; \
	fi

# Build the native C++ engine in-tree.
native:
	$(PYTHON) -m llm_d_kv_cache_manager_tpu.native.build

# ThreadSanitizer stress of the native engine (race detection the
# reference never wired up; SURVEY.md §5).
native-race:
	$(PYTHON) -m llm_d_kv_cache_manager_tpu.native.build --stress-tsan

# Regenerate protobuf message code (grpc wiring is hand-written,
# api/grpc_services.py).
proto:
	cd llm_d_kv_cache_manager_tpu/api && \
	protoc -I protos --python_out=. protos/indexer.proto protos/tokenizer.proto

# What the driver runs: single-chip compile check + virtual multi-chip.
# The multichip check forces the CPU platform via jax.config too — a
# sitecustomize may pre-register an accelerator, and config beats env
# (same override as tests/conftest.py).
graft-check:
	$(PYTHON) -c "import __graft_entry__ as g; fn, args = g.entry(); import jax; jax.jit(fn)(*args); print('entry ok')"
	$(CPU_ENV) $(PYTHON) -c "import jax; jax.config.update('jax_platforms', 'cpu'); import __graft_entry__ as g; g.dryrun_multichip(8); print('multichip ok')"

clean:
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null; true
	rm -rf .pytest_cache llm_d_kv_cache_manager_tpu/native/_build
