"""Fleet-routing benchmark on real hardware (driver contract).

Reproduces the reference's headline experiment shape (BASELINE.md §1-2:
N pods, long shared prefix + short unique question, precise KV-aware
routing vs baseline scheduling) as a single-host simulation in which the
*prefill compute is real*: every request of the two anchored headline
runs executes the flagship Llama model on the default JAX device (the
TPU chip under the driver; CPU otherwise).

- 4 simulated pods, each with its own paged KV pool (models/
  kv_cache_pool.py geometry) and a vLLM-style local prefix cache.
- Workload: 8 prefix groups x 6 requests, 8192-token shared prefix +
  256-token unique suffix, shuffled arrival order (fixed seed).
- Write path is the real one: each prefill publishes BlockStored
  batches through the msgpack codec + sharded event pool into the
  in-memory index (kvevents/).
- Read path is the real one: the precise scheduler calls
  Indexer.get_pod_scores (tokenize -> chained block hashes -> index
  lookup -> tier-weighted longest-prefix score) and routes argmax.
- Load model: open-loop Poisson arrivals, each pod a FIFO server on a
  virtual clock (the reference's headline regime — QPS-loaded fleets
  where misrouting queues prefills, BASELINE.md §1-2).  Service times
  are the *real measured* on-device prefill times: a pod with the
  prefix cached runs ``prefill_continue`` over the 256-token suffix
  only; a miss runs ``prefill_paged`` over all 8448 tokens.
- TTFT per request = routing + queue wait + service.

Three layers of output (one JSON line, reference benchmarking/73-
capacity regime):

1. **Headline** (real compute per request): p50-TTFT speedup of
   precise routing over round-robin at 70% of ideal capacity — the
   BASELINE.json north star (>= 3x at >= 60% hit rate), so
   ``vs_baseline`` = speedup / 3.0.
2. **Matrix** (detail.matrix): 5 strategies (precise / estimated /
   load / random / round_robin, per the reference's strategy tables,
   benchmarking/73-capacity/README.md:241-419) x a QPS ladder x >= 3
   arrival seeds on the same virtual clock with the measured service
   times; p50+p90 TTFT, mean queue depth, hit rate.  The precise
   strategy runs the full real indexer read+write path per request.
3. **Compute** (detail.mfu / detail.kernels): prefill tok/s and MFU of
   the real on-device prefill, plus compiled-mode timings of the
   Pallas kernels vs their XLA counterparts at serving shapes, with a
   bench-time equality assert (the decode winner is routed into
   models/llama.py via LlamaConfig.decode_attention).
"""

from __future__ import annotations

import json
import random
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import IndexConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockRemoved,
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import Message, Pool, PoolConfig
from llm_d_kv_cache_manager_tpu.models import llama
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import Encoding

MODEL_NAME = "bench/llama"
NUM_PODS = 4
NUM_GROUPS = 8
REQS_PER_GROUP = 6
PREFIX_TOKENS = 8192  # benchmark 1's 8k shared system prompt
SUFFIX_TOKENS = 256
BLOCK_SIZE = 16
TOTAL_TOKENS = PREFIX_TOKENS + SUFFIX_TOKENS

# ~0.75B params + 8k prefix (flash-attention prefill): enough compute
# that prefill — the thing routing saves — dominates both the sub-ms
# routing overhead and the axon tunnel's ~70 ms host-readback RTT, as
# in the reference's fleet where an 8k prefill on a 70B model takes
# seconds (BASELINE.md §1).
CFG = llama.LlamaConfig(
    vocab_size=16384,
    d_model=2048,
    n_layers=16,
    n_heads=16,
    n_kv_heads=8,
    d_ff=5632,
    block_size=BLOCK_SIZE,
    dtype="bfloat16",
)
POOL_BLOCKS = 1536  # per pod: holds 2 groups' working set (precise
# routing assigns NUM_GROUPS/NUM_PODS = 2 groups per pod); reuse evicts


class WordTokenizer:
    """Deterministic whitespace tokenizer (ASCII words -> stable ids)."""

    def type(self) -> str:
        return "bench-word"

    def encode(
        self, prompt: str, model_name: str, add_special_tokens: bool
    ) -> Encoding:
        tokens: List[int] = []
        offsets: List[Tuple[int, int]] = []
        pos = 0
        for word in prompt.split(" "):
            tokens.append(int(word[1:]) if word[0] == "t" else 0)
            offsets.append((pos, pos + len(word)))
            pos += len(word) + 1
        return Encoding(tokens=tokens, offsets=offsets)


def make_prompts(rng: random.Random) -> List[Tuple[int, str, List[int]]]:
    """(group, prompt text, token ids) per request, shuffled arrival."""
    group_prefixes = [
        [rng.randrange(1, CFG.vocab_size) for _ in range(PREFIX_TOKENS)]
        for _ in range(NUM_GROUPS)
    ]
    requests = []
    for group in range(NUM_GROUPS):
        for _ in range(REQS_PER_GROUP):
            suffix = [
                rng.randrange(1, CFG.vocab_size) for _ in range(SUFFIX_TOKENS)
            ]
            tokens = group_prefixes[group] + suffix
            text = " ".join(f"t{t}" for t in tokens)
            requests.append((group, text, tokens))
    rng.shuffle(requests)
    return requests


class SimPod:
    """One simulated serving pod: paged pool + local prefix cache.

    ``with_kv=False`` (matrix runs) keeps the block-allocator and
    prefix-cache bookkeeping but skips the ~1.1 GB device pool — the
    virtual-clock runs never touch the device."""

    def __init__(self, name: str, params, with_kv: bool = True) -> None:
        self.name = name
        self.params = params
        self.kv = None
        if with_kv:
            self.kv = jnp.zeros(
                (
                    CFG.n_layers,
                    POOL_BLOCKS,
                    2,
                    CFG.block_size,
                    CFG.n_kv_heads,
                    CFG.head_dim,
                ),
                jnp.bfloat16,
            )
        self._next_block = 0
        # Engine-side prefix cache: chained block hash -> pool block id,
        # plus the reverse map so reuse evicts the old resident.
        self.cached: Dict[int, int] = {}
        self._block_owner: Dict[int, int] = {}

    def alloc(self, n: int) -> Tuple[List[int], List[int]]:
        """Bump-allocate n blocks; returns (ids, evicted block hashes).
        Like a real engine, reusing a block evicts whatever prefix block
        lived there — callers must publish the eviction."""
        ids = [
            (self._next_block + i) % POOL_BLOCKS for i in range(n)
        ]
        self._next_block = (self._next_block + n) % POOL_BLOCKS
        evicted: List[int] = []
        for bid in ids:
            old = self._block_owner.pop(bid, None)
            if old is not None and self.cached.get(old) == bid:
                del self.cached[old]
                evicted.append(old)
        return ids, evicted

    def cached_prefix_blocks(self, block_hashes: Sequence[int]) -> List[int]:
        """Pool ids of the longest cached consecutive prefix."""
        ids: List[int] = []
        for h in block_hashes:
            if h not in self.cached:
                break
            ids.append(self.cached[h])
        return ids


def block_hash_chain(tokens: Sequence[int]) -> List[int]:
    """vLLM-style chained block hashes (the engine's own hash config;
    the indexer absorbs any scheme via the engineKey->requestKey map)."""
    import hashlib

    hashes: List[int] = []
    parent = b"root"
    for i in range(0, len(tokens) - len(tokens) % BLOCK_SIZE, BLOCK_SIZE):
        chunk = tokens[i : i + BLOCK_SIZE]
        digest = hashlib.sha256(
            parent + np.asarray(chunk, np.int64).tobytes()
        ).digest()
        hashes.append(int.from_bytes(digest[-8:], "big"))
        parent = digest
    return hashes


def publish_events(
    event_pool: Pool,
    pod: SimPod,
    tokens: Sequence[int],
    block_hashes: Sequence[int],
    first_new: int,
    evicted: Sequence[int],
) -> None:
    """Publish this request's BlockRemoved (pool-block reuse) and
    BlockStored events in order, as the engine would."""
    events = []
    if evicted:
        events.append(BlockRemoved(block_hashes=list(evicted), medium="hbm"))
    if first_new < len(block_hashes):
        events.append(
            BlockStored(
                block_hashes=list(block_hashes[first_new:]),
                parent_block_hash=(
                    block_hashes[first_new - 1] if first_new > 0 else None
                ),
                token_ids=list(tokens[first_new * BLOCK_SIZE :]),
                block_size=BLOCK_SIZE,
                medium="hbm",
            )
        )
    if not events:
        return
    batch = EventBatch(ts=time.time(), events=events)
    event_pool.add_task(
        Message(
            topic=f"kv@{pod.name}@{MODEL_NAME}",
            payload=batch.encode(),
            pod_identifier=pod.name,
            model_name=MODEL_NAME,
        )
    )


def measure_readback_rtt() -> float:
    """Host->device->host round-trip floor for a trivial readback.

    TTFT sampling ends with an on-device argmax read back to the host;
    on a real TPU VM that costs microseconds, but through a remote
    device tunnel it adds a fixed ~tens-of-ms RPC that is not prefill
    compute.  Subtracting this floor keeps service times (and so the
    queueing model) faithful to what a serving pod would measure
    locally."""
    probe = jnp.arange(8, dtype=jnp.int32)
    int(jnp.sum(probe))  # drain any queued work
    samples = []
    for _ in range(5):
        t0 = time.perf_counter()
        int(jnp.sum(probe))
        samples.append(time.perf_counter() - t0)
    return float(np.median(samples))


def run_fleet(
    scheduler: str,
    requests,
    params,
    prefill_full,
    prefill_suffix,
    arrivals: Sequence[float],
    readback_rtt: float = 0.0,
) -> Tuple[List[float], float]:
    """Run the request stream under one scheduler; returns (TTFTs, hit
    rate).  A fresh indexer + event pool + pods per run.

    Open-loop load model (the reference's headline regime —
    BASELINE.md §1: Poisson arrivals at fixed QPS against N pods, where
    misrouting makes prefill queues pile up): requests *arrive* at
    ``arrivals[i]`` on a virtual clock; each pod is a FIFO server.  The
    prefill itself runs for real on the device and its measured wall
    time is the service time; queueing is then
    ``start = max(arrival, pod_free_at)`` and
    ``TTFT = routing + (start - arrival) + service``."""
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            kvblock_index_config=IndexConfig(),
        ),
        tokenizer=WordTokenizer(),
    )
    indexer.run()
    event_pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(concurrency=2),
    )
    event_pool.start()
    pods = [SimPod(f"pod-{i}", params) for i in range(NUM_PODS)]
    pod_by_name = {p.name: p for p in pods}

    ttfts: List[float] = []
    hits = 0
    rr_next = 0
    pod_free_at = {p.name: 0.0 for p in pods}
    try:
        for (group, text, tokens), arrival in zip(requests, arrivals):
            t0 = time.perf_counter()
            if scheduler == "precise":
                scores = indexer.get_pod_scores(
                    text, MODEL_NAME, [p.name for p in pods]
                )
                best = max(scores.values()) if scores else 0.0
                if best > 0:
                    pod = pod_by_name[
                        max(scores.items(), key=lambda kv: kv[1])[0]
                    ]
                else:
                    pod = pods[rr_next % NUM_PODS]
                    rr_next += 1
            else:
                pod = pods[rr_next % NUM_PODS]
                rr_next += 1

            routing_seconds = time.perf_counter() - t0

            hashes = block_hash_chain(tokens)
            cached_ids = pod.cached_prefix_blocks(hashes)
            # Suffix blocks never repeat across requests, so a hit is
            # exactly the shared prefix; treat partial-prefix hits as
            # misses (single compiled suffix shape).
            n_prefix_blocks = PREFIX_TOKENS // BLOCK_SIZE
            token_arr = np.asarray(tokens, np.int32)
            service_start = time.perf_counter()
            if len(cached_ids) >= n_prefix_blocks:
                hits += 1
                new_ids, evicted = pod.alloc(len(hashes) - n_prefix_blocks)
                table = jnp.asarray(
                    [cached_ids[:n_prefix_blocks] + new_ids], jnp.int32
                )
                logits, pod.kv = prefill_suffix(
                    pod.params,
                    jnp.asarray(token_arr[None, PREFIX_TOKENS:]),
                    pod.kv,
                    table,
                )
                first_new = n_prefix_blocks
                block_ids = cached_ids[:n_prefix_blocks] + new_ids
            else:
                new_ids, evicted = pod.alloc(len(hashes))
                table = jnp.asarray([new_ids], jnp.int32)
                logits, pod.kv = prefill_full(
                    pod.params, jnp.asarray(token_arr[None]), pod.kv, table
                )
                first_new = 0
                block_ids = new_ids
            # Service ends when the first sampled token reaches the host
            # (the same on-device argmax + readback both paths).
            int(jnp.argmax(logits[0, -1]))
            service_seconds = max(
                time.perf_counter() - service_start - readback_rtt, 1e-4
            )
            queue_start = max(arrival, pod_free_at[pod.name])
            pod_free_at[pod.name] = queue_start + service_seconds
            ttfts.append(
                routing_seconds
                + (queue_start - arrival)
                + service_seconds
            )

            # Register only newly-written blocks: re-registering the hit
            # prefix would resurrect hashes that alloc() just evicted when
            # the allocator wrapped into the cached prefix region, mapping
            # them to blocks that now hold suffix KV.
            for h, bid in zip(hashes[first_new:], block_ids[first_new:]):
                pod.cached[h] = bid
                pod._block_owner[bid] = h
            publish_events(
                event_pool, pod, tokens, hashes, first_new, evicted
            )
            event_pool.drain()  # index learns before the next arrival
    finally:
        event_pool.shutdown()
        indexer.shutdown()
    return ttfts, hits / len(requests)


def main() -> None:
    rng = random.Random(0)
    requests = make_prompts(rng)
    params = llama.init_params(jax.random.PRNGKey(0), CFG)

    # Donate the pool: each pod's ~1.1 GB kv array is updated in place
    # instead of copied per request (halves transient HBM, keeps the
    # copy out of every TTFT sample).
    prefill_full = jax.jit(
        lambda p, t, kv, bt: llama.prefill_paged(p, t, kv, bt, CFG),
        donate_argnums=(2,),
    )
    prefill_suffix = jax.jit(
        lambda p, t, kv, bt: llama.prefill_continue(
            p, t, kv, bt, PREFIX_TOKENS, CFG
        ),
        donate_argnums=(2,),
    )
    # Warm both shapes so compile time stays out of the TTFT samples,
    # and measure per-path service times to place the arrival rate.
    warm = SimPod("warm", params)
    full_ids, _ = warm.alloc(TOTAL_TOKENS // BLOCK_SIZE)
    tok = jnp.zeros((1, TOTAL_TOKENS), jnp.int32)
    t_miss = t_hit = float("inf")
    readback_rtt = 0.0
    for _ in range(2):  # second pass = compiled, warm path
        t0 = time.perf_counter()
        logits, warm.kv = prefill_full(
            params, tok, warm.kv, jnp.asarray([full_ids], jnp.int32)
        )
        int(jnp.argmax(logits[0, -1]))
        t_miss = min(t_miss, time.perf_counter() - t0)
        t0 = time.perf_counter()
        logits, warm.kv = prefill_suffix(
            params,
            tok[:, PREFIX_TOKENS:],
            warm.kv,
            jnp.asarray([full_ids], jnp.int32),
        )
        int(jnp.argmax(logits[0, -1]))
        t_hit = min(t_hit, time.perf_counter() - t0)
        readback_rtt = measure_readback_rtt()
    t_miss = max(t_miss - readback_rtt, 1e-4)
    t_hit = max(t_hit - readback_rtt, 1e-4)

    # Secondary metric: decode throughput over the warm pod's full
    # 8448-token context (the reference's output-tok/s axis; decode
    # attention is the Pallas paged kernel on TPU).
    decode = jax.jit(
        lambda p, t, kv, bt, cl: llama.decode_step(p, t, kv, bt, cl, CFG),
        donate_argnums=(2,),
    )
    table = jnp.asarray([full_ids], jnp.int32)
    ctx = jnp.asarray([TOTAL_TOKENS], jnp.int32)
    step_tok = jnp.zeros((1,), jnp.int32)
    logits, warm.kv = decode(params, step_tok, warm.kv, table, ctx)
    int(jnp.argmax(logits[0]))  # compile + drain
    decode_steps = 16
    t0 = time.perf_counter()
    for _ in range(decode_steps):
        logits, warm.kv = decode(params, step_tok, warm.kv, table, ctx)
    int(jnp.argmax(logits[0]))
    decode_elapsed = max(
        time.perf_counter() - t0 - readback_rtt, 1e-4
    )
    decode_tok_s = decode_steps / decode_elapsed
    del warm, logits

    # Arrival rate: 70% of the fleet's capacity under *ideal* routing
    # (first request per group misses, the rest hit).  A well-routed
    # fleet is comfortably stable there; a hit-blind scheduler's
    # effective service time is ~t_miss, pushing it past saturation so
    # prefill queues build — the reference's headline mechanism
    # (BASELINE.md §1-2: TTFT seconds-vs-minutes at the same QPS).
    ideal_miss_fraction = NUM_GROUPS / len(requests)
    ideal_service = (
        ideal_miss_fraction * t_miss + (1 - ideal_miss_fraction) * t_hit
    )
    qps = 0.7 * NUM_PODS / ideal_service
    arrival_rng = random.Random(7)
    arrivals: List[float] = []
    clock = 0.0
    for _ in requests:
        clock += arrival_rng.expovariate(qps)
        arrivals.append(clock)

    rr_ttfts, rr_hit = run_fleet(
        "round_robin", requests, params, prefill_full, prefill_suffix,
        arrivals, readback_rtt,
    )
    pr_ttfts, pr_hit = run_fleet(
        "precise", requests, params, prefill_full, prefill_suffix,
        arrivals, readback_rtt,
    )

    # Each group's FIRST arrival is an unavoidable cold miss under ANY
    # scheduler (the reference's harness likewise excludes its warmup
    # stage); percentiles cover the steady-state samples.  Both
    # schedulers share the arrival order, so the window is identical.
    seen_groups: set = set()
    warmup_idx = set()
    for i, (group, _, _) in enumerate(requests):
        if group not in seen_groups:
            seen_groups.add(group)
            warmup_idx.add(i)
    rr_steady = [t for i, t in enumerate(rr_ttfts) if i not in warmup_idx]
    pr_steady = [t for i, t in enumerate(pr_ttfts) if i not in warmup_idx]
    p50_rr = float(np.percentile(rr_steady, 50))
    p50_pr = float(np.percentile(pr_steady, 50))
    speedup = p50_rr / p50_pr if p50_pr > 0 else 0.0
    print(
        json.dumps(
            {
                "metric": "p50_ttft_speedup_precise_vs_round_robin",
                "value": round(speedup, 3),
                "unit": "x",
                "vs_baseline": round(speedup / 3.0, 3),
                "detail": {
                    "p50_ttft_precise_s": round(p50_pr, 5),
                    "p50_ttft_round_robin_s": round(p50_rr, 5),
                    "prefix_cache_hit_rate_precise": round(pr_hit, 3),
                    "prefix_cache_hit_rate_round_robin": round(rr_hit, 3),
                    "qps": round(qps, 2),
                    "service_miss_s": round(t_miss, 4),
                    "service_hit_s": round(t_hit, 4),
                    "readback_rtt_s": round(readback_rtt, 4),
                    "decode_tok_s_per_seq": round(decode_tok_s, 1),
                    "device": jax.devices()[0].platform,
                    "requests": len(requests),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
