"""Fleet-routing benchmark on real hardware (driver contract).

Reproduces the reference's headline experiment shape (BASELINE.md §1-2:
N pods, long shared prefix + short unique question, precise KV-aware
routing vs baseline scheduling) as a single-host simulation in which the
*prefill compute is real*: every request of the two anchored headline
runs executes the flagship Llama model on the default JAX device (the
TPU chip under the driver; CPU otherwise).

- 4 simulated pods, each with its own paged KV pool (models/
  kv_cache_pool.py geometry) and a vLLM-style local prefix cache.
- Workload: 8 prefix groups x 6 requests, 8192-token shared prefix +
  256-token unique suffix, shuffled arrival order (fixed seed).
- Write path is the real one: each prefill publishes BlockStored
  batches through the msgpack codec + sharded event pool into the
  in-memory index (kvevents/).
- Read path is the real one: the precise scheduler calls
  Indexer.get_pod_scores (tokenize -> chained block hashes -> index
  lookup -> tier-weighted longest-prefix score) and routes argmax.
- Load model: open-loop Poisson arrivals, each pod a FIFO server on a
  virtual clock (the reference's headline regime — QPS-loaded fleets
  where misrouting queues prefills, BASELINE.md §1-2).  Service times
  are the *real measured* on-device prefill times: a pod with the
  prefix cached runs ``prefill_continue`` over the 256-token suffix
  only; a miss runs ``prefill_paged`` over all 8448 tokens.
- TTFT per request = routing + queue wait + service.

Three layers of output (full artifact in a results file, compact
headline on stdout — see the driver-contract emit section; reference
benchmarking/73-capacity regime):

1. **Headline** (real compute per request): p50-TTFT speedup of
   precise routing over round-robin at 70% of ideal capacity — the
   BASELINE.json north star (>= 3x at >= 60% hit rate), so
   ``vs_baseline`` = speedup / 3.0.
2. **Matrix** (detail.matrix): 5 strategies (precise / estimated /
   load / random / round_robin, per the reference's strategy tables,
   benchmarking/73-capacity/README.md:241-419) x a QPS ladder x >= 3
   arrival seeds on the same virtual clock with the measured service
   times; p50+p90 TTFT, mean queue depth, hit rate.  The precise
   strategy runs the full real indexer read+write path per request.
   Three workload regimes: "steady" (the ladder), "churn" (pods hold
   barely one group's working set, constant eviction), and "restart"
   (scheduler-local routing history wiped mid-run — the index, rebuilt
   continuously from engine events, survives; precise holds its hit
   rate where history-only routing pays a cold restart).
3. **Compute** (detail.mfu / detail.kernels): prefill tok/s and MFU of
   the real on-device prefill, plus compiled-mode timings of the
   Pallas kernels vs their XLA counterparts at serving shapes, with a
   bench-time equality assert (the decode winner is routed into
   models/llama.py via LlamaConfig.decode_attention).

Operational contract: one stderr progress line per phase (a timed-out
run's tail shows where the time went), a persistent XLA compilation
cache in ``.xla_cache/`` (compiles dominate a cold run on this 1-core
host), and a soft wall-clock budget (``KVTPU_BENCH_BUDGET_S``, default
1500 s — deliberately under plausible driver timeouts) past which
optional layers are truncated — flagged in the JSON
— so the headline always prints inside the driver's timeout.

Stdout contract (the driver captures only the LAST ~2 KB): a one-line
probe-status JSON first and again immediately before the end, then a
compact (< 1.5 KB) headline JSON as the FINAL line; the full
matrix/micro/kernel detail goes to ``bench_results.json``
(``KVTPU_BENCH_RESULTS_PATH`` overrides) — see ``emit_result``.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

import jax

# Site hooks may force the tunnel platform via jax.config at interpreter
# start, where config beats env (see tests/conftest.py).  This knob wins
# for CI smoke runs and for validating bench logic when no chip is
# reachable: KVTPU_BENCH_PLATFORM=cpu python bench.py.
if os.environ.get("KVTPU_BENCH_PLATFORM"):
    jax.config.update(
        "jax_platforms", os.environ["KVTPU_BENCH_PLATFORM"]
    )
# Persistent XLA compilation cache: the bench compiles ~10 programs
# (two prefill shapes, decode, kernel sweep variants) at 20-60s each on
# this 1-core host — the dominant fixed cost of a run.  Cached, a rerun
# spends that budget measuring instead.
_XLA_CACHE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".xla_cache"
)
try:  # cache knobs vary across jax versions; best-effort
    jax.config.update("jax_compilation_cache_dir", _XLA_CACHE)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:  # noqa: BLE001
    pass
import jax.numpy as jnp
import numpy as np

_T_START = time.monotonic()


def _env_float(name: str, default: float) -> float:
    """A malformed knob must not crash before main()'s parseable-error
    machinery exists; fall back to the default, loudly."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        print(
            f"[bench] ignoring malformed {name}={raw!r}; "
            f"using {default}",
            file=sys.stderr,
        )
        return default


# Soft wall-clock budget: the driver runs `python bench.py` under its
# own (unknown) timeout; a bench that overruns records rc=124 and NO
# metric.  Degrade instead: past the budget, optional layers are
# truncated/skipped (marked in the JSON) and the headline still prints.
_BUDGET_S = _env_float("KVTPU_BENCH_BUDGET_S", 1500.0)


def _elapsed() -> float:
    return time.monotonic() - _T_START


def _over_budget(reserve_s: float = 0.0) -> bool:
    return _elapsed() + reserve_s > _BUDGET_S


def _progress(phase: str) -> None:
    """One stderr line per phase: a timed-out run's tail shows exactly
    where the time went instead of a bare platform warning."""
    print(
        f"[bench +{_elapsed():7.1f}s] {phase}",
        file=sys.stderr,
        flush=True,
    )


# ---------------- driver-contract emit (tail-survivable stdout) --------
#
# r5 post-mortem: the driver captures only the LAST ~2 KB of stdout, and
# the old single-line emit carried the full matrix/micro detail — the
# artifact was clipped to unparseable garbage and the round recorded no
# metric.  The contract now: full detail goes to a results FILE; stdout
# carries only small JSON lines — a probe-status line FIRST (so a run
# that dies mid-flight still leaves a diagnosis trail at the head),
# the same probe-status line again immediately before the last line
# (so it survives tail clipping too), and a compact headline JSON as
# the FINAL line, hard-bounded well under the capture window.

HEADLINE_MAX_BYTES = 1400  # < 1.5 KB with margin for the driver's tail


def _round_floats(obj, digits=4):
    """Round every float in a compact block: full-precision doubles
    (~18 chars each) are what blow the headline budget, and the full
    values live in the results file anyway.  Not applied to
    indexer_restart — the driver-contract test pins that block equal
    to the detail artifact."""
    if isinstance(obj, float):
        return round(obj, digits)
    if isinstance(obj, dict):
        return {k: _round_floats(v, digits) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_round_floats(v, digits) for v in obj]
    return obj


def _probe_status_line(probe: dict) -> None:
    """One-line probe diagnosis: outcome, error class, duration.
    Emitted first AND immediately before the final headline line, so a
    two-rounds-of-dead-chip failure is diagnosable from either end of
    a clipped capture."""
    print(json.dumps({"probe_status": probe}), flush=True)


def _results_file_path() -> str:
    return os.environ.get("KVTPU_BENCH_RESULTS_PATH") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_results.json"
    )


def _write_results_file(full: dict) -> Optional[str]:
    """Atomic (tmp+rename) write of the full artifact; None on failure
    — the compact headline still prints, flagging the lost detail."""
    path = _results_file_path()
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as handle:
            json.dump(full, handle)
        os.replace(tmp, path)
        return path
    except OSError as exc:
        print(
            f"[bench] results file write failed: {exc}", file=sys.stderr
        )
        try:
            if os.path.exists(tmp):
                os.unlink(tmp)
        except OSError:
            pass
        return None


def emit_result(full: dict, probe: dict) -> None:
    """Write the full artifact to the results file; print the probe
    line and then the compact headline as the process's last stdout
    line.  The headline repeats only what the driver needs: metric,
    value, error, device, the scoring-RPC percentiles, and the
    indexer_restart cold/warm comparison."""
    results_path = _write_results_file(full)
    detail = full.get("detail", {})
    read_path = detail.get("read_path") or {}
    read_path_compact = None
    if read_path and "warm_multi_turn" in read_path:
        read_path_compact = {
            "warm_sps": read_path["warm_multi_turn"].get("scores_per_sec"),
            "warm_p50_us": read_path["warm_multi_turn"].get("p50_us"),
            "warm_no_memo_sps": (
                read_path.get("warm_multi_turn_no_memo", {}).get(
                    "scores_per_sec"
                )
            ),
            "cold_sps": read_path["cold"].get("scores_per_sec"),
            "mixed_sps": read_path["mixed"].get("scores_per_sec"),
            "warm_speedup_vs_off": read_path.get("warm_speedup_vs_off"),
            "parity": read_path.get("parity"),
            # The other profiler cells (event_storm.profiler_ab,
            # replica_scaleout.fanout_profile) stay detail-only: the
            # compact line sits within ~100 bytes of the shed loop's
            # budget in full tiny runs, and one representative
            # overhead number is what the driver needs at a glance.
            "prof_overhead": (
                read_path.get("profiler_ab") or {}
            ).get("overhead"),
            # The capture_ab cells (read_path AND event_storm) stay
            # detail-only: the compact line sits within ~100 bytes of
            # the shed budget in full tiny runs, and adding one more
            # field here shed indexer_restart off the line (the
            # driver-contract test pins that block's presence).
        }
    cache_analytics = detail.get("cache_analytics") or {}
    cache_analytics_compact = None
    if cache_analytics and "ledger_truth" in cache_analytics:
        truth = cache_analytics.get("ledger_truth") or {}
        audit = cache_analytics.get("audit_plane") or {}
        overhead = cache_analytics.get("overhead") or {}
        cache_analytics_compact = {
            "ledger_hit_rate": truth.get("ledger_hit_rate"),
            "ground_truth": truth.get("ground_truth_hit_rate"),
            "within_2pct": truth.get("within_2pct"),
            "divergence_detected": audit.get("detected_within_one_cycle"),
            "detected_ratio": audit.get("detected_ratio"),
            "overhead_pct": overhead.get("overhead_pct"),
            "within_3pct": overhead.get("within_3pct"),
            "parity": overhead.get("parity"),
        }
    tiered_churn = detail.get("tiered_churn") or {}
    tiered_churn_compact = None
    if tiered_churn and "eviction_ab" in tiered_churn:
        ab = tiered_churn.get("eviction_ab") or {}
        col = tiered_churn.get("compute_or_load") or {}
        tiered_churn_compact = {
            "hit_lru": ab.get("hit_rate_lru"),
            "hit_pred": ab.get("hit_rate_predictive"),
            "beats_lru": ab.get("beats_lru"),
            "parity": ab.get("policy_off_parity"),
            "ttft_load_s": col.get("ttft_load_s"),
            "ttft_recompute_s": col.get("ttft_recompute_s"),
            "ttft_hybrid_s": col.get("ttft_hybrid_s"),
            "hybrid_ok": col.get("hybrid_le_min_pure"),
            "advice": (col.get("advice") or {}).get("action"),
        }
    scaleout_warmup = detail.get("scaleout_warmup") or {}
    scaleout_warmup_compact = None
    if scaleout_warmup and "arms" in scaleout_warmup:
        # Keys terse (p90 = [transfer_aware, route_to_holder,
        # round_robin] post-join p90 TTFT); full names live in
        # detail.scaleout_warmup.
        arms = scaleout_warmup.get("arms") or {}
        ta = arms.get("transfer_aware") or {}
        scaleout_warmup_compact = {
            "p90": [
                (arms.get(a) or {}).get("p90_ttft_post_join_s")
                for a in (
                    "transfer_aware",
                    "route_to_holder",
                    "round_robin",
                )
            ],
            "beats_rth": scaleout_warmup.get(
                "ttft_p90_beats_route_to_holder"
            ),
            "beats_rr": scaleout_warmup.get(
                "ttft_p90_beats_round_robin"
            ),
            "cold_ratio": scaleout_warmup.get("cold_pod_hit_ratio"),
            "cold_ok": scaleout_warmup.get(
                "cold_pod_warm_within_envelope"
            ),
            "env_s": ta.get("warmup_envelope_s"),
            "parity": (scaleout_warmup.get("parity") or {}).get(
                "parity"
            ),
        }
    host_offload = detail.get("host_offload") or {}
    # The regime pre-computes its compact block (bench_host_offload
    # "headline"); pass it through untouched.
    host_offload_compact = host_offload.get("headline")
    event_storm = detail.get("event_storm") or {}
    event_storm_compact = None
    if event_storm and "n_pods" in event_storm:
        gap = event_storm.get("gap_storm") or {}
        fairness = event_storm.get("fairness") or {}
        consolidated = event_storm.get("consolidated_pollers_1") or {}
        poller_scaling = event_storm.get("poller_scaling") or {}
        replica_local = event_storm.get("replica_local") or {}
        # Headline bytes are a hard driver budget (the shed loop below
        # drops whole blocks when the line overflows), so field names
        # here are terse: stage_us = [decode, apply] µs/msg,
        # p4_ratio = pollers-4-vs-1 non-inversion guard, ri_scaling =
        # replica-local 1→3 process scaling.  Full names live in the
        # results file (detail.event_storm).
        event_storm_compact = {
            "n_pods": event_storm.get("n_pods"),
            "apply_sps": consolidated.get("apply_msgs_per_sec"),
            "stage_us": [
                consolidated.get("decode_us_per_msg"),
                consolidated.get("apply_us_per_msg"),
            ],
            "p4_ratio": poller_scaling.get("ratio_4_vs_1"),
            "ri_scaling": replica_local.get("scaling_1_to_3"),
            "fairness_ok": fairness.get("property_holds"),
            "gap_s": gap.get("recovery_wall_s"),
            "consistency": gap.get("post_resync_consistency"),
        }
    replica_scaleout = detail.get("replica_scaleout") or {}
    replica_scaleout_compact = None
    if replica_scaleout and "cluster_3_replicas" in replica_scaleout:
        failover = replica_scaleout.get("failover") or {}
        replica_scaleout_compact = {
            "single_sps": replica_scaleout["single"].get(
                "scores_per_sec"
            ),
            "cluster1_sps": replica_scaleout["cluster_1_replica"].get(
                "scores_per_sec"
            ),
            "cluster3_sps": replica_scaleout["cluster_3_replicas"].get(
                "scores_per_sec"
            ),
            "parity": replica_scaleout.get("parity"),
            "pre_kill_hit": failover.get("pre_kill_hit_rate"),
            "post_kill_hit": failover.get("post_kill_hit_rate"),
            "dip": failover.get("dip"),
            "within_envelope": failover.get("within_envelope"),
            "slo_state": (failover.get("slo_envelope") or {}).get(
                "state"
            ),
            "trace_overhead": (
                replica_scaleout.get("trace_ab") or {}
            ).get("overhead"),
            # Pipelined read-path A/B (RTT-injected): 3-replica warm
            # multi-turn scores/sec with overlap+pipelining armed, and
            # its p99 as a multiple of the injected RTT.
            "pipelined_sps": (
                (replica_scaleout.get("pipelined_ab") or {}).get(
                    "pipelined_warm"
                )
                or {}
            ).get("scores_per_sec"),
            "p99_rtt": (
                replica_scaleout.get("pipelined_ab") or {}
            ).get("p99_rtt_ratio"),
        }
    compact = {
        "metric": full["metric"],
        "value": full["value"],
        "unit": full.get("unit"),
        "vs_baseline": full.get("vs_baseline"),
        "device": detail.get("device"),
        "routing_precise_us": _round_floats(
            detail.get("routing_precise_us")
        ),
        "read_path": _round_floats(read_path_compact),
        "cache_analytics": _round_floats(cache_analytics_compact),
        "tiered_churn": _round_floats(tiered_churn_compact),
        "scaleout_warmup": _round_floats(scaleout_warmup_compact),
        "host_offload": _round_floats(host_offload_compact),
        "event_storm": _round_floats(event_storm_compact),
        # Passed through un-rounded: the driver-contract test pins
        # this block equal to the detail artifact.
        "indexer_restart": detail.get("indexer_restart"),
        "replica_scaleout": _round_floats(replica_scaleout_compact),
        "elapsed_s": detail.get("elapsed_s"),
        "results": results_path or "WRITE FAILED (stderr has why)",
    }
    if "error" in full:
        compact["error"] = str(full["error"])[:300]
    line = json.dumps(compact)
    # Belt and braces: every field above is small by construction, but
    # the budget is a hard driver contract — shed optional fields
    # before ever printing an oversized last line.
    # Shed order: newest/nice-to-have blocks first.  replica_scaleout
    # and scaleout_warmup go before indexer_restart — the driver-
    # contract test pins indexer_restart's presence on the full tiny
    # run, and the line only fits it after two sheds.
    for key in (
        "replica_scaleout",
        "scaleout_warmup",
        "indexer_restart",
        "event_storm",
        "host_offload",
        "tiered_churn",
        "cache_analytics",
        "read_path",
        "routing_precise_us",
        "results",
    ):
        if len(line) <= HEADLINE_MAX_BYTES:
            break
        compact.pop(key, None)
        line = json.dumps(compact)
    _probe_status_line(probe)
    print(line, flush=True)

import zmq

from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import InMemoryIndex
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    IndexConfig,
    InMemoryIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    EMPTY_BLOCK_HASH,
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockRemoved,
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import Message, Pool, PoolConfig
from llm_d_kv_cache_manager_tpu.metrics.collector import counter_total
from llm_d_kv_cache_manager_tpu.models import llama
from llm_d_kv_cache_manager_tpu.models.kv_cache_pool import (
    KVCachePool,
    KVCachePoolConfig,
)
from llm_d_kv_cache_manager_tpu.native.engine import (
    JobStatus as OffloadJobStatus,
)
from llm_d_kv_cache_manager_tpu.offload.spec import (
    TPUOffloadConnector,
    TPUOffloadSpec,
)
from llm_d_kv_cache_manager_tpu.offload.worker import (
    group_blocks_per_file,
    host_dtype,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import Encoding

MODEL_NAME = "bench/llama"
NUM_PODS = 4
NUM_GROUPS = 8
REQS_PER_GROUP = 6
PREFIX_TOKENS = 8192  # benchmark 1's 8k shared system prompt
SUFFIX_TOKENS = 256
BLOCK_SIZE = 16
TOTAL_TOKENS = PREFIX_TOKENS + SUFFIX_TOKENS

# ~0.75B params + 8k prefix (flash-attention prefill): enough compute
# that prefill — the thing routing saves — dominates both the sub-ms
# routing overhead and the axon tunnel's ~70 ms host-readback RTT, as
# in the reference's fleet where an 8k prefill on a 70B model takes
# seconds (BASELINE.md §1).
CFG = llama.LlamaConfig(
    vocab_size=16384,
    d_model=2048,
    n_layers=16,
    n_heads=16,
    n_kv_heads=8,
    d_ff=5632,
    block_size=BLOCK_SIZE,
    dtype="bfloat16",
)
POOL_BLOCKS = 1536  # per pod: holds 2 groups' working set (precise
# routing assigns NUM_GROUPS/NUM_PODS = 2 groups per pod); reuse evicts
# Churn regime: barely one group's working set (512 prefix blocks +
# 6 requests x 16 suffix blocks = 608), so the allocator wraps and
# evicts constantly.
CHURN_POOL_BLOCKS = 640

# Matrix axes (reference benchmarking/73-capacity: strategy tables over
# a QPS ladder).  Fractions are of the fleet's ideal-routing capacity.
STRATEGIES = ("precise", "estimated", "load", "random", "round_robin")
QPS_FRACTIONS = (0.5, 0.6, 0.7, 0.8, 0.9)
ARRIVAL_SEEDS = (7, 11, 13)

if os.environ.get("KVTPU_BENCH_TINY"):
    # Smoke-run geometry (CI / CPU): same code paths, minutes -> seconds.
    NUM_GROUPS, REQS_PER_GROUP = 4, 4
    PREFIX_TOKENS, SUFFIX_TOKENS = 512, 64
    TOTAL_TOKENS = PREFIX_TOKENS + SUFFIX_TOKENS
    CFG = llama.LlamaConfig(
        vocab_size=2048,
        d_model=256,
        n_layers=2,
        n_heads=8,
        n_kv_heads=4,
        d_ff=704,
        block_size=BLOCK_SIZE,
        dtype="float32",
    )
    POOL_BLOCKS = 160
    CHURN_POOL_BLOCKS = 52  # one tiny group = 32 prefix + 4x4 suffix
    ARRIVAL_SEEDS = (7, 11)


class WordTokenizer:
    """Deterministic whitespace tokenizer (ASCII words -> stable ids)."""

    def type(self) -> str:
        return "bench-word"

    def encode(
        self, prompt: str, model_name: str, add_special_tokens: bool
    ) -> Encoding:
        tokens: List[int] = []
        offsets: List[Tuple[int, int]] = []
        pos = 0
        for word in prompt.split(" "):
            tokens.append(int(word[1:]) if word[0] == "t" else 0)
            offsets.append((pos, pos + len(word)))
            pos += len(word) + 1
        return Encoding(tokens=tokens, offsets=offsets)


def make_prompts(rng: random.Random) -> List[Tuple[int, str, List[int]]]:
    """(group, prompt text, token ids) per request, shuffled arrival."""
    group_prefixes = [
        [rng.randrange(1, CFG.vocab_size) for _ in range(PREFIX_TOKENS)]
        for _ in range(NUM_GROUPS)
    ]
    requests = []
    for group in range(NUM_GROUPS):
        for _ in range(REQS_PER_GROUP):
            suffix = [
                rng.randrange(1, CFG.vocab_size) for _ in range(SUFFIX_TOKENS)
            ]
            tokens = group_prefixes[group] + suffix
            text = " ".join(f"t{t}" for t in tokens)
            requests.append((group, text, tokens))
    rng.shuffle(requests)
    return requests


class SimPod:
    """One simulated serving pod: paged pool + local prefix cache.

    ``with_kv=False`` (matrix runs) keeps the block-allocator and
    prefix-cache bookkeeping but skips the ~1.1 GB device pool — the
    virtual-clock runs never touch the device."""

    def __init__(
        self,
        name: str,
        params=None,
        with_kv: bool = True,
        pool_blocks: int = None,
    ) -> None:
        self.pool_blocks = pool_blocks or POOL_BLOCKS
        self.name = name
        self.params = params
        self.kv = None
        if with_kv:
            self.kv = jnp.zeros(
                (
                    CFG.n_layers,
                    self.pool_blocks,
                    2,
                    CFG.block_size,
                    CFG.n_kv_heads,
                    CFG.head_dim,
                ),
                jnp.bfloat16,
            )
        self._next_block = 0
        # Engine-side prefix cache: chained block hash -> pool block id,
        # plus the reverse map so reuse evicts the old resident.
        self.cached: Dict[int, int] = {}
        self._block_owner: Dict[int, int] = {}
        # Optional eviction journal (tiered_churn parity cell): when a
        # list is attached, alloc() appends every evicted hash in order.
        self.evict_log: Optional[List[int]] = None

    def alloc(self, n: int) -> Tuple[List[int], List[int]]:
        """Bump-allocate n blocks; returns (ids, evicted block hashes).
        Like a real engine, reusing a block evicts whatever prefix block
        lived there — callers must publish the eviction."""
        ids = [
            (self._next_block + i) % self.pool_blocks for i in range(n)
        ]
        self._next_block = (self._next_block + n) % self.pool_blocks
        evicted: List[int] = []
        for bid in ids:
            old = self._block_owner.pop(bid, None)
            if old is not None and self.cached.get(old) == bid:
                del self.cached[old]
                evicted.append(old)
        if self.evict_log is not None:
            self.evict_log.extend(evicted)
        return ids, evicted

    def cached_prefix_blocks(self, block_hashes: Sequence[int]) -> List[int]:
        """Pool ids of the longest cached consecutive prefix."""
        ids: List[int] = []
        for h in block_hashes:
            if h not in self.cached:
                break
            ids.append(self.cached[h])
        return ids


class TieredFleetPolicy:
    """Shared policy state for a tiered_churn predictive run: ONE
    ledger + PolicyFeed across the fleet (the engine-chain analogue of
    the indexer-side wiring — the PolicyFeed contract is key-space
    agnostic, and here the pods' own block-hash chains feed it)."""

    def __init__(self) -> None:
        from llm_d_kv_cache_manager_tpu.analytics.ledger import (
            CacheStatsLedger,
            LedgerConfig,
        )
        from llm_d_kv_cache_manager_tpu.tiering import PolicyFeed

        self.ledger = CacheStatsLedger(LedgerConfig(sample_rate=1.0))
        self.feed = PolicyFeed(ledger=self.ledger)

    def close(self) -> None:
        self.ledger.close()


class TieredSimPod(SimPod):
    """SimPod + the predictive tiering policy at the engine edge.

    Reuse-aware **admission + protection** (the TinyLFU-flavored rule
    from docs/tiering.md): the pod protects one incumbent prefix
    family's blocks from eviction; a challenger family is admitted
    into the cache (registered + advertised) only when the PolicyFeed
    predicts its reuse strictly better (2x shorter expected next use)
    than the incumbent's — otherwise it is served **transiently**:
    blocks are allocated from the unprotected region and never
    registered, so the incumbent's working set survives churn and the
    index is never told about blocks the pod won't keep.

    ``tiering=None`` is the parity oracle: every code path delegates
    to the pristine SimPod behavior, bit-identically (asserted by the
    bench's tiered_churn parity cell).
    """

    # Fraction of the pool the incumbent may pin; the rest stays a
    # churn region so transient requests always progress.
    PROTECT_FRACTION = 0.85

    def __init__(self, *args, tiering: Optional[TieredFleetPolicy] = None,
                 **kw) -> None:
        super().__init__(*args, **kw)
        self.tiering = tiering
        self.protected_ids: set = set()
        self.protected_family: Optional[int] = None
        # Decisions for the in-flight request (prepare_request ->
        # alloc -> commit ride the same virtual-clock step).
        self.register_current = True
        self._pending_protect: Optional[int] = None
        self._protect_cap = int(self.pool_blocks * self.PROTECT_FRACTION)

    # -- per-request policy hooks (called by _fleet_step/commit) --------

    def prepare_request(self, hashes: Sequence[int]) -> None:
        """Record the arrival, then decide admission/protection for
        this request BEFORE account() allocates."""
        if self.tiering is None:
            return
        ledger, feed = self.tiering.ledger, self.tiering.feed
        family = ledger.family_key(hashes, len(hashes))
        matched = len(self.cached_prefix_blocks(hashes))
        ledger.record(family, MODEL_NAME, len(hashes), matched)
        feed.observe_chain(hashes, family)
        self.register_current = True
        self._pending_protect = None
        if self.protected_family is None:
            # No incumbent: this family takes the seat (protection
            # lands on its block ids at commit).
            self._pending_protect = family
        elif family == self.protected_family:
            if matched == 0:
                # Defensive (protected blocks cannot normally be
                # evicted): rebuild protection from this request.
                self.protected_ids.clear()
                self._pending_protect = family
        else:
            challenger = feed.prediction(family)
            incumbent = feed.prediction(self.protected_family)
            now = time.monotonic()
            swap = (
                challenger is not None
                and (
                    incumbent is None
                    or challenger.expected_next_use_s(now) * 2.0
                    < incumbent.expected_next_use_s(now)
                )
            )
            if swap:
                self.protected_ids.clear()
                self._pending_protect = family
            else:
                # Transient service: the incumbent's working set is
                # worth more than caching this request.
                self.register_current = False

    def commit_blocks(self, hashes: Sequence[int],
                      block_ids: Sequence[int]) -> None:
        """Post-registration hook: pin the just-admitted family's
        blocks (up to the protect cap)."""
        if self.tiering is None or self._pending_protect is None:
            return
        self.protected_family = self._pending_protect
        self._pending_protect = None
        room = self._protect_cap - len(self.protected_ids)
        if room > 0:
            self.protected_ids.update(block_ids[:room])

    def alloc(self, n: int) -> Tuple[List[int], List[int]]:
        if self.tiering is None or not self.protected_ids:
            return super().alloc(n)
        # Ring allocation skipping protected ids.  A transient request
        # larger than the unprotected region reuses ids WITHIN itself
        # (real engines serve an over-sized transient request by
        # recycling its own scratch blocks); such requests are never
        # registered, so no stale cache mappings can form.
        ids: List[int] = []
        evicted: List[int] = []
        cursor = self._next_block
        scanned = 0
        while len(ids) < n:
            bid = cursor % self.pool_blocks
            cursor += 1
            scanned += 1
            if bid in self.protected_ids:
                continue
            ids.append(bid)
            old = self._block_owner.pop(bid, None)
            if old is not None and self.cached.get(old) == bid:
                del self.cached[old]
                evicted.append(old)
            if scanned >= self.pool_blocks:
                scanned = 0  # wrapped: continue into duplicates
        self._next_block = cursor % self.pool_blocks
        if self.evict_log is not None:
            self.evict_log.extend(evicted)
        return ids, evicted


def block_hash_chain(tokens: Sequence[int]) -> List[int]:
    """vLLM-style chained block hashes (the engine's own hash config;
    the indexer absorbs any scheme via the engineKey->requestKey map)."""
    import hashlib

    hashes: List[int] = []
    parent = b"root"
    for i in range(0, len(tokens) - len(tokens) % BLOCK_SIZE, BLOCK_SIZE):
        chunk = tokens[i : i + BLOCK_SIZE]
        digest = hashlib.sha256(
            parent + np.asarray(chunk, np.int64).tobytes()
        ).digest()
        hashes.append(int.from_bytes(digest[-8:], "big"))
        parent = digest
    return hashes


def publish_events(
    event_pool: Pool,
    pod: SimPod,
    tokens: Sequence[int],
    block_hashes: Sequence[int],
    first_new: int,
    evicted: Sequence[int],
) -> None:
    """Publish this request's BlockRemoved (pool-block reuse) and
    BlockStored events in order, as the engine would."""
    events = []
    if evicted:
        events.append(BlockRemoved(block_hashes=list(evicted), medium="hbm"))
    if first_new < len(block_hashes):
        events.append(
            BlockStored(
                block_hashes=list(block_hashes[first_new:]),
                parent_block_hash=(
                    block_hashes[first_new - 1] if first_new > 0 else None
                ),
                token_ids=list(tokens[first_new * BLOCK_SIZE :]),
                block_size=BLOCK_SIZE,
                medium="hbm",
            )
        )
    if not events:
        return
    batch = EventBatch(ts=time.time(), events=events)
    event_pool.add_task(
        Message(
            topic=f"kv@{pod.name}@{MODEL_NAME}",
            payload=batch.encode(),
            pod_identifier=pod.name,
            model_name=MODEL_NAME,
        )
    )


class EstimatedScorer:
    """Scheduler-side prefix-affinity approximation (the reference's
    "estimated" strategy, benchmarking/73-capacity/README.md:241-246):
    scores pods by the scheduler's OWN routing history — no engine
    events, so it is blind to evictions and to blocks cached by other
    routes.  The gap between this and "precise" is the product's value
    proposition."""

    def __init__(self, capacity_per_pod: int = 200_000) -> None:
        self.capacity = capacity_per_pod
        self._assumed: Dict[str, Dict[int, None]] = {}

    def pick(self, pod_names: Sequence[str], hashes: Sequence[int]):
        """Pod with the longest assumed consecutive prefix, or None."""
        best, best_len = None, 0
        for name in pod_names:
            assumed = self._assumed.get(name)
            if not assumed:
                continue
            n = 0
            for h in hashes:
                if h not in assumed:
                    break
                n += 1
            if n > best_len:
                best, best_len = name, n
        return best

    def record(self, pod_name: str, hashes: Sequence[int]) -> None:
        assumed = self._assumed.setdefault(pod_name, {})
        for h in hashes:
            assumed.pop(h, None)  # re-insert at LRU tail
            assumed[h] = None
        while len(assumed) > self.capacity:
            assumed.pop(next(iter(assumed)))


class FleetRouter:
    """Routing + engine-cache accounting shared by the real-compute
    headline runs and the virtual-clock matrix cells.  ONE semantics,
    measured two ways — were these duplicated, a fix to one path would
    silently make the headline and the matrix measure different caches.

    Strategies: "precise" runs the real indexer read+write path
    (routing wall time charged to TTFT); "estimated" routes from
    scheduler-local affinity; "load" to the least-backlogged pod;
    "random"/"round_robin" blind.
    """

    def __init__(
        self,
        strategy: str,
        with_kv: bool,
        params=None,
        seed: int = 0,
        pool_blocks: int = None,
        journal=None,
        cache_stats_ledger=None,
        exact_tokenize: bool = False,
        pod_factory=None,
        index_factory=None,
    ) -> None:
        self.strategy = strategy
        # pod_factory(name) lets a regime substitute policy-aware pods
        # (tiered_churn); None keeps the plain SimPod fleet.
        if pod_factory is None:
            def pod_factory(name):
                return SimPod(
                    name, params, with_kv=with_kv, pool_blocks=pool_blocks
                )
        self.pods = [pod_factory(f"pod-{i}") for i in range(NUM_PODS)]
        self.pod_by_name = {p.name: p for p in self.pods}
        self.pod_free_at: Dict[str, float] = {
            p.name: 0.0 for p in self.pods
        }
        self.completions: Dict[str, List[float]] = {
            p.name: [] for p in self.pods
        }
        self._rr = 0
        self._rng = random.Random(31_000 + seed)
        self.indexer = None
        self.event_pool = None
        self.estimated = None
        if strategy == "precise":
            tokenization_config = TokenizationPoolConfig()
            if exact_tokenize:
                # The cache_analytics regime validates the ledger's
                # per-request block counts against engine-side ground
                # truth, so the prefix store's coverage-truncated warm
                # tokenization (which serves slightly fewer tokens than
                # the full prompt) must be off: a ratio above 1.0 makes
                # the fast path unreachable.
                tokenization_config = TokenizationPoolConfig(
                    min_prefix_overlap_ratio=1.01
                )
            self.indexer = Indexer(
                IndexerConfig(
                    token_processor_config=TokenProcessorConfig(
                        block_size=BLOCK_SIZE
                    ),
                    kvblock_index_config=IndexConfig(),
                    tokenizers_pool_config=tokenization_config,
                    cache_stats=cache_stats_ledger is not None,
                ),
                tokenizer=WordTokenizer(),
                cache_stats_ledger=cache_stats_ledger,
                # index_factory() lets a regime substitute a remote
                # backend (replica_scaleout: cluster RemoteIndex); None
                # keeps the config-built in-memory index.
                kv_block_index=(
                    index_factory() if index_factory is not None else None
                ),
            )
            self.indexer.run()
            self.event_pool = Pool(
                self.indexer.kv_block_index,
                self.indexer.token_processor,
                PoolConfig(concurrency=2),
                journal=journal,
            )
            self.event_pool.start()
            # Zero-score fallback affinity (see route()); the index
            # score always overrides it when positive.
            self.estimated = EstimatedScorer()
        elif strategy == "estimated":
            self.estimated = EstimatedScorer()

    def shutdown(self) -> None:
        if self.event_pool is not None:
            self.event_pool.shutdown()
        if self.indexer is not None:
            self.indexer.shutdown()

    def _next_rr(self) -> SimPod:
        pod = self.pods[self._rr % NUM_PODS]
        self._rr += 1
        return pod

    def _affinity(self, hashes: Sequence[int]) -> SimPod:
        """Routing-history affinity (where this prefix last went);
        round-robin for groups never routed before."""
        name = self.estimated.pick([p.name for p in self.pods], hashes)
        return self.pod_by_name[name] if name else self._next_rr()

    def route(
        self, text: str, hashes: Sequence[int]
    ) -> Tuple[SimPod, float]:
        """Pick a pod; returns (pod, routing seconds charged to TTFT)."""
        if self.strategy == "precise":
            t0 = time.perf_counter()
            scores = self.indexer.get_pod_scores(
                text, MODEL_NAME, [p.name for p in self.pods]
            )
            routing_seconds = time.perf_counter() - t0
            if scores and max(scores.values()) > 0:
                pod = self.pod_by_name[
                    max(scores.items(), key=lambda kv: kv[1])[0]
                ]
            else:
                # Zero-score fallback: routing-history affinity, then
                # round-robin for genuinely cold groups.  Under pool
                # churn a prefix's blocks come and go; pure-rr fallback
                # scatters a group across pods (each miss lands
                # somewhere new, evicting yet another group), while
                # affinity keeps the group pinned so its next request
                # can hit whatever survived.  This mirrors llm-d's
                # scorer composition: the precise score breaks ties
                # ABOVE a stable affinity baseline, not above noise.
                pod = self._affinity(hashes)
            return pod, routing_seconds
        if self.strategy == "estimated":
            return self._affinity(hashes), 0.0
        if self.strategy == "load":
            return (
                min(self.pods, key=lambda p: self.pod_free_at[p.name]),
                0.0,
            )
        if self.strategy == "random":
            return self._rng.choice(self.pods), 0.0
        return self._next_rr(), 0.0

    @staticmethod
    def account(
        pod: SimPod, hashes: Sequence[int]
    ) -> Tuple[bool, int, List[int], List[int]]:
        """Engine-side hit check + allocation.  Suffix blocks never
        repeat across requests, so a hit is exactly the shared prefix;
        partial-prefix hits count as misses (single compiled suffix
        shape).  Returns (hit, first_new, block_ids, evicted)."""
        n_prefix_blocks = PREFIX_TOKENS // BLOCK_SIZE
        cached_ids = pod.cached_prefix_blocks(hashes)
        if len(cached_ids) >= n_prefix_blocks:
            new_ids, evicted = pod.alloc(len(hashes) - n_prefix_blocks)
            return (
                True,
                n_prefix_blocks,
                cached_ids[:n_prefix_blocks] + new_ids,
                evicted,
            )
        new_ids, evicted = pod.alloc(len(hashes))
        return False, 0, new_ids, evicted

    def commit(
        self,
        pod: SimPod,
        tokens: Sequence[int],
        hashes: Sequence[int],
        first_new: int,
        block_ids: Sequence[int],
        evicted: Sequence[int],
    ) -> None:
        """Register ONLY newly-written blocks: re-registering a hit
        prefix would resurrect hashes that alloc() just evicted when
        the allocator wrapped into the cached prefix region, mapping
        them to blocks that now hold suffix KV.  Then feed whichever
        learning mechanism the strategy uses."""
        if not getattr(pod, "register_current", True):
            # Tiering admission control declined this request: the
            # blocks were transient scratch — no cache registration and
            # no BlockStored advertisement (the index must never claim
            # blocks the pod won't keep); evictions still publish.
            first_new = len(hashes)
        else:
            for h, bid in zip(hashes[first_new:], block_ids[first_new:]):
                pod.cached[h] = bid
                pod._block_owner[bid] = h
            protect = getattr(pod, "commit_blocks", None)
            if protect is not None:
                protect(hashes, block_ids)
        if self.event_pool is not None:
            publish_events(
                self.event_pool, pod, tokens, hashes, first_new, evicted
            )
            self.event_pool.drain()  # index learns before next arrival
        if self.estimated is not None:
            # Both the estimated strategy and precise's zero-score
            # fallback learn from routing history.
            self.estimated.record(pod.name, hashes)


def run_fleet_virtual(
    strategy: str,
    requests,
    hashes_list: Sequence[Sequence[int]],
    arrivals: Sequence[float],
    t_miss: float,
    t_hit: float,
    seed: int,
    pool_blocks: int = None,
    reset_history_at: Optional[int] = None,
    cache_stats_ledger=None,
    exact_tokenize: bool = False,
    pod_factory=None,
) -> Tuple[List[float], float, float, List[float]]:
    """One matrix cell: the request stream under ``strategy`` on the
    virtual clock, service times taken from the measured on-device
    prefill costs.  Returns (TTFTs, hit rate, mean queue depth,
    per-request routing seconds).

    ``reset_history_at``: request index at which the scheduler
    "restarts" — scheduler-local routing history is wiped, while the
    indexer (a separate service continuously fed by engine events)
    survives.  The reference architecture's core pitch: cache truth
    lives in the shared index, not in any scheduler's memory.
    """
    fleet = FleetRouter(
        strategy,
        with_kv=False,
        seed=seed,
        pool_blocks=pool_blocks,
        cache_stats_ledger=cache_stats_ledger,
        exact_tokenize=exact_tokenize,
        pod_factory=pod_factory,
    )
    ttfts: List[float] = []
    depths: List[int] = []
    routings: List[float] = []
    hits = 0
    try:
        for i, (request, hashes, arrival) in enumerate(
            zip(requests, hashes_list, arrivals)
        ):
            if i == reset_history_at and fleet.estimated is not None:
                fleet.estimated = EstimatedScorer()
            ttft, hit, depth, routing_seconds = _fleet_step(
                fleet, request, hashes, arrival, t_miss, t_hit
            )
            ttfts.append(ttft)
            hits += hit
            depths.append(depth)
            routings.append(routing_seconds)
    finally:
        fleet.shutdown()
    return ttfts, hits / len(requests), float(np.mean(depths)), routings


def _fleet_step(
    fleet: FleetRouter,
    request,
    hashes: Sequence[int],
    arrival: float,
    t_miss: float,
    t_hit: float,
) -> Tuple[float, bool, int, float]:
    """One request through route -> account -> FIFO queue -> commit on
    the virtual clock; returns (ttft, hit, queue depth at arrival,
    routing seconds).  Shared by the matrix cells and the
    indexer_restart regime — one semantics, per the FleetRouter
    contract."""
    group, text, tokens = request
    pod, routing_seconds = fleet.route(text, hashes)
    prepare = getattr(pod, "prepare_request", None)
    if prepare is not None:
        # Tiering policy hook (TieredSimPod): record the arrival and
        # decide admission/protection before account() allocates.
        prepare(hashes)
    hit, first_new, block_ids, evicted = fleet.account(pod, hashes)
    service_seconds = t_hit if hit else t_miss
    depth = sum(1 for c in fleet.completions[pod.name] if c > arrival)
    queue_start = max(arrival, fleet.pod_free_at[pod.name])
    done = queue_start + service_seconds
    fleet.pod_free_at[pod.name] = done
    fleet.completions[pod.name].append(done)
    fleet.commit(pod, tokens, hashes, first_new, block_ids, evicted)
    return (
        routing_seconds + (queue_start - arrival) + service_seconds,
        hit,
        depth,
        routing_seconds,
    )


def bench_indexer_restart(
    requests, hashes_list, t_miss: float, t_hit: float,
    ideal_service: float,
) -> dict:
    """Cold vs warm-recovered routing across an INDEXER restart.

    The ``restart`` matrix workload already prices losing scheduler
    history while the index survives; this regime prices losing the
    INDEX itself.  First half of the stream runs precise routing with
    the persistence journal tapped in and a snapshot published at the
    cut; then the indexer "restarts" — fresh Indexer, fresh index —
    while the engine pods keep their caches (pods did not restart).
    The second half runs twice from identical pod state: cold (empty
    index, the status quo before persistence/) and warm (snapshot +
    journal-tail recovery).  Device-free: only hit rates are compared,
    so no service-time measurement is needed.
    """
    import copy
    import tempfile

    from llm_d_kv_cache_manager_tpu.persistence import (
        PersistenceConfig,
        PersistenceManager,
        recover,
    )

    n = len(requests)
    half = n // 2
    qps = 0.7 * NUM_PODS / ideal_service
    arrivals = poisson_arrivals(qps, n, ARRIVAL_SEEDS[0])
    out: dict = {}
    with tempfile.TemporaryDirectory() as pdir:
        config = PersistenceConfig(directory=pdir)
        manager = PersistenceManager(config)
        fleet = FleetRouter(
            "precise", with_kv=False, seed=0, journal=manager.journal
        )
        try:
            for i in range(half):
                _fleet_step(
                    fleet, requests[i], hashes_list[i], arrivals[i],
                    t_miss, t_hit,
                )
            manager.snapshot(fleet.indexer.kv_block_index)
            saved_pods = copy.deepcopy(fleet.pods)
        finally:
            fleet.shutdown()
            manager.close()

        report = None
        for mode in ("cold", "warm"):
            restarted = FleetRouter("precise", with_kv=False, seed=0)
            # Engine pods survive an indexer restart: transplant their
            # caches; the queue clocks restart at zero.
            restarted.pods = copy.deepcopy(saved_pods)
            restarted.pod_by_name = {p.name: p for p in restarted.pods}
            restarted.pod_free_at = {p.name: 0.0 for p in restarted.pods}
            restarted.completions = {p.name: [] for p in restarted.pods}
            if mode == "warm":
                report = recover(
                    restarted.indexer.kv_block_index, config
                )
            hits = 0
            try:
                for i in range(half, n):
                    _, hit, _, _ = _fleet_step(
                        restarted, requests[i], hashes_list[i],
                        arrivals[i], t_miss, t_hit,
                    )
                    hits += hit
            finally:
                restarted.shutdown()
            out[f"{mode}_hit_rate"] = round(hits / (n - half), 3)
        out["recovered_block_keys"] = report.block_keys_restored
        out["replayed_records"] = report.records_replayed
    return out


def maybe_bench_indexer_restart(
    requests, hashes_list, t_miss, t_hit, ideal_service
) -> dict:
    """bench_indexer_restart under the degrade contract (headline
    reserve), one helper for both emit paths like maybe_bench_micro."""
    if _over_budget(reserve_s=60.0):
        return {"truncated": True}
    _progress("indexer_restart: cold vs warm-recovered routing")
    return bench_indexer_restart(
        requests, hashes_list, t_miss, t_hit, ideal_service
    )


def measure_readback_rtt() -> float:
    """Host->device->host round-trip floor for a trivial readback.

    TTFT sampling ends with an on-device argmax read back to the host;
    on a real TPU VM that costs microseconds, but through a remote
    device tunnel it adds a fixed ~tens-of-ms RPC that is not prefill
    compute.  Subtracting this floor keeps service times (and so the
    queueing model) faithful to what a serving pod would measure
    locally."""
    probe = jnp.arange(8, dtype=jnp.int32)
    int(jnp.sum(probe))  # drain any queued work
    samples = []
    for _ in range(5):
        t0 = time.perf_counter()
        int(jnp.sum(probe))
        samples.append(time.perf_counter() - t0)
    return float(np.median(samples))


def run_fleet(
    scheduler: str,
    requests,
    params,
    prefill_full,
    prefill_suffix,
    arrivals: Sequence[float],
    readback_rtt: float = 0.0,
) -> Tuple[List[float], float, List[float]]:
    """Run the request stream under one scheduler; returns (TTFTs, hit
    rate, per-request routing seconds).  A fresh indexer + event pool +
    pods per run.

    Open-loop load model (the reference's headline regime —
    BASELINE.md §1: Poisson arrivals at fixed QPS against N pods, where
    misrouting makes prefill queues pile up): requests *arrive* at
    ``arrivals[i]`` on a virtual clock; each pod is a FIFO server.  The
    prefill itself runs for real on the device and its measured wall
    time is the service time; queueing is then
    ``start = max(arrival, pod_free_at)`` and
    ``TTFT = routing + (start - arrival) + service``."""
    fleet = FleetRouter(scheduler, with_kv=True, params=params)
    ttfts: List[float] = []
    routings: List[float] = []
    hits = 0
    try:
        for (group, text, tokens), arrival in zip(requests, arrivals):
            hashes = block_hash_chain(tokens)
            pod, routing_seconds = fleet.route(text, hashes)
            routings.append(routing_seconds)
            hit, first_new, block_ids, evicted = fleet.account(
                pod, hashes
            )
            hits += hit
            token_arr = np.asarray(tokens, np.int32)
            table = jnp.asarray([block_ids], jnp.int32)
            service_start = time.perf_counter()
            if hit:
                logits, pod.kv = prefill_suffix(
                    pod.params,
                    jnp.asarray(token_arr[None, PREFIX_TOKENS:]),
                    pod.kv,
                    table,
                )
            else:
                logits, pod.kv = prefill_full(
                    pod.params, jnp.asarray(token_arr[None]), pod.kv, table
                )
            # Service ends when the first sampled token reaches the host
            # (the same on-device argmax + readback both paths).
            int(jnp.argmax(logits[0, -1]))
            service_seconds = max(
                time.perf_counter() - service_start - readback_rtt, 1e-4
            )
            queue_start = max(arrival, fleet.pod_free_at[pod.name])
            fleet.pod_free_at[pod.name] = queue_start + service_seconds
            ttfts.append(
                routing_seconds
                + (queue_start - arrival)
                + service_seconds
            )
            fleet.commit(
                pod, tokens, hashes, first_new, block_ids, evicted
            )
    finally:
        fleet.shutdown()
    return ttfts, hits / len(requests), routings


# ---------------- compute layers (detail.mfu / detail.kernels) ----------

TIMING_CHAIN_STEPS = 24

# The Pallas decode kernel is routed over the XLA gather only when it
# wins by at least this factor at every measured serving shape — a
# within-noise margin (r4: 1.09x) must not flip the default.
DECODE_ROUTE_MIN_SPEEDUP = 1.3


def time_chained(op, operand, readback_rtt: float = 0.0,
                 steps: int = TIMING_CHAIN_STEPS) -> float:
    """Compiled per-call latency through the remote-device tunnel.

    ``block_until_ready`` is a no-op through the tunnel, so single-shot
    timings are ~all RPC noise.  Instead: chain ``steps`` data-dependent
    calls inside ONE jitted scan (the 1e-30-scaled feedback keeps the
    value numerically unchanged while defeating constant folding), read
    back once, subtract the measured readback floor, divide.
    """
    def chain(x):
        def body(xc, _):
            out = op(xc)
            return xc + (1e-30 * out).astype(xc.dtype), None
        xf, _ = jax.lax.scan(body, x, None, length=steps)
        return xf

    chained = jax.jit(chain)
    float(jnp.sum(chained(operand)))  # compile + warm
    best = float("inf")
    for _ in range(3):  # min-of-3 bounds the RTT jitter contribution
        t0 = time.perf_counter()
        float(jnp.sum(chained(operand)))
        best = min(best, time.perf_counter() - t0)
    return max(best - readback_rtt, 1e-6) / steps


def max_rel_err(a, b) -> float:
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    return float(np.max(np.abs(a - b)) / (np.max(np.abs(b)) + 1e-6))


def bench_kernels(readback_rtt: float) -> dict:
    """detail.kernels: Pallas vs XLA compiled at serving shapes.

    Equality is asserted at bench time (a wrong-but-fast kernel must
    fail the bench, not win it); the decode winner is routed into the
    headline runs via ``LlamaConfig.decode_attention``.
    """
    if jax.default_backend() != "tpu":
        return {"skipped": f"backend={jax.default_backend()}"}
    from llm_d_kv_cache_manager_tpu.ops import flash_pallas
    from llm_d_kv_cache_manager_tpu.ops.attention import (
        causal_gqa_attention,
    )
    from llm_d_kv_cache_manager_tpu.ops.flash_attention import (
        flash_gqa_attention,
    )
    from llm_d_kv_cache_manager_tpu.ops.paged_attention import (
        paged_attention,
    )
    from llm_d_kv_cache_manager_tpu.ops.paged_decode_pallas import (
        paged_decode_attention_pallas,
    )

    H, Hkv, Dh = CFG.n_heads, CFG.n_kv_heads, CFG.head_dim
    nblocks = TOTAL_TOKENS // BLOCK_SIZE
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    kv_layer = jax.random.normal(
        k1, (POOL_BLOCKS, 2, BLOCK_SIZE, Hkv, Dh), jnp.bfloat16
    )

    def decode_operands(B):
        q = jax.random.normal(k2, (B, H, Dh), jnp.bfloat16)
        table = jnp.asarray(
            np.stack(
                [
                    np.random.RandomState(7 + i).permutation(
                        POOL_BLOCKS
                    )[:nblocks]
                    for i in range(B)
                ]
            ),
            jnp.int32,
        )
        ctx = jnp.full((B,), TOTAL_TOKENS, jnp.int32)
        return q, table, ctx

    # Decode is sub-ms per call: long chains lift the measurement well
    # above the tunnel's RTT jitter.  Sweep the kernel's blocks-per-
    # step tile at the primary shape (r3 review: BLOCKS_PER_STEP=4 was
    # tuned by anecdote); every candidate must pass the equality gate
    # before it may win.
    B_PRIMARY, B_WIDE = 4, 16  # the fleet's and a loaded serving batch
    q, table, ctx = decode_operands(B_PRIMARY)
    xla_out = paged_attention(q, kv_layer, table, ctx)
    sweep = {}
    best_p, t_decode_pallas, decode_err = None, float("inf"), 1.0
    for blocks_per_step in (2, 4, 8):
        err = max_rel_err(
            paged_decode_attention_pallas(
                q, kv_layer, table, ctx,
                blocks_per_step=blocks_per_step,
            ),
            xla_out,
        )
        assert err < 0.05, (
            f"paged-decode Pallas (P={blocks_per_step}) diverges from "
            f"XLA: max rel err {err:.4f}"
        )
        t = time_chained(
            lambda qq, p=blocks_per_step: paged_decode_attention_pallas(
                qq, kv_layer, table, ctx, blocks_per_step=p
            ),
            q,
            readback_rtt,
            steps=96,
        )
        sweep[f"P{blocks_per_step}_us"] = round(t * 1e6, 1)
        if t < t_decode_pallas:
            best_p, t_decode_pallas, decode_err = blocks_per_step, t, err
    # bf16-operand (mxu_native) dot variant at the winning tile: skips
    # the f32 upcast of K/V in VMEM.  Purely an optional speed variant:
    # failing the equality gate makes it INELIGIBLE (noted in the
    # sweep), never a bench abort — unlike the P-sweep asserts above,
    # which gate the default kernel's correctness.
    mxu_native = False
    t_pallas_f32, err_f32 = t_decode_pallas, decode_err
    err = max_rel_err(
        paged_decode_attention_pallas(
            q, kv_layer, table, ctx,
            blocks_per_step=best_p, mxu_native=True,
        ),
        xla_out,
    )
    if err < 0.05:
        t = time_chained(
            lambda qq: paged_decode_attention_pallas(
                qq, kv_layer, table, ctx,
                blocks_per_step=best_p, mxu_native=True,
            ),
            q,
            readback_rtt,
            steps=96,
        )
        sweep[f"P{best_p}_bf16_us"] = round(t * 1e6, 1)
        if t < t_decode_pallas:
            mxu_native, t_decode_pallas, decode_err = True, t, err
    else:
        sweep[f"P{best_p}_bf16_us"] = f"ineligible: rel err {err:.4f}"
    t_decode_xla = time_chained(
        lambda qq: paged_attention(qq, kv_layer, table, ctx),
        q,
        readback_rtt,
        steps=96,
    )

    # Second serving shape: the B=4 winner config re-measured at a
    # loaded batch, so the routing decision holds across shapes
    # instead of being a one-point anecdote.
    q_w, table_w, ctx_w = decode_operands(B_WIDE)
    xla_out_w = paged_attention(q_w, kv_layer, table_w, ctx_w)
    err_w = max_rel_err(
        paged_decode_attention_pallas(
            q_w, kv_layer, table_w, ctx_w,
            blocks_per_step=best_p, mxu_native=mxu_native,
        ),
        xla_out_w,
    )
    if mxu_native and err_w >= 0.05:
        # The optional bf16-operand variant must hold at EVERY shape;
        # diverging here demotes it (ineligible, never a bench abort —
        # same policy as the primary-shape gate) and reverts the
        # primary timing to the f32-upcast winner.
        sweep[f"P{best_p}_bf16_wide"] = (
            f"ineligible at B={B_WIDE}: rel err {err_w:.4f}"
        )
        mxu_native = False
        t_decode_pallas, decode_err = t_pallas_f32, err_f32
        err_w = max_rel_err(
            paged_decode_attention_pallas(
                q_w, kv_layer, table_w, ctx_w,
                blocks_per_step=best_p, mxu_native=False,
            ),
            xla_out_w,
        )
    assert err_w < 0.05, (
        f"paged-decode Pallas diverges at B={B_WIDE}: {err_w:.4f}"
    )
    t_pallas_w = time_chained(
        lambda qq: paged_decode_attention_pallas(
            qq, kv_layer, table_w, ctx_w,
            blocks_per_step=best_p, mxu_native=mxu_native,
        ),
        q_w,
        readback_rtt,
        steps=96,
    )
    t_xla_w = time_chained(
        lambda qq: paged_attention(qq, kv_layer, table_w, ctx_w),
        q_w,
        readback_rtt,
        steps=96,
    )

    # Routing rule (r4 verdict: a 1.09x margin is within noise of not
    # mattering): the Pallas kernel is routed only when it beats the
    # XLA gather by >= DECODE_ROUTE_MIN_SPEEDUP at EVERY measured
    # serving shape; otherwise the gather is the honest default.
    speedups = (
        t_decode_xla / t_decode_pallas,
        t_xla_w / t_pallas_w,
    )
    decode_winner = (
        "pallas"
        if min(speedups) >= DECODE_ROUTE_MIN_SPEEDUP
        else "gather"
    )

    # detail.kernels.ring: per-ring-step cost, einsum body vs the
    # mask-aware flash partial (ops/ring_flash_pallas.py).  A single
    # chip cannot run a real multi-device ring, but the ring's
    # wall-clock is R x (per-step body + overlapped permute), so the
    # step bodies ARE the comparison: the striped layout's win is
    # exactly flash_causal_step vs einsum_step on every device at
    # every step.
    from llm_d_kv_cache_manager_tpu.ops.ring_flash_pallas import (
        flash_partial,
        normalize_partial,
    )

    RING = 4  # a 4-chip pod-slice ring over the 8k prefill
    T_local = PREFIX_TOKENS // RING
    qr = jax.random.normal(k2, (1, T_local, H, Dh), jnp.bfloat16)
    kr = jax.random.normal(k3, (1, T_local, Hkv, Dh), jnp.bfloat16)
    vr = jax.random.normal(k1, (1, T_local, Hkv, Dh), jnp.bfloat16)

    def einsum_step(qq):
        """One ring step in the einsum body (diagonal/causal step):
        the dense op the ring's where()-masked einsum path pays per
        step regardless of the mask (ops/attention.py — the SAME math
        _ring_attention_local inlines, via the shared helper so the
        reference cannot drift)."""
        return causal_gqa_attention(qq, kr, vr)

    # Equality gate first: the flash causal partial must agree with
    # the einsum body's softmax before its time may count.
    acc, _, l = flash_partial(qr, kr, vr, causal_offset=0)
    ring_err = max_rel_err(
        normalize_partial(acc, l, qr.dtype), einsum_step(qr)
    )
    assert ring_err < 0.05, (
        f"ring flash partial diverges from einsum body: {ring_err:.4f}"
    )

    t_ring_einsum = time_chained(einsum_step, qr, readback_rtt, steps=8)
    t_ring_flash_causal = time_chained(
        lambda qq: flash_partial(qq, kr, vr, causal_offset=0)[0].astype(
            qq.dtype
        ),
        qr,
        readback_rtt,
        steps=8,
    )
    t_ring_flash_full = time_chained(
        lambda qq: flash_partial(
            qq, kr, vr, causal_offset=None
        )[0].astype(qq.dtype),
        qr,
        readback_rtt,
        steps=8,
    )

    Tq = PREFIX_TOKENS  # the 8k shared-prefix prefill shape
    qp = jax.random.normal(k3, (1, Tq, H, Dh), jnp.bfloat16)
    kp = jax.random.normal(k1, (1, Tq, Hkv, Dh), jnp.bfloat16)
    vp = jax.random.normal(k2, (1, Tq, Hkv, Dh), jnp.bfloat16)
    flash_err = max_rel_err(
        flash_pallas.flash_gqa_attention_pallas(qp, kp, vp),
        flash_gqa_attention(qp, kp, vp),
    )
    assert flash_err < 0.05, (
        f"flash-prefill Pallas/XLA diverge: max rel err {flash_err:.4f}"
    )
    t_flash_pallas = time_chained(
        lambda qq: flash_pallas.flash_gqa_attention_pallas(qq, kp, vp),
        qp,
        readback_rtt,
    )
    t_flash_xla = time_chained(
        lambda qq: flash_gqa_attention(qq, kp, vp), qp, readback_rtt
    )
    return {
        "paged_decode": {
            "shape": f"B={B_PRIMARY} ctx={TOTAL_TOKENS} blocks={nblocks}",
            "pallas_us": round(t_decode_pallas * 1e6, 1),
            "xla_gather_us": round(t_decode_xla * 1e6, 1),
            "speedup_pallas": round(t_decode_xla / t_decode_pallas, 2),
            "wide_shape": f"B={B_WIDE} ctx={TOTAL_TOKENS}",
            "wide_pallas_us": round(t_pallas_w * 1e6, 1),
            "wide_xla_gather_us": round(t_xla_w * 1e6, 1),
            "wide_speedup_pallas": round(t_xla_w / t_pallas_w, 2),
            "max_rel_err": round(decode_err, 5),
            "winner": decode_winner,
            "route_rule": (
                f"pallas iff speedup >= {DECODE_ROUTE_MIN_SPEEDUP} at "
                "every measured shape"
            ),
            "blocks_per_step_sweep": sweep,
            "blocks_per_step": best_p,
            "mxu_native": mxu_native,
        },
        "flash_prefill": {
            "shape": f"B=1 T={Tq} H={H} D={Dh}",
            "pallas_ms": round(t_flash_pallas * 1e3, 2),
            "xla_scan_ms": round(t_flash_xla * 1e3, 2),
            "speedup_pallas": round(t_flash_xla / t_flash_pallas, 2),
            "max_rel_err": round(flash_err, 5),
        },
        "ring": {
            # Ring wall-clock ~= R x per-step body (permutes overlap),
            # so the step bodies carry the comparison: a striped flash
            # ring costs ~R x causal_step on every device; the einsum
            # ring costs ~R x einsum_step.
            "shape": (
                f"ring={RING} T_local={T_local} H={H} "
                f"Hkv={Hkv} D={Dh}"
            ),
            "einsum_step_ms": round(t_ring_einsum * 1e3, 2),
            "flash_causal_step_ms": round(
                t_ring_flash_causal * 1e3, 2
            ),
            "flash_full_step_ms": round(t_ring_flash_full * 1e3, 2),
            "striped_flash_vs_einsum": round(
                t_ring_einsum / t_ring_flash_causal, 2
            ),
            "max_rel_err": round(ring_err, 5),
        },
    }


def model_prefill_flops(T: int) -> float:
    """Matmul FLOPs of one dense prefill forward (causal-halved attn)."""
    D, H, Hkv, Dh, F, L, V = (
        CFG.d_model,
        CFG.n_heads,
        CFG.n_kv_heads,
        CFG.head_dim,
        CFG.d_ff,
        CFG.n_layers,
        CFG.vocab_size,
    )
    per_layer = (
        2 * T * D * (H * Dh + 2 * Hkv * Dh)  # qkv projections
        + 2 * T * T * H * Dh  # QK^T + AV, x2 flops, /2 causal
        + 2 * T * H * Dh * D  # output projection
        + 2 * T * 3 * D * F  # gate/up/down
    )
    return float(L * per_layer + 2 * T * D * V)  # + logits head


# device_kind substrings -> peak dense bf16 TFLOP/s per chip (public
# figures; v5p before v5 so the substring match is unambiguous).
PEAK_BF16_TFLOPS = (
    ("v6", 918.0),
    ("trillium", 918.0),
    ("v5p", 459.0),
    ("v5", 197.0),  # v5e / v5 lite
    ("v4", 275.0),
)


def bench_mfu(t_miss: float) -> dict:
    """detail.mfu: measured full-prefill throughput vs chip peak."""
    device = jax.devices()[0]
    kind = device.device_kind.lower()
    peak = next(
        (tf for tag, tf in PEAK_BF16_TFLOPS if tag in kind), None
    )
    flops = model_prefill_flops(TOTAL_TOKENS)
    achieved_tflops = flops / t_miss / 1e12
    return {
        "prefill_tokens": TOTAL_TOKENS,
        "prefill_tok_s": round(TOTAL_TOKENS / t_miss, 1),
        "model_flops_per_prefill": flops,
        "achieved_tflops": round(achieved_tflops, 2),
        "device_kind": device.device_kind,
        "peak_bf16_tflops": peak,
        "mfu": round(achieved_tflops / peak, 4) if peak else None,
    }


def warmup_indexes(requests) -> set:
    """Each group's FIRST arrival: an unavoidable cold miss under ANY
    scheduler (the reference's harness likewise excludes warmup)."""
    seen: set = set()
    warm: set = set()
    for i, (group, _, _) in enumerate(requests):
        if group not in seen:
            seen.add(group)
            warm.add(i)
    return warm


def poisson_arrivals(qps: float, n: int, seed: int) -> List[float]:
    arrival_rng = random.Random(seed)
    clock, out = 0.0, []
    for _ in range(n):
        clock += arrival_rng.expovariate(qps)
        out.append(clock)
    return out


def _matrix_cell(
    strategy,
    qps_frac,
    qps,
    requests,
    hashes_list,
    t_miss,
    t_hit,
    warmup,
    workload="steady",
    pool_blocks=None,
    reset_history_at=None,
) -> dict:
    """One (strategy, qps, workload) cell aggregated over the arrival
    seeds; per-seed values reported raw (no averaging away the spread
    the r3 review called out)."""
    p50s, p90s, depths, hit_rates = [], [], [], []
    for seed in ARRIVAL_SEEDS:
        arrivals = poisson_arrivals(qps, len(requests), seed)
        ttfts, hit_rate, depth, _ = run_fleet_virtual(
            strategy,
            requests,
            hashes_list,
            arrivals,
            t_miss,
            t_hit,
            seed,
            pool_blocks=pool_blocks,
            reset_history_at=reset_history_at,
        )
        steady = [t for i, t in enumerate(ttfts) if i not in warmup]
        p50s.append(round(float(np.percentile(steady, 50)), 4))
        p90s.append(round(float(np.percentile(steady, 90)), 4))
        depths.append(round(depth, 2))
        hit_rates.append(round(hit_rate, 3))
    return {
        "strategy": strategy,
        "workload": workload,
        "qps_frac": qps_frac,
        "qps": round(qps, 2),
        "p50_ttft_s": p50s,
        "p90_ttft_s": p90s,
        "mean_queue_depth": depths,
        "hit_rate": hit_rates,
    }


def run_matrix(
    requests,
    hashes_list,
    t_miss: float,
    t_hit: float,
    ideal_service: float,
    warmup: set,
) -> Tuple[List[dict], bool]:
    """detail.matrix: strategies x QPS ladder x arrival seeds on the
    virtual clock, plus a pool-churn regime at the headline QPS.

    Returns (cells, truncated): past the soft budget the remaining
    cells are dropped and flagged rather than overrunning the driver's
    timeout with the headline unreported."""
    cells: List[dict] = []

    def _out_of_time() -> bool:
        return _over_budget(reserve_s=30.0)

    for frac in QPS_FRACTIONS:
        qps = frac * NUM_PODS / ideal_service
        for strategy in STRATEGIES:
            if _out_of_time():
                return cells, True
            cells.append(
                _matrix_cell(
                    strategy, frac, qps, requests, hashes_list,
                    t_miss, t_hit, warmup,
                )
            )
    # Churn regime: pods hold barely one group's working set, so the
    # allocator wraps and evicts constantly.  This is where "precise"
    # earns its name: BlockRemoved events keep the index truthful about
    # what each pod still holds, while the estimated scorer keeps
    # routing to pods that already evicted the prefix (the reference's
    # precise-vs-estimated gap, benchmarking/73-capacity).
    qps = 0.7 * NUM_PODS / ideal_service
    for strategy in STRATEGIES:
        if _out_of_time():
            return cells, True
        cells.append(
            _matrix_cell(
                strategy, 0.7, qps, requests, hashes_list,
                t_miss, t_hit, warmup,
                workload="churn",
                pool_blocks=CHURN_POOL_BLOCKS,
            )
        )
    # Restart regime: the scheduler loses its routing history halfway
    # through (replica restart / failover).  The index — a separate
    # service continuously rebuilt from engine events — survives, so
    # "precise" recovers instantly while history-only routing pays a
    # cold restart.  This is the architecture's core pitch measured.
    # Only the history-bearing strategies: for load/random/rr the
    # reset is a no-op and the cells would duplicate the steady rows.
    for strategy in ("precise", "estimated"):
        if _out_of_time():
            return cells, True
        cells.append(
            _matrix_cell(
                strategy, 0.7, qps, requests, hashes_list,
                t_miss, t_hit, warmup,
                workload="restart",
                reset_history_at=len(requests) // 2,
            )
        )
    return cells, False


DEVICE_INIT_TIMEOUT_S = _env_float("KVTPU_BENCH_DEVICE_TIMEOUT_S", 900.0)

# Calibrated service times for the no-device fallback: the last
# driver-captured on-chip measurements (BENCH_r03.json detail:
# service_miss_s / service_hit_s — full 8448-token prefill vs 256-token
# suffix continue on the v5e chip).  The virtual-clock matrix is exact
# given service times; with the chip unreachable these keep its cells
# meaningful (and labeled as calibrated, never measured).
CAL_MISS_S = _env_float("KVTPU_BENCH_CAL_MISS_S", 0.1735)
CAL_HIT_S = _env_float("KVTPU_BENCH_CAL_HIT_S", 0.0361)


def require_device() -> Optional[str]:
    """Ensure a usable JAX device WITHOUT risking self-inflicted wedges.

    The tunnel platform's backend init BLOCKS (observed 70-85 min) when
    the remote chip grant is wedged — e.g. by an earlier killed client
    — and then raises UNAVAILABLE.  Waiting out a dead tunnel would eat
    the whole bench budget, so init is probed under a timeout.  Returns
    an error string, or None when the device is usable.

    Probe lifecycle (r4 post-mortem): r4's watchdog probed in a daemon
    thread of THIS process and exited with the init still in flight —
    abandoning a TPU client mid-init is the teardown class suspected of
    perpetuating grant wedges.  The probe now runs in a short-lived
    SUBPROCESS, and a timed-out child is NEVER signaled: killing a
    client that might have just acquired the grant is exactly the
    wedge-creating teardown, so the child is left to finish its init
    and exit cleanly on its own, however long that takes (a reaper
    thread collects it if that happens while the bench still runs).

    * success: the child inits, exits cleanly, releases its grant; the
      parent then performs its own init — guarded by the same timeout
      in a watchdog thread, so a grant that wedges in the window
      between the child's release and the parent's acquire degrades to
      the CPU fallback instead of blocking the bench for 70-85 min.
      (If THAT fires, the process will eventually exit with the init
      thread still blocked — unavoidable for an in-process init, and
      benign by the same argument as above: a blocked waiter holds no
      grant, and the wedge it waits on pre-exists our teardown.)
    * failure: the child's exception is captured from its stderr file.
    * timeout: the child is left running, unsignaled; the parent's own
      backend stays untouched for the CPU fallback.

    Healthy-tunnel cost: two backend inits (probe + parent), a few
    seconds each — paid once, inside the overall budget.

    ``KVTPU_BENCH_FORCE_DEVICE_ERROR`` short-circuits straight to the
    error path (driver-contract tests simulate a wedged tunnel).
    """
    import subprocess
    import tempfile
    import threading

    forced = os.environ.get("KVTPU_BENCH_FORCE_DEVICE_ERROR")
    if forced:
        return f"forced by KVTPU_BENCH_FORCE_DEVICE_ERROR: {forced}"
    if os.environ.get("KVTPU_BENCH_PLATFORM") == "cpu":
        # Explicit CPU run (CI smoke / contract tests): init in-process,
        # instant, no tunnel involved.
        try:
            jax.devices()
            return None
        except Exception as exc:  # noqa: BLE001 - report any init error
            return repr(exc)
    # The child must select the SAME backend the parent will init:
    # KVTPU_BENCH_PLATFORM is applied via jax.config at parent import
    # (top of this file), which a bare child would not replay.  The
    # replay must itself go through jax.config, not JAX_PLATFORMS: a
    # sitecustomize that calls jax.config at interpreter start beats
    # env at backend init (tests/conftest.py documents the same), so
    # an env-only override would leave the child probing the
    # sitecustomize's platform while the parent inits the configured
    # one.
    platform = os.environ.get("KVTPU_BENCH_PLATFORM")
    probe_code = "import jax; "
    if platform:
        probe_code += f"jax.config.update('jax_platforms', {platform!r}); "
    probe_code += "jax.devices()"
    # stderr to a file, not a pipe: a pipe nobody drains can fill and
    # block the child mid-init — indistinguishable from a wedge.
    with tempfile.TemporaryFile(mode="w+") as err_file:
        probe = subprocess.Popen(
            [sys.executable, "-c", probe_code],
            stdout=subprocess.DEVNULL,
            stderr=err_file,
        )
        probe_timeout = max(
            30.0,
            min(DEVICE_INIT_TIMEOUT_S, _BUDGET_S - _elapsed() - 300.0),
        )
        try:
            probe.wait(timeout=probe_timeout)
        except subprocess.TimeoutExpired:
            # Do NOT signal the child (see docstring); reap it in the
            # background if it ever finishes.
            threading.Thread(target=probe.wait, daemon=True).start()
            return (
                f"device init still blocked after "
                f"{probe_timeout:.0f}s (probe left to finish "
                "on its own, never signaled)"
            )
        if probe.returncode != 0:
            err_file.seek(0)
            lines = [
                ln for ln in err_file.read().strip().splitlines() if ln
            ]
            tail = lines[-1][:300] if lines else ""
            return (
                f"device init failed in probe "
                f"(rc={probe.returncode}): {tail}"
            )
    # Probe succeeded: the parent's own init should now be quick, but
    # the grant can wedge in the release->acquire window; guard it.
    result: Dict[str, object] = {}

    def init() -> None:
        try:
            result["devices"] = jax.devices()
        except Exception as exc:  # noqa: BLE001 - report any init error
            result["error"] = repr(exc)

    # Bounded by REMAINING budget (minus a reserve for the fallback
    # layers): probe + post-probe waits must never stack to 2x the
    # device timeout and push first output past the driver's timeout —
    # that would get the process killed with the init thread still
    # blocked, the exact teardown class this function exists to avoid.
    post_probe_timeout = max(
        30.0, min(DEVICE_INIT_TIMEOUT_S, _BUDGET_S - _elapsed() - 120.0)
    )
    thread = threading.Thread(target=init, daemon=True)
    thread.start()
    thread.join(post_probe_timeout)
    if "devices" in result:
        return None
    return str(
        result.get(
            "error",
            f"post-probe init still blocked after "
            f"{post_probe_timeout:.0f}s (probe had succeeded; "
            "grant wedged in the release->acquire window)",
        )
    )


def make_workload() -> Tuple[list, set, List[List[int]]]:
    """The ONE workload both the measured path and the CPU fallback
    run: seeded prompts, warmup (first arrival per group), per-request
    hash chains.  Shared so fallback matrix cells stay comparable to
    measured ones."""
    requests = make_prompts(random.Random(0))
    warmup_idx = warmup_indexes(requests)
    hashes_list = [block_hash_chain(tokens) for _, _, tokens in requests]
    return requests, warmup_idx, hashes_list


def ideal_service_time(
    t_miss: float, t_hit: float, n_requests: int
) -> float:
    """Mean service time under IDEAL routing: the first request per
    group misses, every other hits.  Shared by both paths — were it
    duplicated, a change in main() would silently run the fallback
    matrix at a different effective QPS fraction."""
    miss_fraction = NUM_GROUPS / n_requests
    return miss_fraction * t_miss + (1 - miss_fraction) * t_hit


def measure_routing_micro(
    requests, hashes_list, warmup: set
) -> List[float]:
    """Steady-state scoring-RPC latency samples (tokenize -> chained
    hashes -> index lookup -> tier-weighted score), device-free.

    One precise pass of the SAME fleet loop the matrix cells run
    (run_fleet_virtual — one semantics, per the FleetRouter contract);
    the virtual clock is irrelevant here, so arrivals are all zero."""
    _, _, _, routings = run_fleet_virtual(
        "precise",
        requests,
        hashes_list,
        [0.0] * len(requests),
        CAL_MISS_S,
        CAL_HIT_S,
        seed=0,
    )
    return [r for i, r in enumerate(routings) if i not in warmup]


def bench_micro() -> dict:
    """detail.micro: index + tokenization-path microbenches (reference
    tests/profiling/kv_cache_index/index_benchmark_test.go:97-197 and
    the tokenization make-bench) — device-free, so they are always
    emittable, chip or no chip."""
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
        ChunkedTokenDatabase,
        EMPTY_BLOCK_HASH,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
        InMemoryIndex,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
        InMemoryIndexConfig,
        PodEntry,
    )

    rng = random.Random(97)
    # Token->key chain: the per-request hashing cost at the headline's
    # prompt length.
    db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=BLOCK_SIZE))
    tokens = [rng.randrange(1, 16384) for _ in range(TOTAL_TOKENS)]
    db.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, tokens, MODEL_NAME)  # warm
    reps, t0 = 0, time.perf_counter()
    while time.perf_counter() - t0 < 0.5:
        keys = db.tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, tokens, MODEL_NAME
        )
        reps += 1
    hash_elapsed = time.perf_counter() - t0
    # Index add + chain lookup at the reference microbench scale.
    # Fixtures (key lists, PodEntry objects) are built OUTSIDE the
    # timed region so the number measures the index, not allocation
    # of throwaway arguments (Go microbench fixture-setup discipline).
    n_keys = 10_000
    index = InMemoryIndex(InMemoryIndexConfig(size=n_keys * 2))
    idx_keys = [rng.getrandbits(64) for _ in range(n_keys)]
    key_lists = [[key] for key in idx_keys]
    pod_entries = [
        [PodEntry(f"pod-{i}", "hbm")] for i in range(NUM_PODS)
    ]
    t0 = time.perf_counter()
    for i, key_list in enumerate(key_lists):
        index.add(key_list, key_list, pod_entries[i % NUM_PODS])
    add_elapsed = time.perf_counter() - t0
    chain = len(keys)
    lookups, t0 = 0, time.perf_counter()
    for offset in range(0, n_keys - chain, chain):
        index.lookup(idx_keys[offset:offset + chain], None)
        lookups += 1
    lookup_elapsed = time.perf_counter() - t0
    return {
        "hash_chain_tok_s": round(reps * TOTAL_TOKENS / hash_elapsed, 0),
        "index_add_us_per_key": round(1e6 * add_elapsed / n_keys, 2),
        "index_lookup_us_per_chain": round(
            1e6 * lookup_elapsed / max(lookups, 1), 1
        ),
        "index_keys": n_keys,
        "chain_len": chain,
    }


def maybe_bench_micro(context: str) -> dict:
    """bench_micro under the degrade contract: skipped + marked past
    the budget.  One helper for both emit paths so the sentinel shape
    and reserve stay in lockstep."""
    if _over_budget(reserve_s=60.0):
        return {"truncated": True}
    _progress(f"{context}: index/tokenization microbenches")
    return bench_micro()


READ_PATH_CELL_S = _env_float("KVTPU_BENCH_READPATH_S", 1.2)
ANALYTICS_CELL_S = _env_float("KVTPU_BENCH_ANALYTICS_S", 1.2)


def bench_read_path(cell_seconds: Optional[float] = None) -> dict:
    """detail.read_path regime: per-request scoring throughput/latency
    through the REAL indexer read path (tokenize -> hash -> lookup ->
    score), device-free.

    Three workloads: "warm_multi_turn" (a conversation whose growing
    prefix is resident on two pods — the memoized-suffix-hashing case),
    "cold" (8k prompts the index has never seen — the early-exit case),
    and "mixed" (alternating).  Each also runs with the fast lane OFF
    (READ_PATH_FAST_LANE semantics via IndexerConfig) — the straight
    pre-fast-lane path over the same data — and a parity check asserts
    identical scores both ways, because the fast lane must never change
    routing decisions (docs/performance.md)."""
    cell_s = READ_PATH_CELL_S if cell_seconds is None else cell_seconds
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import PodEntry

    rng = random.Random(171)
    pods = [f"pod-{i}" for i in range(NUM_PODS)]

    def new_indexer(fast: bool, score_memo: bool = True) -> Indexer:
        indexer = Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(
                    block_size=BLOCK_SIZE
                ),
                kvblock_index_config=IndexConfig(),
                read_path_fast_lane=fast,
                score_memo_size=None if score_memo else 0,
            ),
            tokenizer=WordTokenizer(),
        )
        indexer.run()
        return indexer

    # One conversation: an 8k base prefix plus 8 turns of 256-token
    # suffixes.  Scoring request t sees the whole conversation so far.
    convo = [rng.randrange(1, 16384) for _ in range(PREFIX_TOKENS)]
    turns: List[str] = []
    for _ in range(8):
        convo.extend(
            rng.randrange(1, 16384) for _ in range(SUFFIX_TOKENS)
        )
        turns.append(" ".join(f"t{t}" for t in convo))
    cold_prompts = [
        " ".join(
            f"t{rng.randrange(1, 16384)}" for _ in range(PREFIX_TOKENS)
        )
        for _ in range(24)
    ]
    mixed = [p for pair in zip(turns * 3, cold_prompts) for p in pair]

    def seed(indexer: Indexer) -> None:
        keys = indexer.token_processor.tokens_to_kv_block_keys(
            0, convo, MODEL_NAME
        )
        indexer.kv_block_index.add(keys, keys, [PodEntry("pod-0", "hbm")])
        indexer.kv_block_index.add(keys, keys, [PodEntry("pod-1", "host")])

    def run_cell(indexer: Indexer, prompts: List[str]) -> dict:
        # One warm pass populates the tokenization prefix store, so the
        # cell measures steady-state scoring, not first-touch encodes.
        for prompt in prompts:
            indexer.get_pod_scores(prompt, MODEL_NAME, pods)
        latencies: List[float] = []
        deadline = time.perf_counter() + cell_s
        i = 0
        while time.perf_counter() < deadline:
            prompt = prompts[i % len(prompts)]
            t0 = time.perf_counter()
            indexer.get_pod_scores(prompt, MODEL_NAME, pods)
            latencies.append(time.perf_counter() - t0)
            i += 1
        total = sum(latencies)
        return {
            "scores_per_sec": (
                round(len(latencies) / total, 1) if total else 0.0
            ),
            "p50_us": round(float(np.percentile(latencies, 50)) * 1e6, 1),
            "p99_us": round(float(np.percentile(latencies, 99)) * 1e6, 1),
            "requests": len(latencies),
        }

    fast = new_indexer(True)
    off = new_indexer(False)
    # Three lanes: the full fast lane (score memo included — the
    # steady-state production path), the fast lane without the score
    # memo (isolates incremental hashing + early exit; also the honest
    # "cold" lane, since the memo would turn the repeating cold prompt
    # set into exact-repeat hits), and the straight pre-fast-lane path.
    no_memo = new_indexer(True, score_memo=False)
    try:
        seed(fast)
        seed(off)
        seed(no_memo)
        parity_ok = True
        for prompt in turns[:3] + cold_prompts[:2] + [turns[-1]]:
            # Two passes, compared ACROSS lanes per pass: the warm
            # (second) pass serves prefix-store-truncated tokens —
            # identically on every lane — so cold-vs-warm would
            # spuriously differ, while each pass must agree across
            # lanes (the memoized lane serves pass 3+ from the score
            # memo; one extra repeat pins that too).
            for _ in range(2):
                on_scores = fast.get_pod_scores(prompt, MODEL_NAME, pods)
                off_scores = off.get_pod_scores(prompt, MODEL_NAME, pods)
                no_memo_scores = no_memo.get_pod_scores(
                    prompt, MODEL_NAME, pods
                )
                if not (on_scores == off_scores == no_memo_scores):
                    parity_ok = False
            if fast.get_pod_scores(prompt, MODEL_NAME, pods) != off_scores:
                parity_ok = False
        result = {
            "warm_multi_turn": run_cell(fast, turns),
            "warm_multi_turn_no_memo": run_cell(no_memo, turns),
            "cold": run_cell(no_memo, cold_prompts),
            "mixed": run_cell(fast, mixed),
            "warm_multi_turn_fastlane_off": run_cell(off, turns),
            "cold_fastlane_off": run_cell(off, cold_prompts),
            "parity": "ok" if parity_ok else "MISMATCH",
            "cell_seconds": cell_s,
            "block_size": BLOCK_SIZE,
            "prefix_tokens": PREFIX_TOKENS,
        }
        warm_on = result["warm_multi_turn"]["scores_per_sec"]
        warm_off = result["warm_multi_turn_fastlane_off"]["scores_per_sec"]
        result["warm_speedup_vs_off"] = (
            round(warm_on / warm_off, 2) if warm_off else None
        )

        # ---- profiler A/B: the always-on sampling profiler's cost to
        # the warm-multi-turn headline at its DEFAULT rate
        # (obs/profiler.py; docs/observability.md).  The profiler adds
        # zero instructions to application threads — its only cost is
        # the sampler thread competing for the GIL — so the bound is a
        # whole-process claim, measured the same alternating best-of
        # way as the trace A/B.
        from llm_d_kv_cache_manager_tpu.obs.profiler import (
            ProfilerConfig,
            SamplingProfiler,
        )

        prof = SamplingProfiler(ProfilerConfig())  # shipped default hz
        best = {True: 0.0, False: 0.0}
        # Best-of-4 with alternating order, exactly like the cluster
        # trace A/B: the signal (a sampler thread's GIL share) is well
        # under run-to-run scheduler noise at shorter settings.
        for ab_round in range(4):
            order = (True, False) if ab_round % 2 == 0 else (False, True)
            for prof_on in order:
                if prof_on:
                    prof.start()
                else:
                    prof.close()
                best[prof_on] = max(
                    best[prof_on],
                    run_cell(fast, turns)["scores_per_sec"],
                )
        top_self = prof.top(8)
        prof.close()
        overhead = (
            max(0.0, (best[False] - best[True]) / best[False])
            if best[False]
            else 0.0
        )
        result["profiler_ab"] = {
            "hz": prof.config.hz,
            "profiler_on_sps": best[True],
            "profiler_off_sps": best[False],
            "overhead": round(overhead, 4),
            "bound": PROFILE_OVERHEAD_BOUND,
            "within_bound": overhead <= PROFILE_OVERHEAD_BOUND,
            "top_self": top_self,
        }

        # ---- capture A/B: the always-on input flight recorder's cost
        # to the warm-multi-turn headline (obs/capture.py; ISSUE 15's
        # ≤3% acceptance bound).  The recorder's hot-path work is one
        # lock hop + a tuple append per scored request (token lists
        # ride by reference, serialization is dump-time only), so the
        # A/B is measured the same alternating best-of-4 way as the
        # profiler's — the signal is well under scheduler noise at
        # shorter settings.
        from llm_d_kv_cache_manager_tpu.obs.capture import (
            CaptureConfig,
            InputCaptureRecorder,
        )

        # Shipped-default config (same reasoning as the event_storm
        # cell: the bound is a claim about production settings).
        recorder = InputCaptureRecorder(CaptureConfig())
        best = {True: 0.0, False: 0.0}
        # Best-of-6 (vs the profiler's 4): the recorder's true cost is
        # ~1% — a single scheduler hiccup on the off side at best-of-4
        # could still read past the 3% bound.
        for ab_round in range(6):
            order = (True, False) if ab_round % 2 == 0 else (False, True)
            for cap_on in order:
                fast.set_capture(recorder if cap_on else None)
                best[cap_on] = max(
                    best[cap_on],
                    run_cell(fast, turns)["scores_per_sec"],
                )
        fast.set_capture(None)
        ring = recorder.status()["sources"]["scores"]
        overhead = (
            max(0.0, (best[False] - best[True]) / best[False])
            if best[False]
            else 0.0
        )
        result["capture_ab"] = {
            "capture_on_sps": best[True],
            "capture_off_sps": best[False],
            "overhead": round(overhead, 4),
            "bound": CAPTURE_OVERHEAD_BOUND,
            "within_bound": overhead <= CAPTURE_OVERHEAD_BOUND,
            "recorded": ring["appended"],
            "ring_bytes": ring["bytes"],
        }
        return result
    finally:
        fast.shutdown()
        off.shutdown()
        no_memo.shutdown()


def maybe_bench_read_path(context: str) -> dict:
    """bench_read_path under the degrade contract (headline first)."""
    if _over_budget(reserve_s=45.0):
        return {"truncated": True}
    _progress(f"{context}: read_path scoring regime")
    return bench_read_path()


# ------------- replica_scaleout: clustered-indexer regime ---------------


SCALEOUT_CELL_S = _env_float("KVTPU_BENCH_SCALEOUT_S", 1.0)
# Synthetic per-RPC round-trip injected into the pipelined A/B cell's
# transports: the in-process transport is so cheap that overlapping
# it never pays (adaptive arming correctly stays sequential), so the
# cell that prices the OVERLAP itself needs a realistic wire cost.
# 2ms ~ cross-zone gRPC hop; large enough that the fixed per-request
# tokenize/hash/score work doesn't drown the RPC share the A/B is
# measuring.  0 skips the cell.
SCALEOUT_RTT_S = _env_float("KVTPU_BENCH_SCALEOUT_RTT_S", 0.002)
# The pinned failover degradation envelope (docs/replication.md): the
# post-kill hit rate over the measurement window may dip at most this
# far below the pre-kill window — the follower's standby slice is warm,
# so the only lost state is whatever hadn't synced at the kill.
SCALEOUT_DIP_ENVELOPE = 0.15
# Untraced-path budget for the fleet observability plane (ISSUE 13):
# trace plumbing + per-replica rpc accounting may cost at most this
# fraction of clustered scores/sec when no request is traced.
TRACE_OVERHEAD_BOUND = 0.03
# Pinned ceiling for the always-on sampling profiler's cost to a hot
# headline at its DEFAULT rate (obs/profiler.py; the read_path and
# event_storm profiler_ab cells assert it).
PROFILE_OVERHEAD_BOUND = 0.03
# Pinned ceiling for the always-on input flight recorder's cost to
# the same two headlines (obs/capture.py; the read_path and
# event_storm capture_ab cells assert it — the ISSUE 15 acceptance
# bound for capture-on overhead).
CAPTURE_OVERHEAD_BOUND = 0.03


def bench_replica_scaleout(
    requests, hashes_list, t_miss: float, t_hit: float,
    ideal_service: float, cell_seconds: Optional[float] = None,
) -> dict:
    """detail.replica_scaleout regime (docs/replication.md): the
    indexer as an N-replica service, extending ``indexer_restart`` —
    that regime prices losing the whole index; this one prices losing
    ONE replica of it.

    Cell 1 (scores/sec): per-request scoring throughput through the
    REAL read path against a single-process in-memory index, a
    1-replica cluster (pure RPC-hop overhead), and a 3-replica cluster
    (in-process replicas over the local transport), with an exact
    score-parity check across all three — the cluster must never
    change a routing decision (the same oracle the parity tests pin).

    Cell 2 (failover dip): the fleet stream runs precise routing with
    the 3-replica cluster (replication followers syncing); halfway, one
    replica is KILLED mid-traffic.  Engine pods keep their caches —
    only the index slice moves — so the hit-rate dip between the
    pre-kill and post-kill windows is the cost of failover, asserted
    inside the pinned envelope.
    """
    import tempfile

    from llm_d_kv_cache_manager_tpu.cluster import LocalCluster
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import PodEntry

    cell_s = SCALEOUT_CELL_S if cell_seconds is None else cell_seconds
    rng = random.Random(733)
    pods = [f"pod-{i}" for i in range(NUM_PODS)]
    out: dict = {"dip_envelope": SCALEOUT_DIP_ENVELOPE}

    # ---- cell 1: multi-replica scores/sec + parity -------------------
    def new_indexer(
        index=None,
        pipeline_depth=None,
        score_memo=0,
        exact_tokenize=False,
    ) -> Indexer:
        # exact_tokenize (the cache_analytics precedent): a ratio
        # above 1.0 makes the prefix store's serve path unreachable,
        # so warm repeats re-walk the chain in chunks instead of
        # collapsing to one pre-hashed slice — the pipelined A/B
        # prices the chunked drive, which the serve path would mask.
        tokenization_config = (
            TokenizationPoolConfig(min_prefix_overlap_ratio=1.01)
            if exact_tokenize
            else TokenizationPoolConfig()
        )
        indexer = Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(
                    block_size=BLOCK_SIZE
                ),
                kvblock_index_config=IndexConfig(),
                tokenizers_pool_config=tokenization_config,
                score_memo_size=score_memo,
                cache_stats=False,
                pipeline_depth=pipeline_depth,
            ),
            tokenizer=WordTokenizer(),
            kv_block_index=index,
        )
        indexer.run()
        return indexer

    convo = [rng.randrange(1, 16384) for _ in range(PREFIX_TOKENS)]
    prompts: List[str] = []
    for _ in range(6):
        convo.extend(
            rng.randrange(1, 16384) for _ in range(SUFFIX_TOKENS)
        )
        prompts.append(" ".join(f"t{t}" for t in convo))

    def seed_index(indexer: Indexer) -> None:
        keys = indexer.token_processor.tokens_to_kv_block_keys(
            0, convo, MODEL_NAME
        )
        indexer.kv_block_index.add(keys, keys, [PodEntry("pod-0", "hbm")])
        indexer.kv_block_index.add(keys, keys, [PodEntry("pod-1", "host")])

    def run_cell(indexer: Indexer) -> dict:
        for prompt in prompts:  # steady-state warmup
            indexer.get_pod_scores(prompt, MODEL_NAME, pods)
        latencies: List[float] = []
        deadline = time.perf_counter() + cell_s
        i = 0
        while time.perf_counter() < deadline:
            t0 = time.perf_counter()
            indexer.get_pod_scores(
                prompts[i % len(prompts)], MODEL_NAME, pods
            )
            latencies.append(time.perf_counter() - t0)
            i += 1
        total = sum(latencies)
        return {
            "scores_per_sec": (
                round(len(latencies) / total, 1) if total else 0.0
            ),
            "p50_us": round(float(np.percentile(latencies, 50)) * 1e6, 1),
            "p99_us": round(float(np.percentile(latencies, 99)) * 1e6, 1),
            "requests": len(latencies),
        }

    cluster3 = LocalCluster()
    cluster1 = LocalCluster(replica_ids=("solo",))
    single = new_indexer()
    over3 = new_indexer(cluster3.remote_index)
    over1 = new_indexer(cluster1.remote_index)
    try:
        for indexer in (single, over3, over1):
            seed_index(indexer)
        parity_ok = True
        for prompt in prompts:
            want = single.get_pod_scores(prompt, MODEL_NAME, pods)
            if (
                over3.get_pod_scores(prompt, MODEL_NAME, pods) != want
                or over1.get_pod_scores(prompt, MODEL_NAME, pods) != want
            ):
                parity_ok = False
        out["single"] = run_cell(single)
        out["cluster_1_replica"] = run_cell(over1)
        out["cluster_3_replicas"] = run_cell(over3)
        out["parity"] = "ok" if parity_ok else "MISMATCH"
        out["cell_seconds"] = cell_s

        # ---- trace A/B: untraced-path cost of the observability
        # plane.  Side A runs the default plane (trace plumbing +
        # per-replica rpc accounting armed; requests untraced); side B
        # strips it wholesale — router trace checks, tallies, and the
        # replica span piggyback all off, i.e. the pre-plane frame
        # shape.  Best-of-4 with alternating order damps scheduler and
        # warm-cache bias (the signal is a few µs per RPC); the pinned
        # bound is TRACE_OVERHEAD_BOUND.
        def set_plane(on: bool) -> None:
            cluster3.remote_index.trace_rpcs = on
            cluster3.remote_index.rpc_accounting = on
            for replica in cluster3.replicas.values():
                replica.trace_piggyback = on

        best = {True: 0.0, False: 0.0}
        for ab_round in range(4):
            order = (True, False) if ab_round % 2 == 0 else (False, True)
            for plane_on in order:
                set_plane(plane_on)
                best[plane_on] = max(
                    best[plane_on],
                    run_cell(over3)["scores_per_sec"],
                )
        set_plane(True)
        overhead = (
            max(0.0, (best[False] - best[True]) / best[False])
            if best[False]
            else 0.0
        )
        out["trace_ab"] = {
            "plane_on_sps": best[True],
            "plane_off_sps": best[False],
            "overhead": round(overhead, 4),
            "bound": TRACE_OVERHEAD_BOUND,
            "within_bound": overhead <= TRACE_OVERHEAD_BOUND,
        }

        # ---- fan-out profile: a continuous-profiler capture of the
        # 3-replica scoring drive (obs/profiler.py), the live "before"
        # for ROADMAP item 3 — the share of wall time inside
        # cluster/remote_index.py IS the sequential owner/chunk
        # fan-out the pipelining work must erase, and the rpc
        # critical-path counters ride along so the A/B has exact
        # owner-RPC depths next to the stack shares.
        from llm_d_kv_cache_manager_tpu.obs.profiler import (
            ProfilerConfig as _ProfCfg,
            SamplingProfiler as _Prof,
        )

        fan_hz = 199.0  # dense: the cell is short and sampler-only
        fan_prof = _Prof(_ProfCfg(hz=fan_hz))
        fan_prof.start()
        fan_cell = run_cell(over3)
        fan_prof.close()
        fan_total = 0
        fan_in_remote = 0
        for line in fan_prof.collapsed().splitlines():
            stack, _, count_text = line.rpartition(" ")
            if not stack.startswith("main;"):
                # The drive (and the in-process replica RPCs under
                # it) runs on the bench main thread; idle pool
                # threads would only dilute the share.
                continue
            count = int(count_text)
            fan_total += count
            if "cluster/remote_index.py" in stack:
                fan_in_remote += count
        out["fanout_profile"] = {
            "hz": fan_hz,
            "scores_per_sec": fan_cell["scores_per_sec"],
            "samples": fan_total,
            "remote_index_share": (
                round(fan_in_remote / fan_total, 4)
                if fan_total
                else None
            ),
            "top_self": fan_prof.top(10),
            "critical_path": cluster3.remote_index.rpc_stats()[
                "critical_path"
            ],
        }
    finally:
        single.shutdown()
        over3.shutdown()
        over1.shutdown()
        cluster3.close()
        cluster1.close()

    # ---- pipelined A/B: read-path fan-out pipelining ------------------
    # (docs/replication.md "Pipelined read path").  The cells above run
    # in-process transports whose whole "RPC" is cheaper than a thread
    # handoff, so adaptive arming correctly keeps them sequential; this
    # cell injects a realistic per-call RTT and runs the same warm
    # multi-turn workload through the sequential parity oracle
    # (fanout_workers=0 + pipeline_depth=0) and the overlapped +
    # pipelined drive (defaults, arming forced) on twin 3-replica
    # clusters: scores asserted identical, warm throughput asserted
    # >= 2x, pipelined warm p99 reported as a multiple of the RTT.  A
    # cold cell (unique single-shot prompts, index misses) prices the
    # speculation overhead, and a memo cell pins memo-hit repeats at
    # ~single-process rates with ZERO lookup RPC rounds.  Profiler
    # captures around both warm cells give the before/after
    # main-thread remote_index.py wall share (ROADMAP item 3's
    # acceptance: the sequential fan-out share must shrink).
    class _RttTransport:
        """Transport decorator charging one synthetic RTT per call."""

        def __init__(self, inner, rtt_s: float) -> None:
            self._inner = inner
            self._rtt_s = rtt_s
            self.supports_deadline = getattr(
                inner, "supports_deadline", False
            )

        def call(self, method, args):
            time.sleep(self._rtt_s)
            return self._inner.call(method, args)

        def call_ex(self, method, args, traceparent=None):
            time.sleep(self._rtt_s)
            return self._inner.call_ex(
                method, args, traceparent=traceparent
            )

        def call_vv(self, method, args, traceparent=None, timeout=None):
            time.sleep(self._rtt_s)
            return self._inner.call_vv(
                method, args, traceparent=traceparent, timeout=timeout
            )

    def _main_remote_share(prof) -> Optional[float]:
        # Main-thread wall share inside cluster/remote_index.py: the
        # sequential drive blocks THERE (transport waits under _call);
        # the pipelined drive blocks in the indexer's handle.result()
        # while pool threads do the waiting, so the share collapsing
        # is exactly the pipelining landing.
        total = hits = 0
        for line in prof.collapsed().splitlines():
            stack, _, count_text = line.rpartition(" ")
            if not stack.startswith("main;"):
                continue
            count = int(count_text)
            total += count
            if "cluster/remote_index.py" in stack:
                hits += count
        return round(hits / total, 4) if total else None

    rtt_s = SCALEOUT_RTT_S
    if rtt_s > 0.0:
        wrap = lambda _rid, t: _RttTransport(t, rtt_s)  # noqa: E731
        seq_cluster = LocalCluster(
            fanout_workers=0, transport_wrap=wrap
        )
        pipe_cluster = LocalCluster(
            overlap_min_rpc_s=0.0, transport_wrap=wrap
        )
        seq_ix = new_indexer(
            seq_cluster.remote_index,
            pipeline_depth=0,
            exact_tokenize=True,
        )
        pipe_ix = new_indexer(
            pipe_cluster.remote_index, exact_tokenize=True
        )
        memo_pipe = new_indexer(
            pipe_cluster.remote_index,
            score_memo=256,
            exact_tokenize=True,
        )
        memo_single = new_indexer(score_memo=256, exact_tokenize=True)
        try:
            for indexer in (seq_ix, pipe_ix, memo_single):
                seed_index(indexer)
            ab_parity = True
            for prompt in prompts:
                if seq_ix.get_pod_scores(
                    prompt, MODEL_NAME, pods
                ) != pipe_ix.get_pod_scores(prompt, MODEL_NAME, pods):
                    ab_parity = False

            prof_before = _Prof(_ProfCfg(hz=fan_hz))
            prof_before.start()
            seq_warm = run_cell(seq_ix)
            prof_before.close()
            prof_after = _Prof(_ProfCfg(hz=fan_hz))
            prof_after.start()
            pipe_warm = run_cell(pipe_ix)
            prof_after.close()
            before_share = _main_remote_share(prof_before)
            after_share = _main_remote_share(prof_after)
            speedup = (
                round(
                    pipe_warm["scores_per_sec"]
                    / seq_warm["scores_per_sec"],
                    2,
                )
                if seq_warm["scores_per_sec"]
                else None
            )

            # Cold: unique prompts, every chain misses at block 0 —
            # prices tokenize + first-chunk fan-out + the speculation
            # a dead chain drops on the floor.
            cold_rng = random.Random(401)
            cold_pool = [
                " ".join(
                    f"c{cold_rng.randrange(1, 1 << 30)}"
                    for _ in range(128)
                )
                for _ in range(320)
            ]

            def run_cold(indexer, cold_prompts) -> dict:
                latencies: List[float] = []
                for prompt in cold_prompts:
                    t0 = time.perf_counter()
                    indexer.get_pod_scores(prompt, MODEL_NAME, pods)
                    latencies.append(time.perf_counter() - t0)
                total = sum(latencies)
                return {
                    "scores_per_sec": (
                        round(len(latencies) / total, 1)
                        if total
                        else 0.0
                    ),
                    "p99_us": round(
                        float(np.percentile(latencies, 99)) * 1e6, 1
                    ),
                    "requests": len(latencies),
                }

            seq_cold = run_cold(seq_ix, cold_pool[:160])
            pipe_cold = run_cold(pipe_ix, cold_pool[160:])

            # Memo: repeats of one warm prompt must hit the memo (0
            # lookup RPC rounds — touch_chain recency refreshes ride
            # the off-thread pool) at ~the single-process memo rate.
            def run_repeat(indexer, seconds: float) -> dict:
                repeat_prompt = prompts[-1]
                for _ in range(3):  # populate + validate the memo
                    indexer.get_pod_scores(
                        repeat_prompt, MODEL_NAME, pods
                    )
                count = 0
                t0 = time.perf_counter()
                deadline = t0 + seconds
                while time.perf_counter() < deadline:
                    indexer.get_pod_scores(
                        repeat_prompt, MODEL_NAME, pods
                    )
                    count += 1
                elapsed = time.perf_counter() - t0
                return {
                    "scores_per_sec": (
                        round(count / elapsed, 1) if elapsed else 0.0
                    ),
                    "requests": count,
                }

            memo_parity = memo_pipe.get_pod_scores(
                prompts[-1], MODEL_NAME, pods
            ) == memo_single.get_pod_scores(prompts[-1], MODEL_NAME, pods)
            # Converge the memo first: request 1 stores a sentinel
            # vector (nothing piggybacked yet), request 2 recomputes
            # against the now-real vector, request 3+ hit.  Only THEN
            # pin zero lookup rounds.
            for _ in range(3):
                memo_pipe.get_pod_scores(prompts[-1], MODEL_NAME, pods)
            rounds_before = pipe_cluster.remote_index.rpc_stats()[
                "critical_path"
            ]["lookup_calls"]
            memo_pipe_cell = run_repeat(memo_pipe, cell_s / 2)
            hit_rounds = (
                pipe_cluster.remote_index.rpc_stats()["critical_path"][
                    "lookup_calls"
                ]
                - rounds_before
            )
            memo_single_cell = run_repeat(memo_single, cell_s / 2)

            pipe_stats = pipe_cluster.remote_index.rpc_stats()
            out["pipelined_ab"] = {
                "rtt_us": round(rtt_s * 1e6, 1),
                "parity": "ok" if ab_parity else "MISMATCH",
                "sequential_warm": seq_warm,
                "pipelined_warm": pipe_warm,
                "speedup_warm": speedup,
                "speedup_ok": (
                    speedup is not None and speedup >= 2.0
                ),
                "p99_rtt_ratio": round(
                    pipe_warm["p99_us"] / (rtt_s * 1e6), 2
                ),
                "sequential_cold": seq_cold,
                "pipelined_cold": pipe_cold,
                "memo_warm": {
                    "pipelined_sps": memo_pipe_cell["scores_per_sec"],
                    "single_sps": memo_single_cell["scores_per_sec"],
                    "ratio": (
                        round(
                            memo_pipe_cell["scores_per_sec"]
                            / memo_single_cell["scores_per_sec"],
                            3,
                        )
                        if memo_single_cell["scores_per_sec"]
                        else None
                    ),
                    "hit_lookup_rounds": hit_rounds,
                    "hit_rounds_ok": hit_rounds == 0,
                    "parity": memo_parity,
                },
                "profile": {
                    "hz": fan_hz,
                    "before_share": before_share,
                    "after_share": after_share,
                    "improved": (
                        before_share is not None
                        and after_share is not None
                        and after_share < before_share
                    ),
                },
                "rpc": pipe_stats["critical_path"],
                "fanout": pipe_stats["fanout"],
            }
        finally:
            seq_ix.shutdown()
            pipe_ix.shutdown()
            memo_pipe.shutdown()
            memo_single.shutdown()
            seq_cluster.close()
            pipe_cluster.close()

    # ---- cell 2: failover hit-rate dip --------------------------------
    n = len(requests)
    half = n // 2
    window = max(1, half // 2)
    qps = 0.7 * NUM_PODS / ideal_service
    arrivals = poisson_arrivals(qps, n, ARRIVAL_SEEDS[0])
    with tempfile.TemporaryDirectory() as root:
        cluster = LocalCluster(journal_root=root)
        fleet = FleetRouter(
            "precise",
            with_kv=False,
            seed=0,
            index_factory=lambda: cluster.remote_index,
        )
        try:
            from llm_d_kv_cache_manager_tpu.obs.slo import (
                SloEngine,
                SloSpec,
                envelope_violations,
            )

            pre_hits = 0
            for i in range(half):
                _, hit, _, _ = _fleet_step(
                    fleet, requests[i], hashes_list[i], arrivals[i],
                    t_miss, t_hit,
                )
                if i >= half - window:
                    pre_hits += hit
            # Let the event plane and the standby followers catch up,
            # then kill the replica owning the FIRST request's chain —
            # guaranteed to hold live slice state.
            fleet.event_pool.drain()
            while cluster.sync_followers():
                pass  # drain bounded polls until every journal is dry
            ring_before = cluster.membership.ring()
            victim = ring_before.owner(hashes_list[0][0])
            # Direct slice-coverage probe: the fleet hit rate can mask
            # index loss behind the router's affinity fallback, so also
            # ask the cluster for the victim's own resident keys after
            # the kill — a warm follower answers ~all of them.
            victim_dump, _ = cluster.replicas[victim].index.dump_entries()
            owned_sample = [
                key
                for key, _ in victim_dump
                if ring_before.owner(key) == victim
            ][:500]
            # Declarative degradation envelope (docs/observability.md):
            # the PR-10 "dip <= 0.15" one-off pin expressed as SLIs the
            # SLO engine evaluates — post-kill hit rate bounded by
            # (pre-kill rate - envelope), replica deaths and failovers
            # bounded by the single planned kill.  The chaos cell then
            # asserts the PUBLISHED envelope, not ad-hoc numbers.
            pre_rate = round(pre_hits / window, 3)
            slo_hits = {"good": 0.0, "total": 0.0}
            slo = SloEngine(window_fast_s=3600.0, window_slow_s=7200.0)
            slo.register(
                SloSpec(
                    "hit_rate",
                    kind="ratio",
                    objective=max(0.0, min(1.0, pre_rate)),
                    degraded_bound=max(
                        0.0, pre_rate - SCALEOUT_DIP_ENVELOPE
                    ),
                    description=(
                        "post-kill fleet hit rate vs the pre-kill "
                        "baseline"
                    ),
                ),
                lambda: (slo_hits["good"], slo_hits["total"]),
            )
            slo.register(
                SloSpec(
                    "replicas_dead",
                    kind="gauge",
                    objective=0.0,
                    degraded_bound=1.0,
                ),
                lambda: (
                    float(
                        len(cluster.membership.members())
                        - len(cluster.membership.alive())
                    ),
                    0.0,
                ),
            )
            slo.register(
                SloSpec(
                    "failovers",
                    kind="rate",
                    objective=0.0,
                    degraded_bound=1.0,
                ),
                lambda: (
                    float(cluster.membership.failover_count()),
                    0.0,
                ),
            )
            t_base = time.time()
            slo.sample(now=t_base)
            pre_state = slo.evaluate(now=t_base)["state"]
            cluster.kill(victim)
            coverage = None
            if owned_sample:
                served = cluster.remote_index.lookup(owned_sample)
                coverage = round(len(served) / len(owned_sample), 3)
            post_hits = 0
            for i in range(half, half + window):
                _, hit, _, _ = _fleet_step(
                    fleet, requests[i], hashes_list[i], arrivals[i],
                    t_miss, t_hit,
                )
                post_hits += hit
                slo_hits["good"] += hit
                slo_hits["total"] += 1
            slo.sample(now=t_base + 1.0)
            envelope = slo.evaluate(now=t_base + 1.0)
            violations = envelope_violations(envelope)
            post_rate = round(post_hits / window, 3)
            dip = round(max(0.0, pre_rate - post_rate), 3)
            out["failover"] = {
                "pre_kill_hit_rate": pre_rate,
                "post_kill_hit_rate": post_rate,
                "dip": dip,
                "within_envelope": dip <= SCALEOUT_DIP_ENVELOPE,
                "slo_envelope": {
                    "pre_state": pre_state,
                    "state": envelope["state"],
                    "hit_rate_value": envelope["slis"]["hit_rate"][
                        "value"
                    ],
                    "hit_rate_bound": envelope["slis"]["hit_rate"][
                        "degraded_bound"
                    ],
                    "violations": violations,
                    "ok": pre_state == "healthy" and not violations,
                },
                "slice_coverage_post_kill": coverage,
                "slice_keys_sampled": len(owned_sample),
                "coverage_ok": (
                    coverage is None
                    or coverage >= 1.0 - SCALEOUT_DIP_ENVELOPE
                ),
                "killed_replica": victim,
                "failovers": cluster.membership.failover_count(),
                "window_requests": window,
            }
        finally:
            fleet.shutdown()
            cluster.close()
    return out


def maybe_bench_replica_scaleout(
    requests, hashes_list, t_miss, t_hit, ideal_service
) -> dict:
    """bench_replica_scaleout under the degrade contract."""
    if _over_budget(reserve_s=50.0):
        return {"truncated": True}
    _progress(
        "replica_scaleout: clustered scores/sec + failover dip"
    )
    return bench_replica_scaleout(
        requests, hashes_list, t_miss, t_hit, ideal_service
    )


# ------------- cache_analytics: ledger-truth + audit-plane regime -------


def bench_cache_analytics(cell_seconds: Optional[float] = None) -> dict:
    """detail.cache_analytics regime (docs/observability.md), three
    cells, all device-free:

    1. **ledger truth** — the churn workload (pool barely holds one
       group's working set) through the REAL precise read+write path
       with the hit-attribution ledger attached; the ledger's reported
       hit rate must land within ±2% of the bench's engine-side ground
       truth (account() on the routed pod).  The ledger classifies hit
       = best pod covered the full 512-block shared prefix
       (hit_blocks), exactly the engine's own criterion; tokenization
       runs exact (no prefix-store truncation) so block counts align.
    2. **audit plane** — a synthetic 2-pod index built through the
       event pool, with a planted 5% divergence (one pod's inventory
       loses 5% of its blocks → the index's claims become phantoms);
       one auditor cycle must detect the pod, the ratio, and leave the
       clean pod clean.
    3. **overhead A/B** — the warm multi-turn scoring loop with
       analytics on (sample rate 1.0) vs off over identical data;
       the acceptance bar is on-overhead <= 3% (and bit-identical
       scores, asserted here as parity).
    """
    from llm_d_kv_cache_manager_tpu.analytics.auditor import (
        AuditorConfig,
        IndexAuditor,
    )
    from llm_d_kv_cache_manager_tpu.analytics.ledger import (
        CacheStatsLedger,
        LedgerConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import PodEntry
    from llm_d_kv_cache_manager_tpu.kvevents.resync import (
        CallableInventorySource,
        InventoryBlock,
        PodInventory,
    )

    cell_s = (
        ANALYTICS_CELL_S if cell_seconds is None else cell_seconds
    )
    result: dict = {}

    # -- cell 1: ledger hit rate vs engine-side ground truth (churn) --
    rng = random.Random(8080)
    requests = make_prompts(rng)
    hashes_list = [block_hash_chain(tokens) for _, _, tokens in requests]
    n_prefix_blocks = PREFIX_TOKENS // BLOCK_SIZE
    ledger = CacheStatsLedger(
        LedgerConfig(sample_rate=1.0, hit_blocks=n_prefix_blocks)
    )
    t_miss, t_hit = CAL_MISS_S, CAL_HIT_S
    ideal = ideal_service_time(t_miss, t_hit, len(requests))
    qps = 0.7 * NUM_PODS / ideal
    arrivals = poisson_arrivals(qps, len(requests), ARRIVAL_SEEDS[0])
    _, ground_truth, _, _ = run_fleet_virtual(
        "precise",
        requests,
        hashes_list,
        arrivals,
        t_miss,
        t_hit,
        ARRIVAL_SEEDS[0],
        pool_blocks=CHURN_POOL_BLOCKS,
        cache_stats_ledger=ledger,
        exact_tokenize=True,
    )
    snapshot = ledger.snapshot()
    totals = snapshot["totals"]
    recorded = totals["recorded"]
    ledger_hit_rate = totals["hits"] / recorded if recorded else 0.0
    delta = abs(ledger_hit_rate - ground_truth)
    result["ledger_truth"] = {
        "workload": "churn",
        "requests": len(requests),
        "recorded": recorded,
        "ground_truth_hit_rate": round(ground_truth, 4),
        "ledger_hit_rate": round(ledger_hit_rate, 4),
        "delta": round(delta, 4),
        "within_2pct": delta <= 0.02,
        "partials": totals["partials"],
        "families_tracked": snapshot["families_tracked"],
        "window_1m": {
            key: snapshot["windows"]["1m"][key]
            for key in ("requests", "hits", "hit_rate")
        },
    }

    # -- cell 2: planted divergence through the audit plane --
    audit_indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            cache_stats=False,
        ),
        tokenizer=WordTokenizer(),
    )
    audit_pool = Pool(
        audit_indexer.kv_block_index,
        audit_indexer.token_processor,
        PoolConfig(concurrency=2),
    )
    audit_pool.start()
    try:
        blocks_per_pod = 400
        planted_fraction = 0.05
        truth: Dict[str, List[InventoryBlock]] = {}
        plant_rng = random.Random(5050)
        for pod_index in range(2):
            pod = f"audit-pod-{pod_index}"
            tokens = [
                plant_rng.randrange(1, CFG.vocab_size)
                for _ in range(blocks_per_pod * BLOCK_SIZE)
            ]
            hashes = block_hash_chain(tokens)
            batch = EventBatch(
                ts=time.time(),
                events=[
                    BlockStored(
                        block_hashes=list(hashes),
                        parent_block_hash=None,
                        token_ids=list(tokens),
                        block_size=BLOCK_SIZE,
                        medium="hbm",
                    )
                ],
            )
            audit_pool.add_task(
                Message(
                    topic=f"kv@{pod}@{MODEL_NAME}",
                    payload=batch.encode(),
                    pod_identifier=pod,
                    model_name=MODEL_NAME,
                )
            )
            truth[pod] = [
                InventoryBlock(
                    block_hashes=list(hashes),
                    token_ids=list(tokens),
                    block_size=BLOCK_SIZE,
                    medium="hbm",
                )
            ]
        audit_pool.drain()

        # Plant: audit-pod-0's engine "forgot" the last 5% of its
        # blocks — the index now carries that many phantom claims.
        planted = int(blocks_per_pod * planted_fraction)
        kept = blocks_per_pod - planted
        victim = truth["audit-pod-0"][0]
        victim.block_hashes = victim.block_hashes[:kept]
        victim.token_ids = victim.token_ids[: kept * BLOCK_SIZE]

        def fetch(pod: str) -> Optional[PodInventory]:
            if pod not in truth:
                return None
            return PodInventory(
                pod_identifier=pod,
                model_name=MODEL_NAME,
                blocks=truth[pod],
            )

        auditor = IndexAuditor(
            audit_indexer.kv_block_index,
            audit_indexer.token_processor,
            CallableInventorySource(fetch),
            AuditorConfig(interval_s=0.0),
        )
        cycle_start = time.perf_counter()
        reports = {r.pod: r for r in auditor.run_cycle()}
        cycle_s = time.perf_counter() - cycle_start
        divergent = reports.get("audit-pod-0")
        clean = reports.get("audit-pod-1")
        expected_ratio = planted / blocks_per_pod
        result["audit_plane"] = {
            "blocks_per_pod": blocks_per_pod,
            "planted_ratio": expected_ratio,
            "detected_ratio": (
                round(divergent.divergence_ratio, 4) if divergent else None
            ),
            "detected_phantom": divergent.phantom if divergent else None,
            "detected_outcome": divergent.outcome if divergent else None,
            "clean_pod_ratio": (
                round(clean.divergence_ratio, 4) if clean else None
            ),
            "cycle_s": round(cycle_s, 4),
            "detected_within_one_cycle": bool(
                divergent
                and divergent.outcome == "divergent"
                and abs(divergent.divergence_ratio - expected_ratio) < 0.01
                and clean
                and clean.outcome == "clean"
            ),
        }
    finally:
        audit_pool.shutdown()
        audit_indexer.shutdown()

    # -- cell 3: scoring-path overhead, analytics on vs off --
    overhead_rng = random.Random(909)
    convo = [
        overhead_rng.randrange(1, 16384) for _ in range(PREFIX_TOKENS)
    ]
    turns: List[str] = []
    for _ in range(8):
        convo.extend(
            overhead_rng.randrange(1, 16384) for _ in range(SUFFIX_TOKENS)
        )
        turns.append(" ".join(f"t{t}" for t in convo))

    def scoring_indexer(analytics_on: bool, memo: bool) -> Indexer:
        indexer = Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(
                    block_size=BLOCK_SIZE
                ),
                cache_stats=False,
                score_memo_size=None if memo else 0,
            ),
            tokenizer=WordTokenizer(),
            cache_stats_ledger=(
                CacheStatsLedger(LedgerConfig(sample_rate=1.0))
                if analytics_on
                else None
            ),
        )
        indexer.run()
        keys = indexer.token_processor.tokens_to_kv_block_keys(
            0, convo, MODEL_NAME
        )
        indexer.kv_block_index.add(
            keys, keys, [PodEntry("pod-0", "hbm")]
        )
        indexer.kv_block_index.add(
            keys, keys, [PodEntry("pod-1", "host")]
        )
        return indexer

    pods = [f"pod-{i}" for i in range(NUM_PODS)]

    def scoring_cell(indexer: Indexer) -> float:
        for prompt in turns:  # warm pass
            indexer.get_pod_scores(prompt, MODEL_NAME, pods)
        count = 0
        deadline = time.perf_counter() + cell_s
        start = time.perf_counter()
        while time.perf_counter() < deadline:
            indexer.get_pod_scores(
                turns[count % len(turns)], MODEL_NAME, pods
            )
            count += 1
        return count / (time.perf_counter() - start)

    def overhead_ab(memo: bool) -> dict:
        on = scoring_indexer(True, memo)
        off = scoring_indexer(False, memo)
        try:
            parity_ok = all(
                on.get_pod_scores(prompt, MODEL_NAME, pods)
                == off.get_pod_scores(prompt, MODEL_NAME, pods)
                for prompt in turns[:3]
            )
            # Interleaved rounds with alternating order and best-of
            # aggregation: shared-host scheduler noise dwarfs the
            # ~1% signal, and best-of keeps each side's least-
            # disturbed cell.
            sps_on, sps_off = 0.0, 0.0
            for round_index in range(4):
                if round_index % 2:
                    sps_off = max(sps_off, scoring_cell(off))
                    sps_on = max(sps_on, scoring_cell(on))
                else:
                    sps_on = max(sps_on, scoring_cell(on))
                    sps_off = max(sps_off, scoring_cell(off))
            pct = (
                round((1.0 - sps_on / sps_off) * 100.0, 2)
                if sps_off
                else None
            )
            return {
                "scores_per_sec_on": round(sps_on, 1),
                "scores_per_sec_off": round(sps_off, 1),
                "overhead_pct": pct,
                "parity": "ok" if parity_ok else "MISMATCH",
            }
        finally:
            on.shutdown()
            off.shutdown()

    # The acceptance A/B runs the scoring WALK (multi-turn warm, score
    # memo off): production conversations extend every turn, so the
    # walk is the path each new request pays — the memo serves only
    # exact repeats of an already-scored prompt against an unchanged
    # index.  That adversarial repeat path (microseconds total, where
    # the ledger's fixed ~6us cost is proportionally large) is reported
    # alongside, unbounded, as repeat_overhead.
    walk = overhead_ab(memo=False)
    repeat = overhead_ab(memo=True)
    walk_pct = walk["overhead_pct"]
    result["overhead"] = {
        "walk": walk,
        "repeat": repeat,
        "overhead_pct": walk_pct,
        "within_3pct": walk_pct is not None and walk_pct <= 3.0,
        "parity": (
            "ok"
            if walk["parity"] == "ok" and repeat["parity"] == "ok"
            else "MISMATCH"
        ),
        "cell_seconds": cell_s,
    }
    return result


def maybe_bench_cache_analytics(context: str) -> dict:
    """bench_cache_analytics under the degrade contract."""
    if _over_budget(reserve_s=60.0):
        return {"truncated": True}
    _progress(f"{context}: cache_analytics regime")
    try:
        return bench_cache_analytics()
    except Exception as exc:  # noqa: BLE001 — optional layer
        detail = f"{type(exc).__name__}: {exc}"
        _progress(f"cache_analytics failed: {detail}")
        return {"error": detail[:300]}


# ---------------- tiered_churn: predictive tiering regime --------------

# Calibrated offload-path constants for the compute-or-load cell when
# no device RTT was measured this run: r05's measured readback floor,
# and a host<->storage streaming bandwidth for the synthetic load
# observations fed to the advisor's estimator (labeled calibrated,
# never measured).
CAL_READBACK_S = _env_float("KVTPU_BENCH_CAL_READBACK_S", 0.065)
CAL_HOST_BW_BYTES_S = _env_float("KVTPU_BENCH_HOST_BW_GBPS", 5.0) * 1e9


def _tiered_churn_run(pod_factory, seed: int):
    """One churn-workload run (the r05 regime's exact geometry: same
    prompts, same pool, same QPS) under the given pod factory; returns
    (hit_rate, per-pod eviction logs)."""
    rng = random.Random(9090)
    requests = make_prompts(rng)
    hashes_list = [block_hash_chain(tokens) for _, _, tokens in requests]
    t_miss, t_hit = CAL_MISS_S, CAL_HIT_S
    ideal = ideal_service_time(t_miss, t_hit, len(requests))
    qps = 0.7 * NUM_PODS / ideal
    arrivals = poisson_arrivals(qps, len(requests), seed)
    logs: Dict[str, List[int]] = {}

    def factory(name):
        pod = pod_factory(name)
        pod.evict_log = logs.setdefault(name, [])
        return pod

    _, hit_rate, _, _ = run_fleet_virtual(
        "precise",
        requests,
        hashes_list,
        arrivals,
        t_miss,
        t_hit,
        seed,
        pool_blocks=CHURN_POOL_BLOCKS,
        pod_factory=factory,
    )
    return hit_rate, logs


def bench_tiered_churn(readback_rtt: Optional[float] = None) -> dict:
    """detail.tiered_churn regime (docs/tiering.md), device-free:

    1. **eviction-policy A/B** — the r05 churn workload through the
       real precise read+write path twice in one run: the LRU/ring
       baseline (today's eviction order) vs TieredSimPod driving the
       real PolicyFeed + ledger (reuse-aware protection/admission).
       The predictive arm must beat the baseline hit rate (r05
       stalled at 0.375 — the headroom ROADMAP item 4 names).
    2. **policy-off parity** — TieredSimPod with tiering=None must
       reproduce the baseline's hit rate AND per-pod eviction order
       bit-identically (the escape hatch is the oracle).
    3. **compute-or-load** — TTFT for a fully-offloaded shared prefix
       under pure-load vs pure-recompute vs hybrid overlap, priced by
       the real ComputeOrLoadAdvisor fed with the measured (or
       calibrated r05) readback floor; hybrid must be <= the best
       pure arm within noise.
    """
    from llm_d_kv_cache_manager_tpu.tiering import (
        AdvisorConfig,
        ComputeOrLoadAdvisor,
    )

    result: dict = {}
    seed = ARRIVAL_SEEDS[0]

    # -- cells 1+2: eviction-policy A/B + parity, one run each arm --
    baseline_hit, baseline_logs = _tiered_churn_run(
        lambda name: SimPod(name, with_kv=False,
                            pool_blocks=CHURN_POOL_BLOCKS),
        seed,
    )
    parity_hit, parity_logs = _tiered_churn_run(
        lambda name: TieredSimPod(name, with_kv=False,
                                  pool_blocks=CHURN_POOL_BLOCKS,
                                  tiering=None),
        seed,
    )
    policy = TieredFleetPolicy()
    try:
        predictive_hit, _ = _tiered_churn_run(
            lambda name: TieredSimPod(name, with_kv=False,
                                      pool_blocks=CHURN_POOL_BLOCKS,
                                      tiering=policy),
            seed,
        )
    finally:
        policy.close()
    parity_ok = (
        parity_hit == baseline_hit and parity_logs == baseline_logs
    )
    result["eviction_ab"] = {
        "workload": "churn",
        "pool_blocks": CHURN_POOL_BLOCKS,
        "hit_rate_lru": round(baseline_hit, 4),
        "hit_rate_predictive": round(predictive_hit, 4),
        "beats_lru": predictive_hit > baseline_hit,
        "policy_off_parity": parity_ok,
        "evictions_lru": sum(len(v) for v in baseline_logs.values()),
    }

    # -- cell 3: compute-or-load TTFT (single offloaded-prefix point) --
    n_prefix_blocks = PREFIX_TOKENS // BLOCK_SIZE
    # Per-block KV bytes of the bench model (bf16 = 2 bytes).
    bytes_per_block = (
        2 * CFG.n_layers * CFG.block_size * CFG.n_kv_heads
        * CFG.head_dim * 2
    )
    prefill_rate = TOTAL_TOKENS / CAL_MISS_S
    rtt_floor = (
        readback_rtt
        if readback_rtt and readback_rtt > 0
        else CAL_READBACK_S
    )
    advisor = ComputeOrLoadAdvisor(
        AdvisorConfig(
            bytes_per_block=bytes_per_block,
            block_tokens=BLOCK_SIZE,
            prefill_tokens_per_s=prefill_rate,
            rtt_floor_s=rtt_floor,
        )
    )
    # Synthetic load observations at the calibrated bandwidth — the
    # shape the offload worker's rtt_observer would feed live.
    for nbytes in (1 << 20, 8 << 20, 64 << 20):
        advisor.observe_load(
            nbytes, rtt_floor + nbytes / CAL_HOST_BW_BYTES_S
        )
    advice = advisor.advise(n_prefix_blocks)
    suffix_s = SUFFIX_TOKENS / prefill_rate
    ttft_load = advice.load_s + suffix_s
    ttft_recompute = (PREFIX_TOKENS + SUFFIX_TOKENS) / prefill_rate
    hybrid_core = (
        advice.hybrid_s
        if advice.hybrid_s is not None
        else min(advice.load_s, advice.recompute_s)
    )
    ttft_hybrid = hybrid_core + suffix_s
    best_pure = min(ttft_load, ttft_recompute)
    result["compute_or_load"] = {
        "prefix_blocks": n_prefix_blocks,
        "prefix_bytes": n_prefix_blocks * bytes_per_block,
        "rtt_floor_s": round(rtt_floor, 4),
        "rtt_source": (
            "measured" if readback_rtt and readback_rtt > 0
            else "calibrated"
        ),
        "host_bw_bytes_s": CAL_HOST_BW_BYTES_S,
        "prefill_tokens_per_s": round(prefill_rate, 1),
        "ttft_load_s": round(ttft_load, 4),
        "ttft_recompute_s": round(ttft_recompute, 4),
        "ttft_hybrid_s": round(ttft_hybrid, 4),
        "hybrid_le_min_pure": ttft_hybrid <= best_pure * 1.001 + 1e-9,
        "advice": advice.to_dict(),
    }
    return result


def maybe_bench_tiered_churn(
    context: str, readback_rtt: Optional[float] = None
) -> dict:
    """bench_tiered_churn under the degrade contract."""
    if _over_budget(reserve_s=60.0):
        return {"truncated": True}
    _progress(f"{context}: tiered_churn regime (eviction A/B)")
    try:
        return bench_tiered_churn(readback_rtt)
    except Exception as exc:  # noqa: BLE001 — optional layer
        detail = f"{type(exc).__name__}: {exc}"
        _progress(f"tiered_churn failed: {detail}")
        return {"error": detail[:300]}


# ---------------- scaleout_warmup: KV-transfer planning regime ---------

# Arrival rate as a fraction of the ORIGINAL fleet's ideal capacity:
# high enough that the pre-join pods queue (scale-out is worth doing),
# low enough that the post-join fleet can drain.
SCALEOUT_QPS_FRACTION = 0.95
# LOAD_BLEND coefficient for the transfer-aware arm: queue depth folds
# into routing so the freshly-warmed pod actually receives traffic.
SCALEOUT_LOAD_BLEND = 0.2
# Holder queue depth at which the planner starts pricing transfers:
# genuine overload under the saturating arrival rate, not the ambient
# 2-3 deep queue every pod carries at 0.95 utilization.
SCALEOUT_LOAD_THRESHOLD = 6.0
# Pod bring-up (weights load, server start) before a joining pod is
# routable, every arm alike.  Warm-up transfers stream during this
# window — "instant-warm" means the envelope hides inside init, so
# the pod's first routable request is already a prefix hit.
SCALEOUT_INIT_S = 1.0


def _scaleout_engine_advisor(t_miss: float):
    """Transfer-pricing advisor fed the calibrated offload-path
    costs (same constants as tiered_churn's compute-or-load cell)."""
    from llm_d_kv_cache_manager_tpu.tiering import (
        AdvisorConfig,
        ComputeOrLoadAdvisor,
    )

    bytes_per_block = (
        2 * CFG.n_layers * CFG.block_size * CFG.n_kv_heads
        * CFG.head_dim * 2
    )
    advisor = ComputeOrLoadAdvisor(
        AdvisorConfig(
            bytes_per_block=bytes_per_block,
            block_tokens=BLOCK_SIZE,
            prefill_tokens_per_s=TOTAL_TOKENS / t_miss,
            rtt_floor_s=CAL_READBACK_S,
        )
    )
    for nbytes in (1 << 20, 8 << 20, 64 << 20):
        advisor.observe_load(
            nbytes, CAL_READBACK_S + nbytes / CAL_HOST_BW_BYTES_S
        )
        advisor.observe_store(nbytes, nbytes / CAL_HOST_BW_BYTES_S)
    return advisor


def _scaleout_arm(
    arm: str,
    requests,
    hashes_list,
    arrivals,
    t_miss: float,
    t_hit: float,
    join_at: int,
    pool_blocks: int,
) -> dict:
    """One scale-out run: NUM_PODS pods serve the first half of the
    stream, then a cold pod joins at ``join_at``.

    Arms: ``round_robin`` (blind), ``route_to_holder`` (precise index
    routing, today's behavior — the new pod scores zero on every hot
    prefix and never absorbs load), ``transfer_aware`` (precise +
    TransferEngine: instant-warm the new pod with hot families via
    real KVEvents, blend queue depth into routing, and execute priced
    transfer directives mid-stream — a transferred request pays the
    fetch before decoding, a real cost the virtual clock charges).
    """
    from llm_d_kv_cache_manager_tpu.analytics.ledger import (
        CacheStatsLedger,
        LedgerConfig,
    )
    from llm_d_kv_cache_manager_tpu.transfer import (
        TransferConfig,
        TransferEngine,
    )
    from llm_d_kv_cache_manager_tpu.transfer.planner import (
        DONE as PLAN_DONE,
    )

    n_prefix_blocks = PREFIX_TOKENS // BLOCK_SIZE
    pods = [
        SimPod(f"pod-{i}", with_kv=False, pool_blocks=pool_blocks)
        for i in range(NUM_PODS)
    ]
    pod_by_name = {p.name: p for p in pods}
    pod_free_at = {p.name: 0.0 for p in pods}
    rr = 0
    new_pod_name = f"pod-{NUM_PODS}"
    indexer = event_pool = engine = ledger = None
    if arm != "round_robin":
        if arm == "transfer_aware":
            ledger = CacheStatsLedger(LedgerConfig(sample_rate=1.0))
        indexer = Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(
                    block_size=BLOCK_SIZE
                ),
                kvblock_index_config=IndexConfig(),
                cache_stats=ledger is not None,
                load_blend=(
                    SCALEOUT_LOAD_BLEND
                    if arm == "transfer_aware"
                    else 0.0
                ),
            ),
            tokenizer=WordTokenizer(),
            cache_stats_ledger=ledger,
        )
        indexer.run()
        event_pool = Pool(
            indexer.kv_block_index,
            indexer.token_processor,
            PoolConfig(concurrency=2),
        )
        event_pool.start()
    if arm == "transfer_aware":
        # The new pod's pool holds pool_blocks // prefix-blocks
        # families; warm one fewer so suffix churn has headroom.
        warm_families = max(1, pool_blocks // n_prefix_blocks - 1)
        engine = TransferEngine(
            advisor=_scaleout_engine_advisor(t_miss),
            ledger=ledger,
            config=TransferConfig(
                load_threshold=SCALEOUT_LOAD_THRESHOLD,
                min_blocks=2,
                warmup_families=warm_families,
                warmup_moves=warm_families,
            ),
        )
        indexer.set_transfer_engine(engine)
        engine.attach_executor(
            indexer.kv_block_index, event_pool, MODEL_NAME,
            start_warmup=False,
        )

    # request-key -> engine-hash map per group prefix, so executed
    # plans (which carry index keys) can be mirrored into the virtual
    # pods' engine caches — the sim's stand-in for moving bytes.
    rk_to_engine: Dict[int, int] = {}
    seen_groups: set = set()
    records: List[Tuple[int, float, float, str, bool]] = []
    warmup_moves = 0
    warmup_envelope_s = 0.0
    new_pod_ready: Optional[float] = None

    def engine_copy(dst: SimPod, engine_hashes, src) -> int:
        """Engine-side byte movement: replicate src's cached prefix
        into dst (index-side events were already published by the
        executor); dst's alloc-evictions publish like live traffic."""
        src_ids = (
            src.cached_prefix_blocks(engine_hashes)
            if src is not None
            else []
        )
        n = len(src_ids)
        if n == 0:
            return 0
        ids, evicted = dst.alloc(n)
        for h, bid in zip(engine_hashes[:n], ids):
            dst.cached[h] = bid
            dst._block_owner[bid] = h
        if evicted and event_pool is not None:
            batch = EventBatch(
                ts=time.time(),
                events=[
                    BlockRemoved(
                        block_hashes=list(evicted), medium="hbm"
                    )
                ],
            )
            event_pool.add_task(
                Message(
                    topic=f"kv@{dst.name}@{MODEL_NAME}",
                    payload=batch.encode(),
                    pod_identifier=dst.name,
                    model_name=MODEL_NAME,
                )
            )
        return n

    try:
        for i, (request, hashes, arrival) in enumerate(
            zip(requests, hashes_list, arrivals)
        ):
            group, text, tokens = request
            if i == join_at:
                # -- scale-out event: a cold pod joins ---------------
                new_pod = SimPod(
                    new_pod_name, with_kv=False, pool_blocks=pool_blocks
                )
                pods.append(new_pod)
                pod_by_name[new_pod_name] = new_pod
                pod_free_at[new_pod_name] = arrival
                if engine is not None:
                    engine.register_cold_pod(new_pod_name)
                    plans = engine.warmup.queued_plans()
                    while engine.run_warmup_cycle():
                        pass
                    event_pool.drain()
                    for plan in plans:
                        if (
                            plan.state != PLAN_DONE
                            or plan.target_pod != new_pod_name
                        ):
                            continue
                        engine_hashes = [
                            rk_to_engine[k]
                            for k in plan.block_keys
                            if k in rk_to_engine
                        ]
                        copied = engine_copy(
                            new_pod,
                            engine_hashes,
                            pod_by_name.get(plan.source_pod),
                        )
                        if copied:
                            warmup_moves += 1
                            warmup_envelope_s += (
                                plan.est_transfer_s or 0.0
                            )
                    event_pool.drain()
                # Warm-up bytes stream during pod bring-up; the pod is
                # routable once BOTH finish.  The published SLO
                # envelope is the warm-up transient itself.
                new_pod_ready = arrival + max(
                    SCALEOUT_INIT_S, warmup_envelope_s
                )
                pod_free_at[new_pod_name] = new_pod_ready
            if indexer is not None and group not in seen_groups:
                seen_groups.add(group)
                prefix_keys = (
                    indexer.token_processor.tokens_to_kv_block_keys(
                        0, tokens[:PREFIX_TOKENS], MODEL_NAME
                    )
                )
                for rk, eh in zip(prefix_keys, hashes):
                    rk_to_engine[rk] = eh

            # -- route ----------------------------------------------
            routable = [
                p
                for p in pods
                if p.name != new_pod_name
                or (new_pod_ready is not None and arrival >= new_pod_ready)
            ]
            names = [p.name for p in routable]
            directive = None
            routing_s = 0.0
            if arm == "round_robin":
                pod = routable[rr % len(routable)]
                rr += 1
            else:
                t0 = time.perf_counter()
                if arm == "transfer_aware":
                    # Queue depth in request-equivalents from each
                    # pod's backlog — the warm-up envelope shows up
                    # here too, so the blend doesn't pile requests
                    # onto a pod still receiving its warm-up bytes.
                    loads = {
                        name: max(0.0, pod_free_at[name] - arrival)
                        / t_hit
                        for name in names
                    }
                    scores, directive = (
                        indexer.get_pod_scores_planned(
                            text, MODEL_NAME, names, pod_loads=loads
                        )
                    )
                else:
                    scores = indexer.get_pod_scores(
                        text, MODEL_NAME, names
                    )
                routing_s = time.perf_counter() - t0
                if scores and max(scores.values()) > 0:
                    pod = pod_by_name[
                        max(scores.items(), key=lambda kv: kv[1])[0]
                    ]
                else:
                    pod = routable[rr % len(routable)]
                    rr += 1

            # -- execute a priced transfer directive ----------------
            fetch_s = 0.0
            if (
                directive
                and directive.get("planned")
                and directive["target_pod"] in pod_by_name
            ):
                plan = engine.planner.get(directive["plan_id"])
                if plan is not None and engine.executor.execute(plan):
                    event_pool.drain()
                    dst = pod_by_name[directive["target_pod"]]
                    copied = engine_copy(
                        dst,
                        list(hashes[: directive["blocks"]]),
                        pod_by_name.get(directive["source_pod"]),
                    )
                    if copied:
                        event_pool.drain()
                        pod = dst
                        # The target fetches before decoding.
                        fetch_s = directive.get("est_transfer_s") or 0.0

            # -- serve on the virtual clock -------------------------
            hit, first_new, block_ids, evicted = FleetRouter.account(
                pod, hashes
            )
            service = (t_hit if hit else t_miss) + fetch_s
            queue_start = max(arrival, pod_free_at[pod.name])
            done = queue_start + service
            pod_free_at[pod.name] = done
            for h, bid in zip(
                hashes[first_new:], block_ids[first_new:]
            ):
                pod.cached[h] = bid
                pod._block_owner[bid] = h
            if event_pool is not None:
                publish_events(
                    event_pool, pod, tokens, hashes, first_new, evicted
                )
                event_pool.drain()
            records.append(
                (
                    i,
                    arrival,
                    routing_s + (queue_start - arrival) + service,
                    pod.name,
                    hit,
                )
            )
    finally:
        if engine is not None:
            engine.close()
        if event_pool is not None:
            event_pool.shutdown()
        if indexer is not None:
            indexer.shutdown()

    pre = [r for r in records if r[0] < join_at]
    post = [r for r in records if r[0] >= join_at]
    new_pod_post = [r for r in post if r[3] == new_pod_name]
    veteran_post = [r for r in post if r[3] != new_pod_name]
    # "Within the published envelope": the cold pod's hit rate is
    # judged from the moment it becomes routable (init + warm-up
    # transient both behind it).
    settled = [
        r
        for r in new_pod_post
        if new_pod_ready is None or r[1] >= new_pod_ready
    ]
    out = {
        "p90_ttft_pre_join_s": (
            round(float(np.percentile([r[2] for r in pre], 90)), 4)
            if pre
            else None
        ),
        "p90_ttft_post_join_s": (
            round(float(np.percentile([r[2] for r in post], 90)), 4)
            if post
            else None
        ),
        "hit_rate_post_join": (
            round(sum(r[4] for r in post) / len(post), 4)
            if post
            else None
        ),
        "fleet_warm_hit_rate": (
            round(
                sum(r[4] for r in veteran_post) / len(veteran_post), 4
            )
            if veteran_post
            else None
        ),
        "new_pod_requests": len(new_pod_post),
        "new_pod_hit_rate": (
            round(sum(r[4] for r in settled) / len(settled), 4)
            if settled
            else None
        ),
    }
    if arm == "transfer_aware":
        out["warmup"] = {
            "moves": warmup_moves,
            "envelope_s": round(warmup_envelope_s, 4),
            "planner_outcomes": engine.planner.stats()["outcomes"],
            "executor": engine.executor.stats(),
        }
    return out


def _scaleout_parity_cell(requests, hashes_list) -> dict:
    """Planner-off parity: an indexer with the transfer plane attached
    but unused on the plain scoring path (blend off, no pod_loads, no
    planned variant) must return scores bit-identical to a pristine
    indexer fed the same events."""
    from llm_d_kv_cache_manager_tpu.tiering import ComputeOrLoadAdvisor
    from llm_d_kv_cache_manager_tpu.transfer import (
        TransferConfig,
        TransferEngine,
    )

    sample = list(zip(requests, hashes_list))[: min(6, len(requests))]
    names = [f"pod-{i}" for i in range(NUM_PODS)]

    def build(with_transfer: bool):
        indexer = Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(
                    block_size=BLOCK_SIZE
                ),
                kvblock_index_config=IndexConfig(),
                load_blend=0.0,
            ),
            tokenizer=WordTokenizer(),
        )
        indexer.run()
        pool = Pool(
            indexer.kv_block_index,
            indexer.token_processor,
            PoolConfig(concurrency=2),
        )
        pool.start()
        engine = None
        if with_transfer:
            engine = TransferEngine(
                advisor=ComputeOrLoadAdvisor(),
                config=TransferConfig(),
            )
            indexer.set_transfer_engine(engine)
            engine.attach_executor(
                indexer.kv_block_index, pool, MODEL_NAME,
                start_warmup=False,
            )
        return indexer, pool, engine

    plain = build(False)
    planned = build(True)
    try:
        for j, ((_group, _text, tokens), hashes) in enumerate(sample):
            batch = EventBatch(
                ts=1.0,
                events=[
                    BlockStored(
                        block_hashes=list(hashes),
                        parent_block_hash=None,
                        token_ids=list(
                            tokens[: len(hashes) * BLOCK_SIZE]
                        ),
                        block_size=BLOCK_SIZE,
                        medium="hbm",
                    )
                ],
            )
            for _indexer, pool, _engine in (plain, planned):
                pool.add_task(
                    Message(
                        topic=f"kv@pod-{j % NUM_PODS}@{MODEL_NAME}",
                        payload=batch.encode(),
                        pod_identifier=f"pod-{j % NUM_PODS}",
                        model_name=MODEL_NAME,
                    )
                )
                pool.drain()
        parity_ok = all(
            plain[0].get_pod_scores(text, MODEL_NAME, names)
            == planned[0].get_pod_scores(text, MODEL_NAME, names)
            for (_g, text, _t), _h in sample
        )
    finally:
        for indexer, pool, engine in (plain, planned):
            if engine is not None:
                engine.close()
            pool.shutdown()
            indexer.shutdown()
    return {
        "parity": "ok" if parity_ok else "MISMATCH",
        "prompts": len(sample),
    }


def bench_scaleout_warmup() -> dict:
    """detail.scaleout_warmup regime (docs/transfer.md), device-free:

    1. **scale-out A/B/C** — the grouped-prefix stream at 0.95 of the
       original fleet's ideal capacity; a cold pod joins mid-stream.
       transfer-aware (instant-warm + load-blended routing + priced
       directives) vs route-to-holder (today's precise routing) vs
       round-robin, on post-join p90 TTFT and the cold pod's hit rate
       relative to the warm fleet, with the warm-up transient
       published as an SLO envelope.
    2. **planner-off parity** — the transfer plane attached but unused
       must leave plain scores bit-identical (the oracle).
    """
    rng = random.Random(2121)
    base = make_prompts(rng)
    base_hashes = [block_hash_chain(tokens) for _, _, tokens in base]
    t_miss, t_hit = CAL_MISS_S, CAL_HIT_S
    # 0.95 of the original fleet's HIT-dominated capacity: the best
    # any routing can do with warm caches is t_hit per request, so the
    # veterans run saturated and the only path to queue relief is
    # making the new pod useful.
    qps = SCALEOUT_QPS_FRACTION * NUM_PODS / t_hit
    # Replay the grouped stream until the virtual span comfortably
    # exceeds the rho=0.95 queueing time-constant (~t_hit/(1-rho)):
    # shorter runs measure the warm-up transient, not the relief.
    span_s = 4.0 * t_hit / (1.0 - SCALEOUT_QPS_FRACTION)
    reps = max(3, -(-int(span_s * qps) // len(base)))
    requests = base * reps
    hashes_list = base_hashes * reps
    n_prefix_blocks = PREFIX_TOKENS // BLOCK_SIZE
    # Per-pod capacity that BINDS (~half the family set + suffix
    # headroom): with free capacity everywhere, route-to-holder never
    # pays for ignoring the new pod and the regime measures nothing.
    pool_blocks = min(
        POOL_BLOCKS,
        n_prefix_blocks * max(2, NUM_GROUPS // 2)
        + n_prefix_blocks // 2,
    )
    join_at = len(requests) // 3
    # Scale-out transients are noisy at rho ~= 1: median p90 across
    # arrival seeds, same discipline as the headline.
    per_seed = {}
    for seed in ARRIVAL_SEEDS:
        arrivals = poisson_arrivals(qps, len(requests), seed)
        per_seed[seed] = {
            arm: _scaleout_arm(
                arm, requests, hashes_list, arrivals, t_miss, t_hit,
                join_at, pool_blocks,
            )
            for arm in (
                "round_robin", "route_to_holder", "transfer_aware"
            )
        }

    def _median(values):
        vals = sorted(v for v in values if v is not None)
        return vals[len(vals) // 2] if vals else None

    arms = {}
    for arm in ("round_robin", "route_to_holder", "transfer_aware"):
        runs = [per_seed[seed][arm] for seed in ARRIVAL_SEEDS]
        arms[arm] = {
            key: _median([r.get(key) for r in runs])
            for key in (
                "p90_ttft_pre_join_s",
                "p90_ttft_post_join_s",
                "hit_rate_post_join",
                "fleet_warm_hit_rate",
                "new_pod_hit_rate",
            )
        }
        arms[arm]["per_seed"] = {
            str(seed): per_seed[seed][arm] for seed in ARRIVAL_SEEDS
        }
        if arm == "transfer_aware":
            arms[arm]["warmup_envelope_s"] = _median(
                [(r.get("warmup") or {}).get("envelope_s") for r in runs]
            )
    ta = arms["transfer_aware"]
    p90_ta = ta.get("p90_ttft_post_join_s")
    p90_rth = arms["route_to_holder"].get("p90_ttft_post_join_s")
    p90_rr = arms["round_robin"].get("p90_ttft_post_join_s")
    cold_ratio = None
    if ta.get("new_pod_hit_rate") is not None and ta.get(
        "fleet_warm_hit_rate"
    ):
        cold_ratio = round(
            ta["new_pod_hit_rate"] / ta["fleet_warm_hit_rate"], 4
        )
    return {
        "workload": {
            "requests": len(requests),
            "reps": reps,
            "join_at": join_at,
            "qps_fraction": SCALEOUT_QPS_FRACTION,
            "pool_blocks": pool_blocks,
            "load_blend": SCALEOUT_LOAD_BLEND,
            "load_threshold": SCALEOUT_LOAD_THRESHOLD,
        },
        "arms": arms,
        "ttft_p90_beats_route_to_holder": (
            p90_ta is not None
            and p90_rth is not None
            and p90_ta < p90_rth
        ),
        "ttft_p90_beats_round_robin": (
            p90_ta is not None
            and p90_rr is not None
            and p90_ta < p90_rr
        ),
        "cold_pod_hit_ratio": cold_ratio,
        "cold_pod_warm_within_envelope": (
            cold_ratio is not None and cold_ratio >= 0.8
        ),
        "parity": _scaleout_parity_cell(requests, hashes_list),
    }


def maybe_bench_scaleout_warmup(context: str) -> dict:
    """bench_scaleout_warmup under the degrade contract."""
    if _over_budget(reserve_s=60.0):
        return {"truncated": True}
    _progress(f"{context}: scaleout_warmup regime (transfer A/B/C)")
    try:
        return bench_scaleout_warmup()
    except Exception as exc:  # noqa: BLE001 — optional layer
        detail = f"{type(exc).__name__}: {exc}"
        _progress(f"scaleout_warmup failed: {detail}")
        return {"error": detail[:300]}


# ---------------- host_offload: staging-engine data-plane regime -------

# A compact but real KV geometry: 64 KiB per block across layers, so a
# 32-block transfer moves 2 MiB through the actual gather -> staging ->
# file path without dominating the CPU smoke budget.
HO_POOL_BLOCKS = 32
HO_BLOCKS_PER_FILE = 4
HO_LANES_SWEEP = (1, 2, 4)


def _ho_pool_config() -> KVCachePoolConfig:
    return KVCachePoolConfig(
        num_layers=4,
        num_blocks=HO_POOL_BLOCKS,
        block_size=BLOCK_SIZE,
        num_kv_heads=4,
        head_dim=64,
        dtype="bfloat16",
    )


def _ho_fill(pool: KVCachePool, block_ids, seed: int):
    rng = np.random.default_rng(seed)
    c = pool.config
    for block_id in block_ids:
        pool.write_block(
            block_id,
            rng.standard_normal(
                (c.num_layers, 2, c.block_size, c.num_kv_heads, c.head_dim)
            ).astype(host_dtype(c.dtype)),
        )


def _ho_roundtrip(
    device, root: str, lanes: int, rank: int, seed: int
) -> dict:
    """One chip's store + load round trip through the offload
    connector (staged when lanes > 0, the one-shot oracle at 0);
    returns wall times, bytes, and a parity verdict."""
    pool = KVCachePool(
        _ho_pool_config(),
        sharding=jax.sharding.SingleDeviceSharding(device),
    )
    spec = TPUOffloadSpec(
        shared_storage_path=root,
        model_name="bench/offload",
        device_block_size=BLOCK_SIZE,
        offloaded_block_size=BLOCK_SIZE * HO_BLOCKS_PER_FILE,
        threads_per_chip=4,
        staging_lanes=lanes,
        rank=rank,  # each chip writes its own shard tree
    )
    connector = TPUOffloadConnector(spec, pool)
    try:
        half = HO_POOL_BLOCKS // 2
        block_ids = list(range(half))
        _ho_fill(pool, block_ids, seed)
        file_hashes = [
            0x1000 + seed * 0x100 + i
            for i in range(half // HO_BLOCKS_PER_FILE)
        ]
        groups = group_blocks_per_file(
            file_hashes, block_ids, HO_BLOCKS_PER_FILE
        )
        nbytes = half * pool.block_nbytes

        t0 = time.perf_counter()
        connector.store_handler.transfer_async(1, groups)
        store_ok = (
            connector.store_handler.wait(1) == OffloadJobStatus.SUCCEEDED
        )
        store_s = time.perf_counter() - t0

        target_ids = list(range(half, 2 * half))
        t0 = time.perf_counter()
        connector.load_handler.transfer_async(
            2,
            group_blocks_per_file(
                file_hashes, target_ids, HO_BLOCKS_PER_FILE
            ),
        )
        load_ok = (
            connector.load_handler.wait(2) == OffloadJobStatus.SUCCEEDED
        )
        load_s = time.perf_counter() - t0
        parity = store_ok and load_ok and bool(
            np.array_equal(
                pool.gather_to_host(block_ids),
                pool.gather_to_host(target_ids),
            )
        )
        return {
            "store_s": store_s,
            "load_s": load_s,
            "nbytes": nbytes,
            "parity": parity,
        }
    finally:
        connector.close()


def bench_host_offload(t_miss: Optional[float] = None) -> dict:
    """detail.host_offload regime (docs/host-offload.md):

    1. **staging A/B** — the same store+load round trip through the
       one-shot oracle (lanes=0) and the staged pipeline (lanes=2),
       bytes verified both ways;
    2. **lanes sweep x chips** — every local device runs its own
       staged round trip concurrently (per-chip trees, rank-sharded),
       swept over lanes-per-chip: the MULTICHIP per-chip I/O scaling
       cell;
    3. **TTFT** — offload-hit (measured staged load) vs recompute vs
       advisor-hybrid, with the advisor's estimator fed by the REAL
       transfers this regime just ran, not simulated RTTs.
    """
    from llm_d_kv_cache_manager_tpu.tiering import (
        AdvisorConfig,
        ComputeOrLoadAdvisor,
    )

    result: dict = {}
    root = tempfile.mkdtemp(prefix="kvtpu-bench-offload-")
    devices = jax.local_devices()
    try:
        # -- cell 1: staged vs one-shot A/B on chip 0 --
        oneshot = _ho_roundtrip(
            devices[0], os.path.join(root, "oneshot"), 0, 0, seed=1
        )
        staged = _ho_roundtrip(
            devices[0], os.path.join(root, "staged"), 2, 0, seed=1
        )
        nbytes = staged["nbytes"]

        def _mbps(cell, key):
            seconds = max(cell[key], 1e-9)
            return round(cell["nbytes"] / seconds / 1e6, 1)

        result["staging_ab"] = {
            "payload_mb": round(nbytes / 1e6, 2),
            "oneshot_store_mbps": _mbps(oneshot, "store_s"),
            "staged_store_mbps": _mbps(staged, "store_s"),
            "oneshot_load_mbps": _mbps(oneshot, "load_s"),
            "staged_load_mbps": _mbps(staged, "load_s"),
            "parity": oneshot["parity"] and staged["parity"],
        }

        # -- cell 2: MULTICHIP lanes-per-chip sweep --
        # Untimed warmup round trip per chip first: each device's
        # first gather/scatter pays XLA compilation, which would
        # otherwise be billed entirely to the sweep's first lane
        # count.
        warm_threads = [
            threading.Thread(
                target=_ho_roundtrip,
                args=(d, os.path.join(root, "warm"), 1, i, 99),
            )
            for i, d in enumerate(devices)
        ]
        for thread in warm_threads:
            thread.start()
        for thread in warm_threads:
            thread.join()
        sweep = []
        for lanes in HO_LANES_SWEEP:
            lane_root = os.path.join(root, f"lanes_{lanes}")
            cells = [None] * len(devices)

            def run_chip(idx, device, lane_count=lanes, out=cells,
                         base=lane_root):
                out[idx] = _ho_roundtrip(
                    device, base, lane_count, idx, seed=2 + idx
                )

            threads = [
                threading.Thread(target=run_chip, args=(i, d))
                for i, d in enumerate(devices)
            ]
            wall0 = time.perf_counter()
            for thread in threads:
                thread.start()
            for thread in threads:
                thread.join()
            wall = time.perf_counter() - wall0
            total_bytes = sum(c["nbytes"] for c in cells) * 2  # both ways
            sweep.append(
                {
                    "lanes_per_chip": lanes,
                    "chips": len(devices),
                    "wall_s": round(wall, 4),
                    "aggregate_mbps": round(
                        total_bytes / max(wall, 1e-9) / 1e6, 1
                    ),
                    "parity": all(c["parity"] for c in cells),
                }
            )
        best = max(sweep, key=lambda c: c["aggregate_mbps"])
        result["multichip_lanes_sweep"] = {
            "cells": sweep,
            "best_lanes": best["lanes_per_chip"],
            "best_aggregate_mbps": best["aggregate_mbps"],
        }

        # -- cell 3: TTFT offload-hit vs recompute vs advisor-hybrid --
        ho_cfg = _ho_pool_config()
        pool_bytes_per_block = (
            ho_cfg.num_layers
            * 2
            * ho_cfg.block_size
            * ho_cfg.num_kv_heads
            * ho_cfg.head_dim
            * jnp.dtype(ho_cfg.dtype).itemsize
        )
        prefix_blocks = HO_POOL_BLOCKS // 2
        measured_load_s = staged["load_s"]
        prefill_rate = (
            TOTAL_TOKENS / t_miss
            if t_miss and t_miss > 0
            else TOTAL_TOKENS / CAL_MISS_S
        )
        advisor = ComputeOrLoadAdvisor(
            AdvisorConfig(
                bytes_per_block=pool_bytes_per_block,
                block_tokens=BLOCK_SIZE,
                prefill_tokens_per_s=prefill_rate,
            )
        )
        # Feed the estimator with THIS regime's measured transfers.
        advisor.observe_load(nbytes, staged["load_s"])
        advisor.observe_load(oneshot["nbytes"], oneshot["load_s"])
        advisor.observe_store(nbytes, staged["store_s"])
        advice = advisor.advise(prefix_blocks)
        suffix_s = SUFFIX_TOKENS / prefill_rate
        ttft_hit = measured_load_s + suffix_s
        ttft_recompute = (
            prefix_blocks * BLOCK_SIZE + SUFFIX_TOKENS
        ) / prefill_rate
        hybrid_core = (
            advice.hybrid_s
            if advice.hybrid_s is not None
            else min(advice.load_s, advice.recompute_s)
        )
        ttft_hybrid = hybrid_core + suffix_s
        result["ttft"] = {
            "prefix_blocks": prefix_blocks,
            "prefix_bytes": prefix_blocks * pool_bytes_per_block,
            "rtt_source": "measured_staging_path",
            "prefill_tokens_per_s": round(prefill_rate, 1),
            "prefill_source": (
                "measured" if t_miss and t_miss > 0 else "calibrated"
            ),
            "ttft_offload_hit_s": round(ttft_hit, 4),
            "ttft_recompute_s": round(ttft_recompute, 4),
            "ttft_hybrid_s": round(ttft_hybrid, 4),
            "advice": advice.to_dict(),
            "advisor_rtt": advisor.stats()["rtt"],
        }
        # The compact headline block the driver sees (emit_result).
        result["headline"] = {
            "staged_store_mbps": result["staging_ab"]["staged_store_mbps"],
            "staged_load_mbps": result["staging_ab"]["staged_load_mbps"],
            "parity": result["staging_ab"]["parity"],
            "chips": len(devices),
            "best_lanes": best["lanes_per_chip"],
            "best_aggregate_mbps": best["aggregate_mbps"],
            "ttft_hit_s": result["ttft"]["ttft_offload_hit_s"],
            "ttft_recompute_s": result["ttft"]["ttft_recompute_s"],
            "ttft_hybrid_s": result["ttft"]["ttft_hybrid_s"],
            "advice": advice.action,
        }
        return result
    finally:
        shutil.rmtree(root, ignore_errors=True)


def maybe_bench_host_offload(
    context: str, t_miss: Optional[float] = None
) -> dict:
    """bench_host_offload under the degrade contract."""
    if _over_budget(reserve_s=90.0):
        return {"truncated": True}
    _progress(f"{context}: host_offload regime (staging data plane)")
    try:
        return bench_host_offload(t_miss)
    except Exception as exc:  # noqa: BLE001 — optional layer
        detail = f"{type(exc).__name__}: {exc}"
        _progress(f"host_offload failed: {detail}")
        return {"error": detail[:300]}


# ---------------- event_storm: fleet-scale event-plane regime ----------

_STORM_TINY = bool(os.environ.get("KVTPU_BENCH_TINY"))
STORM_PODS = int(
    os.environ.get(
        "KVTPU_BENCH_STORM_PODS", "64" if _STORM_TINY else "1000"
    )
)
STORM_PUBLISH_S = _env_float(
    "KVTPU_BENCH_STORM_S", 1.0 if _STORM_TINY else 3.0
)
STORM_BLOCK_SIZE = 16
# Offered load for the throughput cells, msgs/s across the whole
# fleet.  Must exceed the apply capacity of every cell so each one is
# measured at saturation (sustained capacity), not at whatever rate
# the load generator happened to reach.
STORM_RATE = _env_float("KVTPU_BENCH_STORM_RATE", 6000.0)


def _hist_stats(hist) -> tuple:
    """(sum, count) of an unlabeled prometheus histogram."""
    total = count = 0.0
    for metric in hist.collect():
        for sample in metric.samples:
            if sample.name.endswith("_sum"):
                total = sample.value
            elif sample.name.endswith("_count"):
                count = sample.value
    return total, count


def _pod_labeled_totals(counter, pods) -> dict:
    """pod -> value for a pod-labeled counter, 0.0 when never touched."""
    wanted = set(pods)
    out = {pod: 0.0 for pod in wanted}
    for metric in counter.collect():
        for sample in metric.samples:
            if sample.name.endswith("_total"):
                pod = sample.labels.get("pod")
                if pod in wanted:
                    out[pod] = sample.value
    return out


def _event_plane_threads() -> int:
    """Threads belonging to the event plane: pollers (consolidated),
    legacy per-pod subscriber threads (baseline), pool workers, and the
    resync worker."""
    prefixes = ("kvtpu-evplane-", "kvtpu-events-", "kvtpu-zmq-")
    return sum(
        1
        for t in threading.enumerate()
        if any(t.name.startswith(p) for p in prefixes)
    )


class _StormFleet:
    """N simulated publishers over inproc: raw PUB sockets + per-pod
    seq counters, sending pre-encoded payloads so the publish side
    never bottlenecks the measurement (the apply path is the subject).
    """

    def __init__(self, context, n_pods: int, run_id: str) -> None:
        import struct as _struct

        self._struct = _struct
        self.context = context
        self.pods = [f"storm-{run_id}-{i}" for i in range(n_pods)]
        self.endpoints = {
            pod: f"inproc://{pod}" for pod in self.pods
        }
        self.socks = {}
        for pod in self.pods:
            sock = context.socket(zmq.PUB)
            sock.setsockopt(zmq.LINGER, 0)
            sock.bind(self.endpoints[pod])
            self.socks[pod] = sock
        self.topics = {
            pod: f"kv@{pod}@{MODEL_NAME}".encode() for pod in self.pods
        }
        self.seq = {pod: 0 for pod in self.pods}
        # One shared payload: distinct engine keys per pod are not
        # needed for the throughput cells (shared blocks across pods
        # are realistic), and the apply-side token hashing dominates
        # regardless.
        tokens = list(range(2 * STORM_BLOCK_SIZE))
        self.payload = EventBatch(
            ts=0.0,
            events=[
                BlockStored(
                    block_hashes=[0xBEEF, 0xCAFE],
                    parent_block_hash=None,
                    token_ids=tokens,
                    block_size=STORM_BLOCK_SIZE,
                )
            ],
        ).encode()

    def publish_raw(self, pod: str, payload=None) -> None:
        self.seq[pod] += 1
        self.socks[pod].send_multipart(
            [
                self.topics[pod],
                self._struct.pack(">Q", self.seq[pod]),
                payload if payload is not None else self.payload,
            ]
        )

    def skip_seq(self, pod: str, count: int) -> None:
        self.seq[pod] += count

    def close(self) -> None:
        for sock in self.socks.values():
            sock.close()


def _storm_pool(index=None, start=True, **kw):
    index = index or InMemoryIndex(InMemoryIndexConfig(size=2_000_000))
    db = ChunkedTokenDatabase(
        TokenProcessorConfig(block_size=STORM_BLOCK_SIZE)
    )
    pool = Pool(index, db, PoolConfig(**kw))
    if start:
        pool.start()
    return pool, index, db


def _wait_join(fleet, pods, seen, deadline_s: float = 60.0) -> int:
    """Publish warmup rounds until every pod's subscription is live
    (PUB/SUB is lossy pre-join); returns pods joined."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline and len(seen) < len(pods):
        for pod in pods:
            if pod not in seen:
                fleet.publish_raw(pod)
        time.sleep(0.05)
    return len(seen)


# Standalone publisher process for the throughput cells.  Publishing
# must happen OUTSIDE the measured process: in production the
# publishers are remote vLLM pods, and an in-process load generator
# shares the GIL with the subscription layer under test — under the
# thread-per-pod baseline its 1000+ threads starve the generator until
# offered load collapses to whatever the baseline can absorb, and the
# A/B degenerates to comparing publish rates.  SNDHWM=0 so a saturated
# cell backs up into the publisher's buffers instead of dropping
# (drops would read as forced seq gaps and poison the gap metrics).
_STORM_PUBLISHER_SRC = r"""
import json, os, struct, sys, time
import zmq

spec = json.load(open(sys.argv[1]))
go_path = sys.argv[2]
endpoints = spec["endpoints"]
topics = {pod: t.encode() for pod, t in spec["topics"].items()}
payload = bytes.fromhex(spec["payload_hex"])
rate = float(spec["rate"])
deadline = time.monotonic() + float(spec["duration"])

ctx = zmq.Context()
ctx.set(zmq.MAX_SOCKETS, max(4096, 2 * len(endpoints)))
socks = {}
for pod, endpoint in endpoints.items():
    s = ctx.socket(zmq.PUB)
    s.setsockopt(zmq.LINGER, 0)
    s.setsockopt(zmq.SNDHWM, 0)
    s.bind(endpoint)
    socks[pod] = s
seq = {pod: 0 for pod in endpoints}
pods = list(endpoints)
pass_s = len(pods) / rate if rate else 0.0
# Warmup: one gentle pass per 0.5s until the parent (having seen a
# message from every pod) drops the go-file — joining at full offered
# load would saturate a slow cell before its fleet ever finished
# subscribing.  Then publish at the saturation rate.
while time.monotonic() < deadline:
    go = os.path.exists(go_path)
    t0 = time.monotonic()
    for pod in pods:
        seq[pod] += 1
        socks[pod].send_multipart(
            [topics[pod], struct.pack(">Q", seq[pod]), payload]
        )
    sleep_s = (pass_s if go else 0.5) - (time.monotonic() - t0)
    if sleep_s > 0:
        time.sleep(sleep_s)
for s in socks.values():
    s.close()
ctx.term()
"""


def _spawn_storm_publisher(
    workdir: str,
    endpoints: Dict[str, str],
    payload: bytes,
    rate: float,
    duration: float,
) -> Tuple[subprocess.Popen, str]:
    spec = {
        "endpoints": endpoints,
        "topics": {
            pod: f"kv@{pod}@{MODEL_NAME}" for pod in endpoints
        },
        "payload_hex": payload.hex(),
        "rate": rate,
        "duration": duration,
    }
    src_path = os.path.join(workdir, "publisher.py")
    spec_path = os.path.join(workdir, "spec.json")
    go_path = os.path.join(workdir, "go")
    with open(src_path, "w") as f:
        f.write(_STORM_PUBLISHER_SRC)
    with open(spec_path, "w") as f:
        json.dump(spec, f)
    proc = subprocess.Popen(
        [sys.executable, src_path, spec_path, go_path],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    return proc, go_path


def _storm_throughput_cell(
    pods, endpoints, payload, attach, detach, publish_s: float
) -> dict:
    """One apply-throughput cell: attach subscriptions for `pods`,
    spawn the external publisher at STORM_RATE (above every cell's
    capacity), wait for join, and measure APPLY completions inside a
    `publish_s` window — the sustained ingest capacity with the
    subscription layer's own overhead (poller vs 1000 threads) on the
    same CPUs.  The backlog left in sockets dies with detach (LINGER
    0); the pool's own backlog is drained after the measurement, not
    counted: folding an unbounded drain tail into the rate made the
    number depend on backlog luck, not capacity.

    The cell also reports the decode-vs-apply stage split
    (µs/message inside the window, from ``Pool.stage_stats``) so the
    bottleneck is attributable straight from the BENCH artifact."""
    from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS

    pool, _index, _db = _storm_pool(concurrency=4)
    seen = set()
    seen_lock = threading.Lock()

    def sink(message):
        with seen_lock:
            seen.add(message.pod_identifier)
        pool.add_task(message)

    def sink_batch(messages):
        with seen_lock:
            for message in messages:
                seen.add(message.pod_identifier)
        pool.add_tasks(messages)

    attach(sink, sink_batch)
    workdir = tempfile.mkdtemp(prefix="kvtpu-storm-pub-")
    proc = None
    detached = False
    try:
        proc, go_path = _spawn_storm_publisher(
            workdir,
            endpoints,
            payload,
            STORM_RATE,
            duration=150.0 + publish_s,
        )
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline and len(seen) < len(pods):
            time.sleep(0.05)
        joined = len(seen)
        # Full join reached (at gentle warmup load): release the
        # saturation rate, give the backlog a moment to build, then
        # measure the steady state.
        with open(go_path, "w"):
            pass
        time.sleep(1.0)
        drained_before, _ = _hist_stats(METRICS.kvevents_batch_size)
        dropped_before = counter_total(METRICS.kvevents_dropped)
        stages_before = pool.stage_stats()
        threads = _event_plane_threads()

        t0 = time.perf_counter()
        time.sleep(publish_s)
        elapsed = time.perf_counter() - t0
        drained_after, _ = _hist_stats(METRICS.kvevents_batch_size)
        stages_after = pool.stage_stats()
        applied = drained_after - drained_before
        # Detach BEFORE draining the pool backlog: the subscription
        # layer's overhead belongs in the window, not in the cleanup.
        detach()
        detached = True
        proc.terminate()
        proc.wait(timeout=30)
        pool.drain()

        def stage_us(stage):
            msgs = (
                stages_after[f"{stage}_msgs"]
                - stages_before[f"{stage}_msgs"]
            )
            if not msgs:
                return None
            seconds = (
                stages_after[f"{stage}_s"] - stages_before[f"{stage}_s"]
            )
            return round(seconds / msgs * 1e6, 1)

        return {
            "pods": len(pods),
            "pods_joined": joined,
            "offered_msgs_per_sec": STORM_RATE,
            "applied_msgs_in_window": int(applied),
            "apply_msgs_per_sec": round(applied / elapsed, 1),
            "decode_us_per_msg": stage_us("decode"),
            "apply_us_per_msg": stage_us("apply"),
            "dropped": int(
                counter_total(METRICS.kvevents_dropped) - dropped_before
            ),
            "event_plane_threads": threads,
            "window_s": round(elapsed, 2),
        }
    finally:
        if not detached:
            detach()
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        pool.shutdown()
        shutil.rmtree(workdir, ignore_errors=True)


# Offered load for the replica-local ingestion cells.  Must exceed the
# AGGREGATE capacity of the largest replica set so scaling is measured
# at saturation — with the fast lane a single ingestor can absorb the
# default storm rate, which would clamp every cell to the offered load
# and read as "no scaling".
STORM_RI_RATE = _env_float("KVTPU_BENCH_STORM_RI_RATE", 24000.0)

# One replica-local ingestor as its own PROCESS (own GIL, own poller
# pool + kvevents pool + index slice — the deployment shape of
# CLUSTER_LOCAL_INGEST).  Subscribes to its pod slice, reports joins,
# waits for the go-file, measures applies inside the window, writes a
# result JSON.  Spawned by _storm_replica_local_cell.
_STORM_INGESTOR_SRC = r"""
import json, os, sys, threading, time

spec = json.load(open(sys.argv[1]))
sys.path.insert(0, spec["repo_root"])
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import zmq

from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    InMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    InMemoryIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import Pool, PoolConfig
from llm_d_kv_cache_manager_tpu.kvevents.poller import (
    ChannelConfig,
    PollerPool,
    PollerPoolConfig,
)
from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS

endpoints = spec["endpoints"]
context = zmq.Context()
context.set(zmq.MAX_SOCKETS, max(1024, 2 * len(endpoints) + 64))
index = InMemoryIndex(InMemoryIndexConfig(size=2_000_000))
db = ChunkedTokenDatabase(
    TokenProcessorConfig(block_size=int(spec["block_size"]))
)
pool = Pool(index, db, PoolConfig(concurrency=int(spec["concurrency"])))
pool.start()
seen = set()
lock = threading.Lock()


def sink(message):
    with lock:
        seen.add(message.pod_identifier)
    pool.add_task(message)


def sink_batch(messages):
    with lock:
        for message in messages:
            seen.add(message.pod_identifier)
    pool.add_tasks(messages)


ppool = PollerPool(
    context=context,
    config=PollerPoolConfig(pollers=1, poll_interval_ms=20),
)
for pod, endpoint in endpoints.items():
    ppool.attach(
        ChannelConfig(endpoint=endpoint, pod_identifier=pod),
        sink,
        sink_batch=sink_batch,
    )

deadline = time.monotonic() + float(spec["join_timeout_s"])
while time.monotonic() < deadline and len(seen) < len(endpoints):
    time.sleep(0.05)
with open(spec["joined_path"], "w") as f:
    f.write(str(len(seen)))
deadline = time.monotonic() + 150
while time.monotonic() < deadline and not os.path.exists(spec["go_path"]):
    time.sleep(0.02)
time.sleep(1.0)


def hist_sum(hist):
    total = 0.0
    for metric in hist.collect():
        for sample in metric.samples:
            if sample.name.endswith("_sum"):
                total = sample.value
    return total


before = hist_sum(METRICS.kvevents_batch_size)
t0 = time.perf_counter()
time.sleep(float(spec["window_s"]))
elapsed = time.perf_counter() - t0
applied = hist_sum(METRICS.kvevents_batch_size) - before
with open(spec["result_path"], "w") as f:
    json.dump(
        {
            "pods": len(endpoints),
            "pods_joined": len(seen),
            "applied_msgs_in_window": int(applied),
            "window_s": round(elapsed, 2),
            "apply_msgs_per_sec": round(applied / elapsed, 1),
        },
        f,
    )
ppool.shutdown()
pool.shutdown()
context.term()
"""


def _storm_replica_local_cell(
    fleet, storm_endpoints: Dict[str, str], window: float
) -> dict:
    """Replica-local ingestion scaling: the same 1000-pod fleet
    ingested by 1 vs 3 ingestor PROCESSES (each its own GIL), the pod
    set sliced by the production rendezvous slicer
    (``cluster.ingest.pod_owner``).  Offered load (STORM_RI_RATE) sits
    above the aggregate capacity of the largest set so every cell is
    measured at saturation; the aggregate apply rate across replicas
    is the headline, ``scaling_1_to_3`` the claim.  ``cpu_count``
    rides along because process-level scaling is physically bounded by
    the cores available to the bench box."""
    from llm_d_kv_cache_manager_tpu.cluster.ingest import pod_owner
    from llm_d_kv_cache_manager_tpu.cluster.ring import HashRing

    repo_root = os.path.dirname(os.path.abspath(__file__))
    result: dict = {
        "offered_msgs_per_sec": STORM_RI_RATE,
        "cpu_count": os.cpu_count(),
    }
    for n_replicas in (1, 3):
        _progress(
            f"event_storm: replica-local ingestion, {n_replicas} replicas"
        )
        ring = HashRing([f"ingest-{i}" for i in range(n_replicas)])
        slices: Dict[str, Dict[str, str]] = {r: {} for r in ring.members}
        for pod, endpoint in storm_endpoints.items():
            slices[pod_owner(ring, pod)][pod] = endpoint
        workdir = tempfile.mkdtemp(prefix="kvtpu-storm-ri-")
        ingestors = []
        publisher = None
        try:
            go_path = os.path.join(workdir, "go")
            src_path = os.path.join(workdir, "ingestor.py")
            with open(src_path, "w") as f:
                f.write(_STORM_INGESTOR_SRC)
            joined_paths = []
            result_paths = []
            for replica_id in ring.members:
                spec = {
                    "repo_root": repo_root,
                    "endpoints": slices[replica_id],
                    "block_size": STORM_BLOCK_SIZE,
                    "concurrency": 4,
                    "window_s": window,
                    "join_timeout_s": 120.0,
                    "go_path": go_path,
                    "joined_path": os.path.join(
                        workdir, f"{replica_id}.joined"
                    ),
                    "result_path": os.path.join(
                        workdir, f"{replica_id}.json"
                    ),
                }
                joined_paths.append(spec["joined_path"])
                result_paths.append(spec["result_path"])
                spec_path = os.path.join(workdir, f"{replica_id}.spec")
                with open(spec_path, "w") as f:
                    json.dump(spec, f)
                ingestors.append(
                    subprocess.Popen(
                        [sys.executable, src_path, spec_path],
                        stdout=subprocess.DEVNULL,
                        stderr=subprocess.DEVNULL,
                    )
                )
            publisher, _pub_go = _spawn_storm_publisher(
                workdir,
                storm_endpoints,
                fleet.payload,
                STORM_RI_RATE,
                duration=200.0 + window,
            )
            # _spawn_storm_publisher hardcodes its go file inside
            # workdir — the same go_path the ingestor specs point at,
            # so one touch releases saturation AND the measurement.
            deadline = time.monotonic() + 130.0
            while time.monotonic() < deadline and not all(
                os.path.exists(p) for p in joined_paths
            ):
                time.sleep(0.1)
            with open(go_path, "w"):
                pass
            deadline = time.monotonic() + 60.0 + window
            for proc in ingestors:
                remaining = max(1.0, deadline - time.monotonic())
                try:
                    proc.wait(timeout=remaining)
                except subprocess.TimeoutExpired:
                    proc.kill()
            per_replica = []
            for path in result_paths:
                try:
                    with open(path) as f:
                        per_replica.append(json.load(f))
                except (OSError, ValueError):
                    per_replica.append(None)
            rates = [
                cell["apply_msgs_per_sec"]
                for cell in per_replica
                if cell
            ]
            result[f"replicas_{n_replicas}"] = {
                "per_replica": per_replica,
                "aggregate_apply_msgs_per_sec": round(sum(rates), 1),
                "pods_joined": sum(
                    cell["pods_joined"] for cell in per_replica if cell
                ),
            }
        finally:
            for proc in ingestors:
                if proc.poll() is None:
                    proc.kill()
                    proc.wait(timeout=10)
            if publisher is not None and publisher.poll() is None:
                publisher.terminate()
                try:
                    publisher.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    publisher.kill()
            shutil.rmtree(workdir, ignore_errors=True)
    agg1 = result["replicas_1"]["aggregate_apply_msgs_per_sec"]
    agg3 = result["replicas_3"]["aggregate_apply_msgs_per_sec"]
    result["scaling_1_to_3"] = round(agg3 / agg1, 2) if agg1 else None
    return result


def bench_event_storm(
    n_pods: Optional[int] = None, publish_s: Optional[float] = None
) -> dict:
    """detail.event_storm regime (docs/event-plane.md): the full
    subscribe -> demux -> shard-lane -> batched-apply path at fleet
    scale, device-free.

    Cells: consolidated poller (pollers=1 and pollers=4) vs the legacy
    thread-per-pod baseline at equal publish load (apply throughput +
    event-plane thread count); per-pod flow control on vs off under a
    deliberately chatty pod (fairness: an under-budget pod must never
    be shed); and a forced 10%-gap storm with inventory resync
    (gap-recovery wall time, per-pod staleness window, post-resync
    index consistency vs the publishers' ground truth)."""
    from llm_d_kv_cache_manager_tpu.kvevents.poller import (
        ChannelConfig,
        PollerPool,
        PollerPoolConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvevents.resync import (
        CallableInventorySource,
        InventoryBlock,
        PodInventory,
        ResyncConfig,
        ResyncManager,
    )
    from llm_d_kv_cache_manager_tpu.kvevents.zmq_subscriber import (
        ZMQSubscriber,
        ZMQSubscriberConfig,
    )
    from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS

    n = STORM_PODS if n_pods is None else n_pods
    window = STORM_PUBLISH_S if publish_s is None else publish_s
    run_id = uuid.uuid4().hex[:8]
    # Dedicated context: the fleet needs ~2N sockets and libzmq's
    # default max_sockets is 1023 — at N=1000 most SUB opens would
    # fail (and surface only as endless reconnect backoff).
    context = zmq.Context(2)
    context.set(zmq.MAX_SOCKETS, max(4096, 4 * n))
    fleet = _StormFleet(context, n, run_id)
    # The throughput cells subscribe over ipc to an EXTERNAL publisher
    # process (see _STORM_PUBLISHER_SRC); the inproc fleet above feeds
    # the gap/fairness logic cells, where publish volume is tiny.
    ipc_dir = tempfile.mkdtemp(prefix="kvtpu-storm-ipc-")
    storm_endpoints = {
        pod: f"ipc://{ipc_dir}/p{i}" for i, pod in enumerate(fleet.pods)
    }
    result: dict = {
        "n_pods": n,
        "publish_seconds": window,
        "block_size": STORM_BLOCK_SIZE,
        "offered_rate_msgs_per_sec": STORM_RATE,
    }
    try:
        # -- consolidated poller cells --------------------------------
        for pollers in (1, 4):
            _progress(
                f"event_storm: consolidated pollers={pollers}, N={n}"
            )
            ppool = PollerPool(
                context=context,
                config=PollerPoolConfig(
                    pollers=pollers, poll_interval_ms=20
                ),
            )
            channels = []

            def attach(sink, sink_batch, ppool=ppool, channels=channels):
                for pod in fleet.pods:
                    channels.append(
                        ppool.attach(
                            ChannelConfig(
                                endpoint=storm_endpoints[pod],
                                pod_identifier=pod,
                            ),
                            sink,
                            sink_batch=sink_batch,
                        )
                    )

            def detach(ppool=ppool, channels=channels):
                for channel in channels:
                    ppool.detach(channel)
                ppool.shutdown()

            cell = _storm_throughput_cell(
                fleet.pods,
                storm_endpoints,
                fleet.payload,
                attach,
                detach,
                window,
            )
            # The headline thread claim: the event plane is
            # pollers + pool workers, independent of N.
            cell["thread_ceiling"] = pollers + 4
            cell["thread_ceiling_ok"] = (
                cell["event_plane_threads"] <= cell["thread_ceiling"]
            )
            result[f"consolidated_pollers_{pollers}"] = cell

        # -- legacy thread-per-pod baseline ---------------------------
        _progress(f"event_storm: thread-per-pod baseline, N={n}")
        subscribers = []

        def attach_baseline(sink, _sink_batch):
            # The legacy subscriber has no batched sink — that IS the
            # baseline being measured.
            for pod in fleet.pods:
                sub = ZMQSubscriber(
                    ZMQSubscriberConfig(
                        endpoint=storm_endpoints[pod],
                        pod_identifier=pod,
                    ),
                    sink,
                    context=context,
                )
                sub.start()
                subscribers.append(sub)

        def detach_baseline():
            for sub in subscribers:
                sub._stop.set()
            for sub in subscribers:
                sub.stop()

        baseline = _storm_throughput_cell(
            fleet.pods,
            storm_endpoints,
            fleet.payload,
            attach_baseline,
            detach_baseline,
            window,
        )
        result["baseline_thread_per_pod"] = baseline
        consolidated = result["consolidated_pollers_1"]
        result["speedup_vs_thread_baseline"] = (
            round(
                consolidated["apply_msgs_per_sec"]
                / baseline["apply_msgs_per_sec"],
                2,
            )
            if baseline["apply_msgs_per_sec"]
            else None
        )

        # Non-inversion regression guard (BENCH_r06: pollers=4 applied
        # 324 msg/s vs 519 at pollers=1 — the O(lanes) shed scan under
        # the shard lock convoyed pollers against workers).  Apply rate
        # must be monotone-ish in pollers: a 0.85 tolerance absorbs
        # scheduler noise at saturation (the seed inversion sat at
        # 0.62x, far below it).
        r1 = consolidated["apply_msgs_per_sec"]
        r4 = result["consolidated_pollers_4"]["apply_msgs_per_sec"]
        result["poller_scaling"] = {
            "pollers_1_sps": r1,
            "pollers_4_sps": r4,
            "ratio_4_vs_1": round(r4 / r1, 3) if r1 else None,
            "monotone_tolerance": 0.85,
            "monotone_ok": bool(r1 and r4 >= 0.85 * r1),
        }

        # -- fairness: per-pod budget on vs off ------------------------
        result["fairness"] = _storm_fairness_cells(
            context, fleet, run_id
        )

        # -- forced gap storm + resync --------------------------------
        result["gap_storm"] = _storm_gap_cell(
            context,
            fleet,
            METRICS,
            CallableInventorySource,
            InventoryBlock,
            PodInventory,
            ResyncConfig,
            ResyncManager,
        )

        # -- replica-local ingestion scaling --------------------------
        result["replica_local"] = _storm_replica_local_cell(
            fleet, storm_endpoints, window
        )

        # -- profiler A/B on the apply path ---------------------------
        result["profiler_ab"] = _storm_profiler_ab(fleet.payload)

        # -- capture A/B on the apply path ----------------------------
        result["capture_ab"] = _storm_capture_ab(fleet.payload)
        return result
    finally:
        fleet.close()
        context.term()
        shutil.rmtree(ipc_dir, ignore_errors=True)


def _storm_profiler_ab(payload: bytes, rounds: int = 2) -> dict:
    """Profiler on-vs-off A/B on the decode+apply hot path
    (obs/profiler.py at its DEFAULT rate; docs/observability.md).

    In-process by design: the subject is the sampler thread's cost to
    the apply loop, and sockets would re-introduce the publisher-side
    noise the external-process cells exist to avoid.  Pre-built
    messages ride the batched sink (``add_tasks``: lock-free
    pre-decode + one shard round trip, the production poller shape)
    and the pool is drained to empty; apply rate = messages / wall.
    Alternating best-of damps scheduler bias, as in the trace A/B.
    """
    from llm_d_kv_cache_manager_tpu.obs.profiler import (
        ProfilerConfig,
        SamplingProfiler,
    )

    n_msgs = 4000
    n_pods = 16

    def one_side() -> float:
        pool, _index, _db = _storm_pool(concurrency=4)
        messages = [
            Message(
                topic=f"kv@ab-{i % n_pods}@{MODEL_NAME}",
                payload=payload,
                pod_identifier=f"ab-{i % n_pods}",
                model_name=MODEL_NAME,
                seq=i // n_pods + 1,
            )
            for i in range(n_msgs)
        ]
        t0 = time.perf_counter()
        for start in range(0, n_msgs, 64):
            pool.add_tasks(messages[start:start + 64])
        pool.drain()
        elapsed = time.perf_counter() - t0
        pool.shutdown()
        return round(n_msgs / elapsed, 1) if elapsed else 0.0

    prof = SamplingProfiler(ProfilerConfig())  # shipped default hz
    best = {True: 0.0, False: 0.0}
    for ab_round in range(rounds):
        order = (True, False) if ab_round % 2 == 0 else (False, True)
        for prof_on in order:
            if prof_on:
                prof.start()
            else:
                prof.close()
            best[prof_on] = max(best[prof_on], one_side())
    prof.close()
    overhead = (
        max(0.0, (best[False] - best[True]) / best[False])
        if best[False]
        else 0.0
    )
    return {
        "hz": prof.config.hz,
        "n_msgs": n_msgs,
        "profiler_on_msgs_per_sec": best[True],
        "profiler_off_msgs_per_sec": best[False],
        "overhead": round(overhead, 4),
        "bound": PROFILE_OVERHEAD_BOUND,
        "within_bound": overhead <= PROFILE_OVERHEAD_BOUND,
    }


def _storm_capture_ab(payload: bytes, rounds: int = 5) -> dict:
    """Input-flight-recorder on-vs-off A/B on the decode+apply hot
    path (obs/capture.py; ISSUE 15's ≤3% acceptance bound) — the same
    in-process batched-sink shape as ``_storm_profiler_ab``, with the
    capture tap (payload stash + compact ring append per message in
    ``Pool.add_tasks``) attached on one side.  Longer runs and more
    best-of rounds than the profiler cell: the tap's true cost
    (~0.5µs/msg against a ~25µs/msg all-in-process apply) sits near
    this container class's run-to-run noise floor."""
    from llm_d_kv_cache_manager_tpu.obs.capture import (
        CaptureConfig,
        InputCaptureRecorder,
    )

    n_msgs = 8000
    n_pods = 16

    def one_burst(pool) -> float:
        messages = [
            Message(
                topic=f"kv@cab-{i % n_pods}@{MODEL_NAME}",
                payload=payload,
                pod_identifier=f"cab-{i % n_pods}",
                model_name=MODEL_NAME,
                seq=i // n_pods + 1,
            )
            for i in range(n_msgs)
        ]
        t0 = time.perf_counter()
        for start in range(0, n_msgs, 64):
            pool.add_tasks(messages[start:start + 64])
        pool.drain()
        elapsed = time.perf_counter() - t0
        return round(n_msgs / elapsed, 1) if elapsed else 0.0

    # Shipped-default config: the bound is a claim about production
    # settings, and an oversized ring just measures gc scans of its
    # own retained objects instead of the tap.
    recorder = InputCaptureRecorder(CaptureConfig())
    # One WARM pool per side, reused across rounds: per-run pool
    # construction (worker-thread startup, cold shard caches) costs
    # more run-to-run variance than the tap itself.
    pool_off, _index_off, _db_off = _storm_pool(concurrency=4)
    pool_on, _index_on, _db_on = _storm_pool(concurrency=4)
    pool_on.set_capture(recorder)
    best = {True: 0.0, False: 0.0}
    try:
        one_burst(pool_off)  # warmup both sides
        one_burst(pool_on)
        for ab_round in range(rounds):
            order = (
                (True, False) if ab_round % 2 == 0 else (False, True)
            )
            for cap_on in order:
                best[cap_on] = max(
                    best[cap_on],
                    one_burst(pool_on if cap_on else pool_off),
                )
    finally:
        pool_off.shutdown()
        pool_on.shutdown()
    ring = recorder.status()["sources"]["kvevents"]
    overhead = (
        max(0.0, (best[False] - best[True]) / best[False])
        if best[False]
        else 0.0
    )
    return {
        "n_msgs": n_msgs,
        "capture_on_msgs_per_sec": best[True],
        "capture_off_msgs_per_sec": best[False],
        "overhead": round(overhead, 4),
        "bound": CAPTURE_OVERHEAD_BOUND,
        "within_bound": overhead <= CAPTURE_OVERHEAD_BOUND,
        "recorded": ring["appended"],
        "ring_bytes": ring["bytes"],
    }


def _storm_fairness_cells(context, fleet, run_id: str) -> dict:
    """Deterministic fairness A/B at the pool layer: 8 quiet pods
    enqueue 5 messages each (well under the effective budget,
    64 // 9 = 7), then one chatty pod bursts 2000 into the same shard
    of an unstarted pool (so the backlog is real, as in a storm).  With
    per-pod flow control ON the chatty pod pays for its own flood and
    no quiet message may be shed; OFF (legacy global FIFO, drop-oldest)
    the quiet pods — whose messages are the oldest — are shed first:
    exactly the starvation mode the lanes exist to kill."""
    from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS

    chatty = "storm-fair-chatty"
    quiet = [f"storm-fair-quiet-{i}" for i in range(8)]
    payload = fleet.payload
    cells = {}
    for mode, per_pod in (("budget_on", True), ("budget_off", False)):
        _progress(f"event_storm: fairness {mode}")
        # Enqueue-only (never started): the cell measures shedding
        # against a standing backlog, the storm's worst case.
        pool, _index, _db = _storm_pool(
            start=False,
            concurrency=1,
            max_queue_depth=64,
            per_pod_flow_control=per_pod,
        )
        shed_before = _pod_labeled_totals(
            METRICS.kvevents_pod_shed, [chatty] + quiet
        )

        def enqueue(pod, i):
            pool.add_task(
                Message(
                    topic=f"kv@{pod}@{MODEL_NAME}",
                    payload=payload,
                    pod_identifier=pod,
                    model_name=MODEL_NAME,
                    seq=i,
                )
            )

        for i in range(5):
            for pod in quiet:
                enqueue(pod, i)
        for i in range(2000):
            enqueue(chatty, i)
        shed_after = _pod_labeled_totals(
            METRICS.kvevents_pod_shed, [chatty] + quiet
        )
        quiet_shed = sum(shed_after[p] - shed_before[p] for p in quiet)
        quiet_queued = sum(
            depth
            for q in pool._queues
            for pod, depth in q.lane_depths().items()
            if pod in quiet
        )
        cells[mode] = {
            "chatty_shed": int(shed_after[chatty] - shed_before[chatty]),
            "quiet_shed": int(quiet_shed),
            "quiet_queued": quiet_queued,
        }
        pool.start()
        pool.drain()
        pool.shutdown()
    cells["property_holds"] = (
        cells["budget_on"]["quiet_shed"] == 0
        and cells["budget_on"]["quiet_queued"] == 40
    )
    return cells


def _storm_gap_cell(
    context,
    fleet,
    METRICS,
    CallableInventorySource,
    InventoryBlock,
    PodInventory,
    ResyncConfig,
    ResyncManager,
) -> dict:
    """Force seq gaps on 10% of the fleet and measure the resync loop:
    recovery wall time, staleness window, post-resync consistency."""
    from llm_d_kv_cache_manager_tpu.kvevents.poller import (
        ChannelConfig,
        PollerPool,
        PollerPoolConfig,
    )

    _progress("event_storm: 10% gap storm + resync")
    rng = random.Random(7)
    gap_pods = fleet.pods[: max(1, len(fleet.pods) // 10)]
    pool, index, db = _storm_pool(concurrency=4)

    # Ground truth: each pod "stores" one private 2-block chain; the
    # inventory source serves it back on resync.
    truth = {}
    for pod in fleet.pods:
        base = rng.randrange(1, 1 << 30)
        tokens = [
            (base + j) % 30000 + 1 for j in range(2 * STORM_BLOCK_SIZE)
        ]
        truth[pod] = InventoryBlock(
            block_hashes=[base * 2 + 1, base * 2 + 2],
            token_ids=tokens,
            block_size=STORM_BLOCK_SIZE,
            medium="hbm",
        )

    source = CallableInventorySource(
        lambda pod: PodInventory(
            pod_identifier=pod,
            model_name=MODEL_NAME,
            blocks=[truth[pod]],
        )
    )
    resync = ResyncManager(
        pool, source, ResyncConfig(apply_timeout_s=60.0)
    )
    resync.start()

    seen = set()
    seen_lock = threading.Lock()

    def sink(message):
        with seen_lock:
            seen.add(message.pod_identifier)
        pool.add_task(message)

    ppool = PollerPool(
        context=context,
        config=PollerPoolConfig(pollers=1, poll_interval_ms=10),
    )
    manager_channels = {
        pod: ppool.attach(
            ChannelConfig(
                endpoint=fleet.endpoints[pod], pod_identifier=pod
            ),
            sink,
            on_gap=resync.gap_listener,
        )
        for pod in fleet.pods
    }
    try:
        _wait_join(fleet, fleet.pods, seen)
        # Phase 1: every pod stores its ground-truth chain.
        for pod in fleet.pods:
            block = truth[pod]
            fleet.publish_raw(
                pod,
                EventBatch(
                    ts=0.0,
                    events=[
                        BlockStored(
                            block_hashes=list(block.block_hashes),
                            parent_block_hash=None,
                            token_ids=list(block.token_ids),
                            block_size=block.block_size,
                            medium="hbm",
                        )
                    ],
                ).encode(),
            )
        time.sleep(0.5)
        pool.drain()

        staleness_sum0, staleness_n0 = _hist_stats(
            METRICS.kvevents_resync_staleness
        )
        # Phase 2: force a gap on 10% of pods (skip 5 seqs, then one
        # live message so the tracker sees the jump).
        t0 = time.perf_counter()
        for pod in gap_pods:
            fleet.skip_seq(pod, 5)
            fleet.publish_raw(pod)
        # Recovery = every forced gap DETECTED (resync attempted) and
        # the suspect set drained again — not just "no suspects yet".
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            stats = resync.stats()
            outcomes = stats["resyncs_ok"] + stats["resyncs_failed"]
            if outcomes >= len(gap_pods) and not stats["suspect"]:
                break
            time.sleep(0.05)
        recovery_s = time.perf_counter() - t0
        stats = resync.stats()
        staleness_sum1, staleness_n1 = _hist_stats(
            METRICS.kvevents_resync_staleness
        )
        resynced = int(staleness_n1 - staleness_n0)

        # Post-resync consistency: every gapped pod's ground-truth
        # chain must be claimed by exactly that pod again.
        consistent = 0
        for pod in gap_pods:
            keys = db.tokens_to_kv_block_keys(
                EMPTY_BLOCK_HASH, truth[pod].token_ids, MODEL_NAME
            )
            found = index.lookup(keys)
            if set(found) == set(keys) and all(
                any(e.pod_identifier == pod for e in entries)
                for entries in found.values()
            ):
                consistent += 1
        return {
            "gap_pods": len(gap_pods),
            "resynced": resynced,
            "resyncs_failed": stats["resyncs_failed"],
            "still_suspect": len(stats["suspect"]),
            "recovery_wall_s": round(recovery_s, 3),
            "staleness_mean_s": (
                round(
                    (staleness_sum1 - staleness_sum0) / resynced, 4
                )
                if resynced
                else None
            ),
            "post_resync_consistency": (
                round(consistent / len(gap_pods), 4) if gap_pods else None
            ),
        }
    finally:
        for channel in manager_channels.values():
            ppool.detach(channel)
        ppool.shutdown()
        resync.close()
        pool.shutdown()


def maybe_bench_event_storm(context: str) -> dict:
    """bench_event_storm under the degrade contract."""
    if _over_budget(reserve_s=90.0):
        return {"truncated": True}
    _progress(f"{context}: event_storm fleet regime (N={STORM_PODS})")
    try:
        return bench_event_storm()
    except Exception as exc:  # noqa: BLE001 — optional layer
        logger_exc = f"{type(exc).__name__}: {exc}"
        _progress(f"event_storm failed: {logger_exc}")
        return {"error": logger_exc[:300]}


def _routing_percentiles(samples: Sequence[float]) -> Optional[dict]:
    if not samples:
        return None
    return {
        "p50": round(float(np.percentile(samples, 50)) * 1e6, 1),
        "p99": round(float(np.percentile(samples, 99)) * 1e6, 1),
    }


def emit_cpu_fallback(device_error: str, probe: dict) -> None:
    """No usable device: spend the remaining budget on every
    device-independent layer instead of recording an empty artifact
    (the r4 failure mode: a wedged chip produced value 0.0 and NOTHING
    else, wasting ~600s of remaining budget).

    The virtual-clock matrix (all regimes), the scoring-RPC
    percentiles, and the index/tokenization microbenches need no chip;
    service times come from the last driver-captured on-chip
    measurements (``CAL_MISS_S``/``CAL_HIT_S``, labeled
    ``service_times: "calibrated"``).  The headline stays zeroed — a
    dead tunnel must never be conflated with a measured speedup."""
    # Deliberately NO jax use anywhere below (not even config.update):
    # in the post-probe-wedge path an init thread may still be blocked
    # holding JAX's backend lock, and any jax call here would deadlock
    # behind it.  Everything in this fallback is pure Python/numpy.
    _progress(f"device unavailable ({device_error}); CPU-detail fallback")
    requests, warmup_idx, hashes_list = make_workload()
    t_miss, t_hit = CAL_MISS_S, CAL_HIT_S
    ideal_service = ideal_service_time(t_miss, t_hit, len(requests))
    _progress("fallback: scoring-RPC percentiles")
    routing_samples = measure_routing_micro(
        requests, hashes_list, warmup_idx
    )
    micro = maybe_bench_micro("fallback")
    read_path = maybe_bench_read_path("fallback")
    cache_analytics = maybe_bench_cache_analytics("fallback")
    tiered_churn = maybe_bench_tiered_churn("fallback")
    scaleout_warmup = maybe_bench_scaleout_warmup("fallback")
    event_storm = maybe_bench_event_storm("fallback")
    indexer_restart = maybe_bench_indexer_restart(
        requests, hashes_list, t_miss, t_hit, ideal_service
    )
    replica_scaleout = maybe_bench_replica_scaleout(
        requests, hashes_list, t_miss, t_hit, ideal_service
    )
    _progress("fallback: virtual-clock matrix (calibrated service times)")
    matrix, matrix_truncated = run_matrix(
        requests, hashes_list, t_miss, t_hit, ideal_service, warmup_idx
    )
    _progress("emit (fallback)")
    emit_result(
        {
            "metric": "p50_ttft_speedup_precise_vs_round_robin",
            "value": 0.0,
            "unit": "x",
            "vs_baseline": 0.0,
            "error": f"device unavailable: {device_error}",
            "detail": {
                "device": "cpu",
                "service_times": "calibrated",
                "service_miss_s": round(t_miss, 4),
                "service_hit_s": round(t_hit, 4),
                "routing_precise_us": _routing_percentiles(
                    routing_samples
                ),
                "micro": micro,
                "read_path": read_path,
                "cache_analytics": cache_analytics,
                "tiered_churn": tiered_churn,
                "scaleout_warmup": scaleout_warmup,
                "event_storm": event_storm,
                "indexer_restart": indexer_restart,
                "replica_scaleout": replica_scaleout,
                "requests": len(requests),
                "elapsed_s": round(_elapsed(), 1),
                "budget_s": _BUDGET_S,
                "matrix_truncated": matrix_truncated,
                "matrix": matrix,
            },
        },
        probe,
    )


def main() -> None:
    probe_start = time.monotonic()
    device_error = require_device()
    probe = {
        "outcome": "error" if device_error else "ok",
        "error_class": (
            device_error.split(":")[0][:80] if device_error else None
        ),
        "duration_s": round(time.monotonic() - probe_start, 1),
    }
    # First stdout line: even a run killed mid-flight leaves the probe
    # diagnosis at the head of the capture.
    _probe_status_line(probe)
    if device_error is not None:
        # The artifact must stay parseable AND diagnosable: explicit
        # error, zero headline, full device-independent detail.
        emit_cpu_fallback(device_error, probe)
        return

    _progress(f"device ready ({jax.devices()[0].platform}); init params")
    requests, warmup_idx, hashes_list = make_workload()
    params = llama.init_params(jax.random.PRNGKey(0), CFG)

    # Donate the pool: each pod's ~1.1 GB kv array is updated in place
    # instead of copied per request (halves transient HBM, keeps the
    # copy out of every TTFT sample).
    prefill_full = jax.jit(
        lambda p, t, kv, bt: llama.prefill_paged(p, t, kv, bt, CFG),
        donate_argnums=(2,),
    )
    prefill_suffix = jax.jit(
        lambda p, t, kv, bt: llama.prefill_continue(
            p, t, kv, bt, PREFIX_TOKENS, CFG
        ),
        donate_argnums=(2,),
    )
    # Warm both shapes so compile time stays out of the TTFT samples,
    # and measure per-path service times to place the arrival rate.
    _progress("compile + warm prefill shapes")
    warm = SimPod("warm", params)
    full_ids, _ = warm.alloc(TOTAL_TOKENS // BLOCK_SIZE)
    tok = jnp.zeros((1, TOTAL_TOKENS), jnp.int32)
    t_miss = t_hit = float("inf")
    readback_rtt = 0.0
    for _ in range(2):  # second pass = compiled, warm path
        t0 = time.perf_counter()
        logits, warm.kv = prefill_full(
            params, tok, warm.kv, jnp.asarray([full_ids], jnp.int32)
        )
        int(jnp.argmax(logits[0, -1]))
        t_miss = min(t_miss, time.perf_counter() - t0)
        t0 = time.perf_counter()
        logits, warm.kv = prefill_suffix(
            params,
            tok[:, PREFIX_TOKENS:],
            warm.kv,
            jnp.asarray([full_ids], jnp.int32),
        )
        int(jnp.argmax(logits[0, -1]))
        t_hit = min(t_hit, time.perf_counter() - t0)
        readback_rtt = measure_readback_rtt()
    t_miss = max(t_miss - readback_rtt, 1e-4)
    t_hit = max(t_hit - readback_rtt, 1e-4)

    # detail.kernels: compiled Pallas-vs-XLA at serving shapes, and the
    # decode winner routed into the headline via decode_attention.
    _progress("detail.kernels: Pallas-vs-XLA sweep")
    kernels = bench_kernels(readback_rtt)
    decode_winner = kernels.get("paged_decode", {}).get("winner")
    if decode_winner:
        CFG.decode_attention = decode_winner
        CFG.decode_blocks_per_step = kernels["paged_decode"][
            "blocks_per_step"
        ]
        CFG.decode_mxu_native = kernels["paged_decode"]["mxu_native"]

    # Secondary metric: decode throughput over the warm pod's full
    # 8448-token context (the reference's output-tok/s axis; decode
    # attention is whichever kernel detail.kernels just measured ahead).
    decode_tok_s = None
    decode_truncated = True
    if not _over_budget(reserve_s=120.0):
        decode_truncated = False
        _progress("decode throughput")
        decode = jax.jit(
            lambda p, t, kv, bt, cl: llama.decode_step(
                p, t, kv, bt, cl, CFG
            ),
            donate_argnums=(2,),
        )
        table = jnp.asarray([full_ids], jnp.int32)
        ctx = jnp.asarray([TOTAL_TOKENS], jnp.int32)
        step_tok = jnp.zeros((1,), jnp.int32)
        logits, warm.kv = decode(params, step_tok, warm.kv, table, ctx)
        int(jnp.argmax(logits[0]))  # compile + drain
        decode_steps = 16
        t0 = time.perf_counter()
        for _ in range(decode_steps):
            logits, warm.kv = decode(params, step_tok, warm.kv, table, ctx)
        int(jnp.argmax(logits[0]))
        decode_elapsed = max(
            time.perf_counter() - t0 - readback_rtt, 1e-4
        )
        decode_tok_s = round(decode_steps / decode_elapsed, 1)
        del logits
    del warm

    # detail.mfu: full-prefill throughput vs chip peak.
    mfu = bench_mfu(t_miss)

    # Arrival rate: 70% of the fleet's capacity under *ideal* routing
    # (first request per group misses, the rest hit).  A well-routed
    # fleet is comfortably stable there; a hit-blind scheduler's
    # effective service time is ~t_miss, pushing it past saturation so
    # prefill queues build — the reference's headline mechanism
    # (BASELINE.md §1-2: TTFT seconds-vs-minutes at the same QPS).
    ideal_service = ideal_service_time(t_miss, t_hit, len(requests))
    qps = 0.7 * NUM_PODS / ideal_service

    # Headline: REAL on-device compute per request, across arrival
    # seeds — one Poisson draw has ~±10-20% noise (burned r2->r3), so
    # the reported value is the median seed and the spread is explicit.
    per_seed: List[dict] = []
    routing_samples: List[float] = []
    headline_truncated = False
    for seed in ARRIVAL_SEEDS:
        if per_seed and _over_budget(reserve_s=180.0):
            # ~1 headline seed costs 2 fleet runs of real prefills;
            # report the seeds measured rather than record nothing.
            headline_truncated = True
            _progress(
                f"budget: stopping headline after {len(per_seed)} seed(s)"
            )
            break
        _progress(f"headline seed {seed}: real-compute fleet runs")
        arrivals = poisson_arrivals(qps, len(requests), seed)
        rr_ttfts, rr_hit, _ = run_fleet(
            "round_robin", requests, params, prefill_full,
            prefill_suffix, arrivals, readback_rtt,
        )
        pr_ttfts, pr_hit, pr_routings = run_fleet(
            "precise", requests, params, prefill_full, prefill_suffix,
            arrivals, readback_rtt,
        )
        # Steady-state only, matching the TTFT percentiles below: the
        # warmup requests route against a cold index (cheap lookups,
        # first-call setup) and would bias the scoring-RPC stats.
        routing_samples.extend(
            r for i, r in enumerate(pr_routings) if i not in warmup_idx
        )
        rr_steady = [
            t for i, t in enumerate(rr_ttfts) if i not in warmup_idx
        ]
        pr_steady = [
            t for i, t in enumerate(pr_ttfts) if i not in warmup_idx
        ]
        p50_rr = float(np.percentile(rr_steady, 50))
        p50_pr = float(np.percentile(pr_steady, 50))
        per_seed.append(
            {
                "seed": seed,
                "speedup": round(p50_rr / p50_pr, 3) if p50_pr else 0.0,
                "p50_ttft_precise_s": round(p50_pr, 5),
                "p50_ttft_round_robin_s": round(p50_rr, 5),
                "hit_rate_precise": round(pr_hit, 3),
                "hit_rate_round_robin": round(rr_hit, 3),
            }
        )
    by_speedup = sorted(per_seed, key=lambda s: s["speedup"])
    # Lower-middle for even seed counts: a conservative headline, never
    # the max masquerading as the median.
    median = by_speedup[(len(by_speedup) - 1) // 2]
    speedup = median["speedup"]

    # detail.micro: device-free index/tokenization microbenches —
    # optional like every detail layer per the degrade contract.
    micro = maybe_bench_micro("detail.micro")

    # detail.read_path: scoring-path throughput regime (fast lane on
    # vs off + parity), device-free.
    read_path = maybe_bench_read_path("detail.read_path")

    # detail.cache_analytics: hit-attribution ledger vs ground truth,
    # planted index divergence through the audit plane, analytics
    # overhead A/B — device-free.
    cache_analytics = maybe_bench_cache_analytics("detail.cache_analytics")

    # detail.tiered_churn: predictive-eviction A/B on the churn
    # workload + compute-or-load TTFT (docs/tiering.md), device-free
    # except for the measured readback floor.
    tiered_churn = maybe_bench_tiered_churn(
        "detail.tiered_churn", readback_rtt
    )

    # detail.scaleout_warmup: KV-transfer planning A/B/C — instant-warm
    # scale-out + load-blended routing + priced transfer directives vs
    # route-to-holder vs round-robin (docs/transfer.md), device-free.
    scaleout_warmup = maybe_bench_scaleout_warmup("detail.scaleout_warmup")

    # detail.host_offload: the staging-engine data plane — staged vs
    # one-shot A/B, the MULTICHIP lanes-per-chip sweep, and TTFT
    # offload-hit vs recompute vs advisor-hybrid priced from the
    # measured transfers (docs/host-offload.md).
    host_offload = maybe_bench_host_offload("detail.host_offload", t_miss)

    # detail.event_storm: fleet-scale event-plane regime (consolidated
    # poller vs thread-per-pod, per-pod fairness, gap->resync),
    # device-free.
    event_storm = maybe_bench_event_storm("detail.event_storm")

    # Persistence regime: cold vs warm-recovered routing across an
    # indexer restart (uses the measured service times).
    indexer_restart = maybe_bench_indexer_restart(
        requests, hashes_list, t_miss, t_hit, ideal_service
    )

    # detail.replica_scaleout: the indexer as an N-replica cluster —
    # multi-replica scores/sec + parity + the failover hit-rate dip
    # (docs/replication.md), device-free.
    replica_scaleout = maybe_bench_replica_scaleout(
        requests, hashes_list, t_miss, t_hit, ideal_service
    )

    # detail.matrix: 5 strategies x QPS ladder x seeds, virtual clock.
    _progress("detail.matrix: virtual-clock strategy ladder")
    matrix, matrix_truncated = run_matrix(
        requests, hashes_list, t_miss, t_hit, ideal_service, warmup_idx
    )
    _progress("emit")

    emit_result(
        {
            "metric": "p50_ttft_speedup_precise_vs_round_robin",
            "value": speedup,
            "unit": "x",
            "vs_baseline": round(speedup / 3.0, 3),
            "detail": {
                "p50_ttft_precise_s": median["p50_ttft_precise_s"],
                "p50_ttft_round_robin_s": median[
                    "p50_ttft_round_robin_s"
                ],
                "prefix_cache_hit_rate_precise": median[
                    "hit_rate_precise"
                ],
                "prefix_cache_hit_rate_round_robin": median[
                    "hit_rate_round_robin"
                ],
                "headline_seeds": per_seed,
                "speedup_spread": {
                    "min": by_speedup[0]["speedup"],
                    "median": speedup,
                    "max": by_speedup[-1]["speedup"],
                },
                "qps": round(qps, 2),
                # The scoring RPC's own cost (reference: index
                # microbench axis): tokenize -> hash -> lookup ->
                # score per request, inside the precise runs.
                "routing_precise_us": _routing_percentiles(
                    routing_samples
                ),
                "micro": micro,
                "read_path": read_path,
                "cache_analytics": cache_analytics,
                "tiered_churn": tiered_churn,
                "scaleout_warmup": scaleout_warmup,
                "host_offload": host_offload,
                "event_storm": event_storm,
                "indexer_restart": indexer_restart,
                "replica_scaleout": replica_scaleout,
                "service_times": "measured",
                "service_miss_s": round(t_miss, 4),
                "service_hit_s": round(t_hit, 4),
                "readback_rtt_s": round(readback_rtt, 4),
                "decode_tok_s_per_seq": decode_tok_s,
                "decode_attention": CFG.decode_attention,
                "device": jax.devices()[0].platform,
                "requests": len(requests),
                "elapsed_s": round(_elapsed(), 1),
                "budget_s": _BUDGET_S,
                "headline_seeds_truncated": headline_truncated,
                "decode_truncated": decode_truncated,
                "matrix_truncated": matrix_truncated,
                "matrix": matrix,
                "mfu": mfu,
                "kernels": kernels,
            },
        },
        probe,
    )


if __name__ == "__main__":
    main()
