{{/* Common labels. */}}
{{- define "fleet.labels" -}}
app.kubernetes.io/name: {{ .Chart.Name }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end }}

{{/* Namespace: always the release namespace — identical under real
helm (which sets it from -n, default "default") and the subset
renderer, so `make chart` output can't diverge between the two. */}}
{{- define "fleet.namespace" -}}
{{- .Release.Namespace -}}
{{- end }}

{{/* vLLM workload name. */}}
{{- define "fleet.vllmName" -}}
{{- .Release.Name }}-vllm-{{ .Values.vllm.model.label | lower -}}
{{- end }}

{{/* Indexer workload/service name. */}}
{{- define "fleet.indexerName" -}}
{{- .Release.Name }}-kv-cache-indexer
{{- end }}

{{/* Valkey service name. */}}
{{- define "fleet.valkeyName" -}}
{{- .Release.Name }}-valkey
{{- end }}

{{/* Shared-storage PVC name (honors existingClaim). */}}
{{- define "fleet.sharedClaim" -}}
{{- if .Values.sharedStorage.existingClaim -}}
{{- .Values.sharedStorage.existingClaim -}}
{{- else -}}
{{- .Release.Name }}-shared-kv
{{- end -}}
{{- end }}

{{/* ZMQ endpoint the indexer binds in central (non-discovery) mode. */}}
{{- define "fleet.centralZmqUrl" -}}
tcp://{{ include "fleet.indexerName" . }}.{{ include "fleet.namespace" . }}.svc.cluster.local:{{ .Values.events.port -}}
{{- end }}

{{/* Valkey index-backend URL for the indexer. */}}
{{- define "fleet.valkeyUrl" -}}
valkey://{{ include "fleet.valkeyName" . }}.{{ include "fleet.namespace" . }}.svc.cluster.local:{{ .Values.valkey.port -}}
{{- end }}
