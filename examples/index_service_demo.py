"""gRPC index-service demo (reference: examples/kv_cache_index_service).

Boots the scoring service on a Unix-domain socket, seeds the index the
way a live fleet would (via KVEvents through the pool), and queries it
with the generated client stub.

    python examples/index_service_demo.py
"""

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from llm_d_kv_cache_manager_tpu.api import indexer_pb2
from llm_d_kv_cache_manager_tpu.api.indexer_service import new_client, serve
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import BlockStored, EventBatch
from llm_d_kv_cache_manager_tpu.kvevents.pool import Message, Pool, PoolConfig
from llm_d_kv_cache_manager_tpu.tokenization.pool import TokenizationPoolConfig
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
    LocalFastTokenizer,
)
from tests.helpers.tiny_tokenizer import save_tokenizer_json

MODEL = "test-model"
BLOCK_SIZE = 4
PROMPT = "the quick brown fox jumps over the lazy dog"


def main() -> None:
    tokenizer_dir = save_tokenizer_json(tempfile.mkdtemp(), MODEL)
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=2, model_name=MODEL
            ),
        ),
        tokenizer=LocalFastTokenizer(tokenizer_dir),
    )
    indexer.run()
    pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(concurrency=2),
    )
    pool.start()

    # Simulate two pods: pod-a stores the whole prompt, pod-b one block.
    tokens = indexer.tokenization_pool.tokenize(PROMPT, MODEL, None)
    n_blocks = len(tokens) // BLOCK_SIZE
    for pod, blocks in (("pod-a", n_blocks), ("pod-b", 1)):
        events = [
            BlockStored(
                block_hashes=[0x2000 + i],
                parent_block_hash=0x2000 + i - 1 if i else None,
                token_ids=tokens[i * BLOCK_SIZE:(i + 1) * BLOCK_SIZE],
                block_size=BLOCK_SIZE,
                lora_id=None,
                medium="hbm",
            )
            for i in range(blocks)
        ]
        batch = EventBatch(ts=time.time(), events=events)
        pool.add_task(
            Message(
                topic=f"kv@{pod}@{MODEL}",
                payload=batch.encode(),
                pod_identifier=pod,
                model_name=MODEL,
                seq=1,
            )
        )
    pool.drain()

    uds = os.path.join(tempfile.mkdtemp(), "indexer.sock")
    server = serve(indexer, f"unix://{uds}")
    client = new_client(f"unix://{uds}")
    response = client.GetPodScores(
        indexer_pb2.GetPodScoresRequest(prompt=PROMPT, model_name=MODEL)
    )
    for entry in response.scores:
        print(f"  {entry.pod}: {entry.score}")
    assert response.scores[0].pod == "pod-a"

    server.stop(grace=None)
    pool.shutdown()
    indexer.shutdown()
    print("index service demo completed successfully")


if __name__ == "__main__":
    main()
