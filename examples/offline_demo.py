"""Offline demo: the full event->index->score loop in one process.

Counterpart of the reference's offline ZMQ example
(examples/kv_events/offline/main.go:143-187): a dummy publisher emits
BlockStored/BlockRemoved KVEvents over a real ZMQ socket, the subscriber
pool ingests them, and the indexer scores pods for the same prompt —
showing the score rise when a pod stores the prompt's blocks and fall
after eviction.

    python examples/offline_demo.py
"""

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import BlockRemoved, BlockStored
from llm_d_kv_cache_manager_tpu.kvevents.pool import Pool, PoolConfig
from llm_d_kv_cache_manager_tpu.kvevents.publisher import Publisher
from llm_d_kv_cache_manager_tpu.kvevents.subscriber_manager import (
    SubscriberManager,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import TokenizationPoolConfig
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
    LocalFastTokenizer,
)
from tests.helpers.tiny_tokenizer import save_tokenizer_json

MODEL = "test-model"
POD = "vllm-pod-0"
BLOCK_SIZE = 4
ENDPOINT = "tcp://127.0.0.1:5557"
PROMPT = (
    "the quick brown fox jumps over the lazy dog . "
    "pack my box with five dozen liquor jugs"
)


def main() -> None:
    tokenizer_dir = save_tokenizer_json(tempfile.mkdtemp(), MODEL)
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=2, model_name=MODEL
            ),
        ),
        tokenizer=LocalFastTokenizer(tokenizer_dir),
    )
    indexer.run()

    pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(concurrency=2),
    )
    pool.start()
    manager = SubscriberManager(sink=pool.add_task)
    manager.ensure_subscriber(POD, ENDPOINT)
    publisher = Publisher(
        ENDPOINT, pod_identifier=POD, model_name=MODEL, bind=True
    )
    time.sleep(1.0)  # ZMQ slow-joiner

    print(f"[1] cold index scores: {score(indexer)}")

    # The engine reports its own hashes; token ids let the indexer
    # recompute its request-key chain (the dual-key design).
    tokens = indexer.tokenization_pool.tokenize(PROMPT, MODEL, None)
    engine_hashes = [0x1000 + i for i in range(len(tokens) // BLOCK_SIZE)]
    events = [
        BlockStored(
            block_hashes=[engine_hashes[i]],
            parent_block_hash=engine_hashes[i - 1] if i else None,
            token_ids=tokens[i * BLOCK_SIZE:(i + 1) * BLOCK_SIZE],
            block_size=BLOCK_SIZE,
            lora_id=None,
            medium="hbm",
        )
        for i in range(len(engine_hashes))
    ]
    publisher.publish(*events)
    wait_for(lambda: score(indexer).get(POD, 0) > 0)
    print(f"[2] after BlockStored x{len(events)}: {score(indexer)}")

    # Evict the tail half; the longest-prefix score shrinks.
    half = len(engine_hashes) // 2
    publisher.publish(
        BlockRemoved(block_hashes=engine_hashes[half:], medium="hbm")
    )
    wait_for(lambda: 0 < score(indexer).get(POD, 0) <= half)
    print(f"[3] after BlockRemoved tail: {score(indexer)}")

    publisher.close()
    manager.shutdown()
    pool.shutdown()
    indexer.shutdown()
    print("offline demo completed successfully")


def score(indexer):
    return indexer.get_pod_scores(PROMPT, MODEL, None)


def wait_for(predicate, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(0.1)
    raise TimeoutError("condition not reached")


if __name__ == "__main__":
    main()
