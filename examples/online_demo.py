"""Online demo: HTTP scoring service fed by live ZMQ events.

Counterpart of the reference's online example
(examples/kv_events/online/main.go:273-385): boots the HTTP service
(api/http_service.py) plus the event-subscription stack, publishes
BlockStored events from a simulated pod, and queries
``/score_completions`` and ``/metrics`` over real HTTP.

    python examples/online_demo.py
"""

import json
import os
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from llm_d_kv_cache_manager_tpu.api.http_service import serve
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import IndexConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import BlockStored
from llm_d_kv_cache_manager_tpu.kvevents.pool import Pool, PoolConfig
from llm_d_kv_cache_manager_tpu.kvevents.publisher import Publisher
from llm_d_kv_cache_manager_tpu.kvevents.subscriber_manager import (
    SubscriberManager,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import TokenizationPoolConfig
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
    LocalFastTokenizer,
)
from tests.helpers.tiny_tokenizer import save_tokenizer_json

MODEL = "test-model"
POD = "vllm-pod-0"
BLOCK_SIZE = 4
ENDPOINT = "tcp://127.0.0.1:5558"
PROMPT = "the quick brown fox jumps over the lazy dog"


def main() -> None:
    tokenizer_dir = save_tokenizer_json(tempfile.mkdtemp(), MODEL)
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=2, model_name=MODEL
            ),
            kvblock_index_config=IndexConfig(enable_metrics=True),
        ),
        tokenizer=LocalFastTokenizer(tokenizer_dir),
    )
    indexer.run()
    pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(concurrency=2),
    )
    pool.start()
    manager = SubscriberManager(sink=pool.add_task)
    manager.ensure_subscriber(POD, ENDPOINT)
    publisher = Publisher(
        ENDPOINT, pod_identifier=POD, model_name=MODEL, bind=True
    )
    server = serve(indexer, host="127.0.0.1", port=0)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    time.sleep(1.0)  # ZMQ slow-joiner

    tokens = indexer.tokenization_pool.tokenize(PROMPT, MODEL, None)
    publisher.publish(
        *[
            BlockStored(
                block_hashes=[0x3000 + i],
                parent_block_hash=0x3000 + i - 1 if i else None,
                token_ids=tokens[i * BLOCK_SIZE:(i + 1) * BLOCK_SIZE],
                block_size=BLOCK_SIZE,
                lora_id=None,
                medium="hbm",
            )
            for i in range(len(tokens) // BLOCK_SIZE)
        ]
    )

    deadline = time.time() + 10
    scores = {}
    while time.time() < deadline and not scores.get(POD):
        request = urllib.request.Request(
            base + "/score_completions",
            data=json.dumps({"prompt": PROMPT, "model": MODEL}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(request, timeout=10) as response:
            scores = json.load(response)
        time.sleep(0.2)
    print(f"scores over HTTP: {scores}")
    assert scores.get(POD, 0) > 0

    with urllib.request.urlopen(base + "/metrics", timeout=10) as response:
        lines = [
            line
            for line in response.read().decode().splitlines()
            if line.startswith("kvtpu_kvcache_index_lookup")
        ]
    print("metrics excerpt:")
    for line in lines[:4]:
        print(f"  {line}")

    publisher.close()
    server.shutdown()
    manager.shutdown()
    pool.shutdown()
    indexer.shutdown()
    print("online demo completed successfully")


if __name__ == "__main__":
    main()
