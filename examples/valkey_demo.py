"""Shared-index demo: two indexer replicas over one Valkey/Redis store.

Counterpart of the reference's valkey demo (examples/): replica A
ingests the fleet's events; replica B — a different process in
production — serves scoring queries against the same distributed index.
An in-process RESP server stands in for Valkey (tests/helpers/miniresp,
the miniredis pattern), so the demo runs hermetically; point
``address`` at a real ``valkey://`` endpoint in a cluster.

    python examples/valkey_demo.py
"""

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    IndexConfig,
    RedisIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import BlockStored, EventBatch
from llm_d_kv_cache_manager_tpu.kvevents.pool import Message, Pool, PoolConfig
from llm_d_kv_cache_manager_tpu.tokenization.pool import TokenizationPoolConfig
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
    LocalFastTokenizer,
)
from tests.helpers.miniresp import MiniRespServer
from tests.helpers.tiny_tokenizer import save_tokenizer_json

MODEL = "test-model"
BLOCK_SIZE = 4
PROMPT = "the quick brown fox jumps over the lazy dog"


def make_indexer(tokenizer_dir: str, address: str) -> Indexer:
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            kvblock_index_config=IndexConfig(
                redis_config=RedisIndexConfig(
                    address=address, flavor="valkey"
                ),
            ),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=1, model_name=MODEL
            ),
        ),
        tokenizer=LocalFastTokenizer(tokenizer_dir),
    )
    indexer.run()
    return indexer


def main() -> None:
    valkey = MiniRespServer()
    tokenizer_dir = save_tokenizer_json(tempfile.mkdtemp(), MODEL)

    writer = make_indexer(tokenizer_dir, valkey.address)  # event ingester
    reader = make_indexer(tokenizer_dir, valkey.address)  # scoring replica

    pool = Pool(
        writer.kv_block_index,
        writer.token_processor,
        PoolConfig(concurrency=2),
    )
    pool.start()

    tokens = writer.tokenization_pool.tokenize(PROMPT, MODEL, None)
    events = [
        BlockStored(
            block_hashes=[0x6000 + i],
            parent_block_hash=0x6000 + i - 1 if i else None,
            token_ids=tokens[i * BLOCK_SIZE:(i + 1) * BLOCK_SIZE],
            block_size=BLOCK_SIZE,
            lora_id=None,
            medium="hbm",
        )
        for i in range(len(tokens) // BLOCK_SIZE)
    ]
    batch = EventBatch(ts=time.time(), events=events)
    pool.add_task(
        Message(
            topic=f"kv@pod-a@{MODEL}",
            payload=batch.encode(),
            pod_identifier="pod-a",
            model_name=MODEL,
            seq=1,
        )
    )
    pool.drain()

    # The *other* replica sees the same index state over the wire.
    scores = reader.get_pod_scores(PROMPT, MODEL, None)
    print(f"replica-B scores (events ingested by replica-A): {scores}")
    assert scores.get("pod-a", 0) > 0

    pool.shutdown()
    writer.shutdown()
    reader.shutdown()
    valkey.close()
    print("valkey demo completed successfully")


if __name__ == "__main__":
    main()
