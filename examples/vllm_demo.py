"""Live-engine demo: index KV events from a real vLLM(-TPU) process.

Counterpart of the reference's real-engine demo
(examples/kv_events/vllm/vllm_kv_cache_demo.py): boot vLLM with KV
events enabled, subscribe the indexer to its ZMQ stream, run prompts,
and watch pod scores reflect the engine's actual prefix cache.

Requires a vLLM install (vllm-tpu on TPU VMs); in environments without
it this prints the integration recipe and exits cleanly so
hack/verify-examples.sh can include it unconditionally.

Fleet invariants (docs/configuration.md):
- engine `--block-size` must equal the indexer's block_size
- engine PYTHONHASHSEED must equal the indexer's hash_seed
- `prefix_caching_hash_algo="sha256_cbor"` interops via the
  engineKey->requestKey map (last-8-bytes big-endian rule)
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

MODEL = os.environ.get("MODEL_NAME", "meta-llama/Llama-3.1-8B-Instruct")
BLOCK_SIZE = int(os.environ.get("BLOCK_SIZE", "16"))
ZMQ_ENDPOINT = os.environ.get("ZMQ_ENDPOINT", "tcp://localhost:5557")
POD = os.environ.get("POD_IDENTIFIER", "localhost")

SHARED_STORAGE = os.environ.get("SHARED_STORAGE_PATH", "/mnt/kv-cache")

RECIPE = f"""\
vLLM not installed — to run this demo on a serving host:

  PYTHONHASHSEED=42 vllm serve {MODEL} \\
    --block-size {BLOCK_SIZE} \\
    --kv-events-config '{{
        "enable_kv_cache_events": true,
        "publisher": "zmq",
        "endpoint": "{ZMQ_ENDPOINT.replace("localhost", "*")}",
        "topic": "kv@{POD}@{MODEL}"
      }}' \\
    --kv-transfer-config '{{
        "kv_connector": "OffloadingConnector",
        "kv_role": "kv_both",
        "kv_connector_extra_config": {{
          "spec_name": "TPUSharedStorageOffloadingSpec",
          "spec_module_path":
            "llm_d_kv_cache_manager_tpu.offload.vllm_spec",
          "shared_storage_path": "{SHARED_STORAGE}",
          "block_size": {BLOCK_SIZE * 4},
          "threads_per_chip": 8,
          "max_staging_memory_gb": 16
        }}
      }}' \\
    --prefix-caching-hash-algo sha256_cbor

then:  python examples/vllm_demo.py

vllm demo completed successfully (recipe mode)\
"""


def main() -> None:
    try:
        import vllm  # noqa: F401
    except ImportError:
        print(RECIPE)
        return

    from llm_d_kv_cache_manager_tpu.kvcache.indexer import (
        Indexer,
        IndexerConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
        TokenProcessorConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvevents.pool import Pool, PoolConfig
    from llm_d_kv_cache_manager_tpu.kvevents.subscriber_manager import (
        SubscriberManager,
    )
    from llm_d_kv_cache_manager_tpu.tokenization.pool import (
        TokenizationPoolConfig,
    )

    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE,
                hash_seed=os.environ.get("PYTHONHASHSEED", ""),
            ),
            tokenizers_pool_config=TokenizationPoolConfig(
                model_name=MODEL
            ),
        )
    )
    indexer.run()
    pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(concurrency=2),
    )
    pool.start()
    manager = SubscriberManager(sink=pool.add_task)
    manager.ensure_subscriber(POD, ZMQ_ENDPOINT)

    from vllm import LLM, SamplingParams

    # Wire the TPU shared-storage offload connector (offload/vllm_spec.py)
    # so evicted blocks page to shared storage and can be re-served.
    kv_transfer_config = {
        "kv_connector": "OffloadingConnector",
        "kv_role": "kv_both",
        "kv_connector_extra_config": {
            "spec_name": "TPUSharedStorageOffloadingSpec",
            "spec_module_path": (
                "llm_d_kv_cache_manager_tpu.offload.vllm_spec"
            ),
            "shared_storage_path": SHARED_STORAGE,
            "block_size": BLOCK_SIZE * 4,
            "threads_per_chip": 8,
            "max_staging_memory_gb": 16,
        },
    }
    llm = LLM(
        model=MODEL,
        enable_prefix_caching=True,
        block_size=BLOCK_SIZE,
        kv_transfer_config=kv_transfer_config,
    )
    shared = "You are a helpful assistant. " * 200
    prompts = [shared + q for q in ("What is JAX?", "What is a TPU?")]
    llm.generate(prompts, SamplingParams(max_tokens=8))
    time.sleep(2.0)  # let events drain

    for prompt in prompts:
        scores = indexer.get_pod_scores(prompt, MODEL, None)
        print(f"scores for {prompt[-24:]!r}: {scores}")
        assert scores.get(POD, 0) > 0, "engine events not indexed"

    manager.shutdown()
    pool.shutdown()
    indexer.shutdown()
    print("vllm demo completed successfully")


if __name__ == "__main__":
    main()
