# Makes hack/ importable so `python -m hack.kvlint` works from the
# repo root (the analyzer lives in hack/kvlint/).  Developer tooling
# only — never shipped (pyproject packages.find includes only
# llm_d_kv_cache_manager_tpu*).
