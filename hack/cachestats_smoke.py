"""CI smoke gate for the cache-efficiency analytics plane.

Boots the HTTP scoring service with the hit-attribution ledger and an
index-truth auditor wired to a controllable inventory source, then
asserts the whole analytics loop closes:

* scored traffic lands in the ledger: ``GET /debug/cachestats`` shows
  the right request count, a sane hit/partial split, a tracked prefix
  family, and live window frames;
* the family drill-down (``?family=<id>``) resolves;
* a planted divergence (the inventory "forgets" 10% of a pod's
  blocks) is detected by one auditor cycle: the report says divergent
  with the right ratio, the audit log carries it, and
  ``kvtpu_index_divergence_ratio`` lands on ``/metrics``;
* ``/healthz`` carries the analytics block (ledger summary + audit
  status);
* the analytics metric families are present in the exposition.

Run: ``python hack/cachestats_smoke.py`` (CI step "Cache analytics
smoke", ``make cachestats-smoke``).  Prints "cachestats smoke
completed successfully" on success; any assertion exits non-zero.
"""

import json
import os
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TOKENIZERS_PARALLELISM", "false")
# Deterministic smoke: record every request, tier detail on all.
os.environ.setdefault("CACHESTATS_SAMPLE_RATE", "1")
os.environ.setdefault("CACHESTATS_TIER_SAMPLE", "1")

from llm_d_kv_cache_manager_tpu.analytics import (  # noqa: E402
    AuditorConfig,
    IndexAuditor,
)
from llm_d_kv_cache_manager_tpu.api.http_service import serve  # noqa: E402
from llm_d_kv_cache_manager_tpu.kvcache.indexer import (  # noqa: E402
    Indexer,
    IndexerConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (  # noqa: E402,E501
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (  # noqa: E402
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (  # noqa: E402
    Message,
    Pool,
    PoolConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.resync import (  # noqa: E402
    CallableInventorySource,
    InventoryBlock,
    PodInventory,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (  # noqa: E402
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (  # noqa: E402
    LocalFastTokenizer,
)
from tests.helpers.tiny_tokenizer import save_tokenizer_json  # noqa: E402

MODEL = "test-model"
BLOCK_SIZE = 4
PROMPT = "the quick brown fox jumps over the lazy dog . " * 8
COLD = "completely different words never stored anywhere at all . " * 8


def post(base, path, obj):
    request = urllib.request.Request(
        base + path,
        data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(request, timeout=30) as response:
        return json.load(response)


def get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as response:
        return json.load(response)


def get_text(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as response:
        return response.read().decode()


def main() -> None:
    tokenizer_dir = save_tokenizer_json(tempfile.mkdtemp(), MODEL)
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=2, model_name=MODEL
            ),
        ),
        tokenizer=LocalFastTokenizer(tokenizer_dir),
    )
    assert indexer.cache_stats is not None, "ledger must default on"
    indexer.run()
    event_pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(concurrency=2),
    )
    event_pool.start()

    # Store the warm prompt's full chain on pod-1; remember the truth
    # for the inventory source.
    tokens = indexer.tokenization_pool.tokenize(PROMPT, MODEL, None)
    n_blocks = len(tokens) // BLOCK_SIZE
    engine_hashes = list(range(0x200, 0x200 + n_blocks))
    batch = EventBatch(
        ts=1.0,
        events=[
            BlockStored(
                block_hashes=list(engine_hashes),
                parent_block_hash=None,
                token_ids=tokens[: n_blocks * BLOCK_SIZE],
                block_size=BLOCK_SIZE,
                medium="hbm",
            )
        ],
    )
    event_pool.add_task(
        Message(
            topic=f"kv@pod-1@{MODEL}",
            payload=batch.encode(),
            pod_identifier="pod-1",
            model_name=MODEL,
        )
    )
    event_pool.drain()

    inventory_blocks = {
        "pod-1": [
            InventoryBlock(
                block_hashes=list(engine_hashes),
                token_ids=tokens[: n_blocks * BLOCK_SIZE],
                block_size=BLOCK_SIZE,
                medium="hbm",
            )
        ]
    }

    def fetch(pod):
        blocks = inventory_blocks.get(pod)
        if blocks is None:
            return None
        return PodInventory(
            pod_identifier=pod, model_name=MODEL, blocks=blocks
        )

    auditor = IndexAuditor(
        indexer.kv_block_index,
        indexer.token_processor,
        CallableInventorySource(fetch),
        AuditorConfig(interval_s=0.0),
    )
    server = serve(indexer, host="127.0.0.1", port=0, auditor=auditor)
    base = f"http://127.0.0.1:{server.server_address[1]}"

    # 1. Scored traffic: warm hits + a cold miss.
    warm_scores = post(
        base, "/score_completions", {"prompt": PROMPT, "model": MODEL}
    )
    assert warm_scores.get("pod-1") == n_blocks, warm_scores
    for _ in range(3):
        post(base, "/score_completions", {"prompt": PROMPT, "model": MODEL})
    cold_scores = post(
        base, "/score_completions", {"prompt": COLD, "model": MODEL}
    )
    assert cold_scores == {}, cold_scores

    # 2. /debug/cachestats: totals, windows, families.
    stats = get(base, "/debug/cachestats")
    totals = stats["totals"]
    assert totals["recorded"] == 5, totals
    assert totals["hits"] == 4, totals
    assert totals["misses"] == 1, totals
    assert totals["tiers"].get("hbm", 0) > 0, totals
    assert stats["windows"]["1m"]["requests"] == 5, stats["windows"]
    assert stats["families_tracked"] >= 2, stats
    top = stats["top_families"]
    assert top and top[0]["requests"] == 4, top
    assert top[0]["ewma_interarrival_s"] is not None, top

    # 3. Family drill-down.
    family = get(base, f"/debug/cachestats?family={top[0]['family']}")
    assert family["requests"] == 4, family

    # 4. Clean audit first: index and inventory agree.
    reports = auditor.run_cycle()
    assert len(reports) == 1 and reports[0].outcome == "clean", [
        r.to_dict() for r in reports
    ]

    # 5. Plant a divergence: the pod "forgets" 10% of its blocks, so
    # the index's claims become phantoms; one cycle must detect it.
    keep = n_blocks - max(1, n_blocks // 10)
    victim = inventory_blocks["pod-1"][0]
    victim.block_hashes = victim.block_hashes[:keep]
    victim.token_ids = victim.token_ids[: keep * BLOCK_SIZE]
    planted_ratio = (n_blocks - keep) / n_blocks
    reports = auditor.run_cycle()
    report = reports[0]
    assert report.outcome == "divergent", report.to_dict()
    assert abs(report.divergence_ratio - planted_ratio) < 1e-6, (
        report.to_dict(),
        planted_ratio,
    )
    assert report.phantom == n_blocks - keep, report.to_dict()

    stats = get(base, "/debug/cachestats")
    assert stats["audit"]["divergent_pods"].get("pod-1"), stats["audit"]
    assert stats["audit_log"], "audit log empty"
    assert stats["audit_divergent"][0]["pod"] == "pod-1", stats

    # 6. /healthz analytics block.
    health = get(base, "/healthz")
    analytics = health.get("analytics", {})
    assert analytics.get("cachestats", {}).get("recorded") == 5, analytics
    assert analytics.get("audit", {}).get("audits") == 2, analytics

    # 7. Metric families on /metrics.
    text = get_text(base, "/metrics")
    assert 'kvtpu_cachestats_requests_total{outcome="hit"} 4.0' in text
    assert 'kvtpu_index_divergence_ratio{pod="pod-1"}' in text
    assert "kvtpu_cachestats_reuse_distance_count" in text
    assert 'kvtpu_index_audits_total{outcome="divergent"} 1.0' in text

    server.shutdown()
    event_pool.shutdown()
    indexer.shutdown()
    print("cachestats smoke completed successfully")


if __name__ == "__main__":
    main()
