#!/usr/bin/env python3
"""Deterministic native-format gate that runs in ANY environment.

The authoritative gate is ``clang-format --dry-run --Werror`` with the
pinned root ``.clang-format`` (Google, 80 col) — CI runs it on GitHub
runners, where the binary ships.  The dev image has no clang-format and
cannot install one, so this checker enforces the mechanically-decidable
subset of the same style everywhere a Python interpreter exists:

* UTF-8, LF line endings, final newline present
* no tab characters, no trailing whitespace
* <= 80 columns
* indentation in steps of two spaces (Google IndentWidth: 2), allowing
  continuation-line alignment (any depth deeper than the previous
  line's + 2 is treated as alignment and accepted)

A file that passes clang-format also passes this subset; a file that
fails this subset fails clang-format.  Exit 0 = clean, 1 = violations
(one line each: path:line: message).

Usage: python hack/check_native_format.py [files...]
(defaults to llm_d_kv_cache_manager_tpu/native/src/*.cpp|hpp)
"""

from __future__ import annotations

import glob
import os
import sys

DEFAULT_GLOBS = (
    "llm_d_kv_cache_manager_tpu/native/src/*.cpp",
    "llm_d_kv_cache_manager_tpu/native/src/*.hpp",
)
MAX_COLS = 80
INDENT = 2


def check_file(path: str) -> list:
    problems = []
    with open(path, "rb") as handle:
        raw = handle.read()
    try:
        text = raw.decode("utf-8")
    except UnicodeDecodeError as exc:
        return [f"{path}:0: not valid UTF-8 ({exc})"]
    if b"\r" in raw:
        problems.append(f"{path}:0: CR line endings (LF only)")
    if raw and not raw.endswith(b"\n"):
        problems.append(f"{path}:0: missing final newline")
    prev_indent = 0
    for lineno, line in enumerate(text.split("\n")[:-1], start=1):
        if "\t" in line:
            problems.append(f"{path}:{lineno}: tab character")
        if line != line.rstrip():
            problems.append(f"{path}:{lineno}: trailing whitespace")
        if len(line) > MAX_COLS:
            problems.append(
                f"{path}:{lineno}: {len(line)} columns (max {MAX_COLS})"
            )
        stripped = line.lstrip(" ")
        if not stripped:
            continue
        indent = len(line) - len(stripped)
        # Google style indents in steps of 2; deeper indents are
        # continuation alignment (clang-format aligns to arbitrary
        # columns), so only a *shallow* odd step relative to the
        # previous code line is decidably wrong.
        if indent <= prev_indent + INDENT and indent % INDENT:
            # Exceptions clang-format itself produces at odd columns:
            # ' *' continuation lines of block comments and visibility
            # labels (Google offsets 'public:' etc. by one).
            is_comment_cont = stripped.startswith("*")
            is_access_label = stripped.rstrip() in (
                "public:",
                "private:",
                "protected:",
            )
            if not is_comment_cont and not is_access_label:
                problems.append(
                    f"{path}:{lineno}: indent {indent} not a multiple "
                    f"of {INDENT}"
                )
        if indent <= prev_indent + INDENT:
            prev_indent = indent
    return problems


def main() -> int:
    files = sys.argv[1:]
    if not files:
        root = os.path.join(os.path.dirname(__file__), "..")
        files = [
            path
            for pattern in DEFAULT_GLOBS
            for path in sorted(glob.glob(os.path.join(root, pattern)))
        ]
    if not files:
        print("check_native_format: no files found", file=sys.stderr)
        return 1
    problems = []
    for path in files:
        problems.extend(check_file(path))
    for problem in problems:
        print(problem)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
