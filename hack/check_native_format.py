#!/usr/bin/env python3
"""Deterministic native-format gate that runs in ANY environment.

The authoritative gate is ``clang-format --dry-run --Werror`` with the
pinned root ``.clang-format`` (Google, 80 col) — CI runs it on GitHub
runners, where the binary ships.  The dev image has no clang-format and
cannot install one, so this checker enforces the mechanically-decidable
subset of the same style everywhere a Python interpreter exists:

* UTF-8, LF line endings, final newline present
* no tab characters, no trailing whitespace (outside raw strings)
* <= 80 columns for breakable lines (clang-format leaves a single
  unbreakable token — a long string literal, include path, URL —
  over the limit, so lines whose overflow is one unbroken token pass)
* indentation in steps of two spaces (Google IndentWidth: 2), allowing
  continuation-line alignment (any depth deeper than the previous
  line's + 2 is treated as alignment and accepted)

The rules are tuned so clang-format-clean code passes (the known
clang-format outputs this subset cannot express — unbreakable-token
overflow, raw-string contents — are carved out above); a failure
therefore indicates code the authoritative gate would also reject or
that was never formatted.  Exit 0 = clean, 1 = violations (one line
each: path:line: message).

The unbreakable-token carve-out (``_is_breakable_overflow``) accepts
over-limit lines in two steps: a break opportunity (space) at or past
column 79 always means clang-format could have wrapped — violation.
Otherwise, if the line's only spaces sit before that column, the final
token decides: when it would FIT on its own continuation line
(indent + 4 + token <= 80), clang-format would have wrapped at the
early space and produced no over-limit line at all — violation (this
closes the documented false-negative class, e.g. ``return
kLongButWrappableIdentifier...;``).  Only a token too long to fit even
after wrapping (giant string literal, include path, URL) passes, since
clang-format itself leaves those overflowing.  Known imprecision: the
fit check models the plain ContinuationIndentWidth placement
(indent+4); a line clang-format would align deeper (open-bracket
alignment) where the token does NOT fit could be a false positive —
in practice clang-format falls back to the indent+4-style break when
alignment would overflow, so such lines are still wrappable.  Carved
out entirely: preprocessor directives (clang-format never wraps
``#include``/``#define`` paths) and raw-string interiors (never
edited), which keeps the gate's no-false-positive contract on
clang-format-clean code.

Usage: python hack/check_native_format.py [files...]
(defaults to llm_d_kv_cache_manager_tpu/native/src/*.cpp|hpp)
"""

from __future__ import annotations

import glob
import os
import sys

DEFAULT_GLOBS = (
    "llm_d_kv_cache_manager_tpu/native/src/*.cpp",
    "llm_d_kv_cache_manager_tpu/native/src/*.hpp",
)
MAX_COLS = 80
INDENT = 2


# Continuation indent clang-format uses when it wraps at a plain break
# (Google style ContinuationIndentWidth: 4).
_CONTINUATION_INDENT = 4


def _is_breakable_overflow(line: str) -> bool:
    """True when the over-limit line could have been wrapped under the
    column limit — i.e. clang-format (ColumnLimit 80) would never have
    produced it (see the module docstring for the full argument).

    Two cases: a break opportunity (space) at or past column 79, or an
    early-break line whose final token would fit on its own
    continuation line at indent + 4.  Preprocessor directives are
    never wrapped by clang-format and always pass."""
    if line.lstrip().startswith("#"):
        return False  # #include/#define: clang-format never wraps
    if " " in line[MAX_COLS - 1:].strip():
        return True
    # Only spaces before the limit: breakable iff wrapping at the last
    # of them leaves a final token that fits at the continuation
    # indent.  (A token that fits nowhere is clang-format's own
    # unbreakable-overflow output and must keep passing.)
    body = line.rstrip()
    indent = len(line) - len(line.lstrip(" "))
    head, sep, tail = body.rpartition(" ")
    if not sep or not tail:
        return False  # one giant token, nothing to wrap
    return indent + _CONTINUATION_INDENT + len(tail) <= MAX_COLS


def check_file(path: str) -> list:
    problems = []
    with open(path, "rb") as handle:
        raw = handle.read()
    try:
        text = raw.decode("utf-8")
    except UnicodeDecodeError as exc:
        return [f"{path}:0: not valid UTF-8 ({exc})"]
    if b"\r" in raw:
        problems.append(f"{path}:0: CR line endings (LF only)")
    if raw and not raw.endswith(b"\n"):
        problems.append(f"{path}:0: missing final newline")
    prev_indent = 0
    in_raw_string = False
    for lineno, line in enumerate(text.split("\n")[:-1], start=1):
        # clang-format never edits raw-string literal contents; skip
        # whitespace rules inside them (naive tracker — good enough
        # for the R"(...)" forms that appear in native code).
        was_raw = in_raw_string
        if in_raw_string:
            if ')"' in line:
                in_raw_string = False
        elif 'R"(' in line and ')"' not in line.split('R"(', 1)[1]:
            in_raw_string = True
        if not was_raw:
            if "\t" in line:
                problems.append(f"{path}:{lineno}: tab character")
            if line != line.rstrip():
                problems.append(f"{path}:{lineno}: trailing whitespace")
        if (
            not was_raw
            and len(line) > MAX_COLS
            and _is_breakable_overflow(line)
        ):
            problems.append(
                f"{path}:{lineno}: {len(line)} columns (max {MAX_COLS})"
            )
        stripped = line.lstrip(" ")
        if not stripped:
            continue
        indent = len(line) - len(stripped)
        # Google style indents in steps of 2; deeper indents are
        # continuation alignment (clang-format aligns to arbitrary
        # columns), so only a *shallow* odd step relative to the
        # previous code line is decidably wrong.
        if indent <= prev_indent + INDENT and indent % INDENT:
            # Exceptions clang-format itself produces at odd columns:
            # ' *' continuation lines of block comments and visibility
            # labels (Google offsets 'public:' etc. by one).
            is_comment_cont = stripped.startswith("*")
            is_access_label = stripped.rstrip() in (
                "public:",
                "private:",
                "protected:",
            )
            if not is_comment_cont and not is_access_label:
                problems.append(
                    f"{path}:{lineno}: indent {indent} not a multiple "
                    f"of {INDENT}"
                )
        if indent <= prev_indent + INDENT:
            prev_indent = indent
    return problems


def main() -> int:
    files = sys.argv[1:]
    if not files:
        root = os.path.join(os.path.dirname(__file__), "..")
        files = [
            path
            for pattern in DEFAULT_GLOBS
            for path in sorted(glob.glob(os.path.join(root, pattern)))
        ]
    if not files:
        print("check_native_format: no files found", file=sys.stderr)
        return 1
    problems = []
    for path in files:
        problems.extend(check_file(path))
    for problem in problems:
        print(problem)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
