#!/usr/bin/env python3
"""Deterministic native-format gate that runs in ANY environment.

The authoritative gate is ``clang-format --dry-run --Werror`` with the
pinned root ``.clang-format`` (Google, 80 col) — CI runs it on GitHub
runners, where the binary ships.  The dev image has no clang-format and
cannot install one, so this checker enforces the mechanically-decidable
subset of the same style everywhere a Python interpreter exists:

* UTF-8, LF line endings, final newline present
* no tab characters, no trailing whitespace (outside raw strings)
* <= 80 columns for breakable lines (clang-format leaves a single
  unbreakable token — a long string literal, include path, URL —
  over the limit, so lines whose overflow is one unbroken token pass)
* indentation in steps of two spaces (Google IndentWidth: 2), allowing
  continuation-line alignment (any depth deeper than the previous
  line's + 2 is treated as alignment and accepted)

The rules are tuned so clang-format-clean code passes (the known
clang-format outputs this subset cannot express — unbreakable-token
overflow, raw-string contents — are carved out above); a failure
therefore indicates code the authoritative gate would also reject or
that was never formatted.  Exit 0 = clean, 1 = violations (one line
each: path:line: message).

Known false-negative class (column check only): the unbreakable-token
carve-out (``_is_breakable_overflow``) looks for a break opportunity at
or past column 79 only.  An over-limit line whose ONLY spaces sit
before that column — e.g. a short prefix followed by one giant token,
``return kVeryLongUnbreakableIdentifierThatRunsPastTheLimit...`` — is
treated as unbreakable and passes, even though clang-format would have
wrapped at the early space and THEN left the token overflowing on its
own line (or, for a breakable tail, not overflowed at all).  Deciding
that correctly requires clang-format's break-cost model; this gate
stays conservative (never a false positive on formatted code) and
leaves the class to the authoritative CI gate.

Usage: python hack/check_native_format.py [files...]
(defaults to llm_d_kv_cache_manager_tpu/native/src/*.cpp|hpp)
"""

from __future__ import annotations

import glob
import os
import sys

DEFAULT_GLOBS = (
    "llm_d_kv_cache_manager_tpu/native/src/*.cpp",
    "llm_d_kv_cache_manager_tpu/native/src/*.hpp",
)
MAX_COLS = 80
INDENT = 2


def _is_breakable_overflow(line: str) -> bool:
    """True when the part past the limit could have been wrapped:
    clang-format (ColumnLimit 80) only exceeds the limit when a single
    unbreakable token — long string literal, include path, URL — runs
    past it, i.e. when there is no break opportunity (space) at or
    beyond the last column.  False negative: over-limit lines whose
    only break opportunities sit before column 79 pass here (see the
    module docstring)."""
    return " " in line[MAX_COLS - 1:].strip()


def check_file(path: str) -> list:
    problems = []
    with open(path, "rb") as handle:
        raw = handle.read()
    try:
        text = raw.decode("utf-8")
    except UnicodeDecodeError as exc:
        return [f"{path}:0: not valid UTF-8 ({exc})"]
    if b"\r" in raw:
        problems.append(f"{path}:0: CR line endings (LF only)")
    if raw and not raw.endswith(b"\n"):
        problems.append(f"{path}:0: missing final newline")
    prev_indent = 0
    in_raw_string = False
    for lineno, line in enumerate(text.split("\n")[:-1], start=1):
        # clang-format never edits raw-string literal contents; skip
        # whitespace rules inside them (naive tracker — good enough
        # for the R"(...)" forms that appear in native code).
        was_raw = in_raw_string
        if in_raw_string:
            if ')"' in line:
                in_raw_string = False
        elif 'R"(' in line and ')"' not in line.split('R"(', 1)[1]:
            in_raw_string = True
        if not was_raw:
            if "\t" in line:
                problems.append(f"{path}:{lineno}: tab character")
            if line != line.rstrip():
                problems.append(f"{path}:{lineno}: trailing whitespace")
        if len(line) > MAX_COLS and _is_breakable_overflow(line):
            problems.append(
                f"{path}:{lineno}: {len(line)} columns (max {MAX_COLS})"
            )
        stripped = line.lstrip(" ")
        if not stripped:
            continue
        indent = len(line) - len(stripped)
        # Google style indents in steps of 2; deeper indents are
        # continuation alignment (clang-format aligns to arbitrary
        # columns), so only a *shallow* odd step relative to the
        # previous code line is decidably wrong.
        if indent <= prev_indent + INDENT and indent % INDENT:
            # Exceptions clang-format itself produces at odd columns:
            # ' *' continuation lines of block comments and visibility
            # labels (Google offsets 'public:' etc. by one).
            is_comment_cont = stripped.startswith("*")
            is_access_label = stripped.rstrip() in (
                "public:",
                "private:",
                "protected:",
            )
            if not is_comment_cont and not is_access_label:
                problems.append(
                    f"{path}:{lineno}: indent {indent} not a multiple "
                    f"of {INDENT}"
                )
        if indent <= prev_indent + INDENT:
            prev_indent = indent
    return problems


def main() -> int:
    files = sys.argv[1:]
    if not files:
        root = os.path.join(os.path.dirname(__file__), "..")
        files = [
            path
            for pattern in DEFAULT_GLOBS
            for path in sorted(glob.glob(os.path.join(root, pattern)))
        ]
    if not files:
        print("check_native_format: no files found", file=sys.stderr)
        return 1
    problems = []
    for path in files:
        problems.extend(check_file(path))
    for problem in problems:
        print(problem)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
