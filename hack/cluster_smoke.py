"""CI smoke gate for the replicated index service (cluster/).

Boots THREE in-process replicas + a router HTTP scoring service whose
indexer runs against the cluster's ``RemoteIndex`` (journal-fed
replication followers syncing standby slices), then asserts the fleet
story end to end:

* scored traffic flows through the clustered read path (admissions via
  the REAL kvevents pool route to slice owners; scores arrive over the
  live HTTP endpoint);
* one replica is KILLED mid-traffic: scoring keeps answering without a
  single error, the heartbeat removes the replica from the ring
  (failover counter, ring version bump — visible in
  ``GET /debug/cluster`` and ``kvtpu_cluster_*`` on ``/metrics``);
* the failed-over slice is WARM: post-kill scores equal pre-kill
  scores (the follower inherited the slice), inside the pinned
  degradation envelope;
* ``POST /replica`` serves the wire surface (probed directly).

Run: ``python hack/cluster_smoke.py`` (CI step "Cluster smoke",
``make cluster-smoke``).  Prints "cluster smoke completed
successfully" on success; any assertion exits non-zero.
"""

import json
import os
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TOKENIZERS_PARALLELISM", "false")

from llm_d_kv_cache_manager_tpu.api.http_service import serve  # noqa: E402
from llm_d_kv_cache_manager_tpu.cluster import LocalCluster  # noqa: E402
from llm_d_kv_cache_manager_tpu.cluster.replica import (  # noqa: E402
    encode_request,
)
from llm_d_kv_cache_manager_tpu.kvcache.indexer import (  # noqa: E402
    Indexer,
    IndexerConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (  # noqa: E402,E501
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (  # noqa: E402
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (  # noqa: E402
    Message,
    Pool,
    PoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (  # noqa: E402
    Encoding,
)

MODEL = "smoke-model"
BLOCK_SIZE = 4
# Warm-failover envelope (docs/replication.md): every pre-kill-scored
# prompt must score identically post-kill; the envelope bounds how many
# may degrade before the gate fails (followers sync continuously, so
# the expected count is zero).
DEGRADED_PROMPT_BUDGET = 0


class WordTokenizer:
    """Deterministic: 't<id>' words -> ids (no network, no HF)."""

    def type(self) -> str:
        return "smoke-word"

    def encode(self, prompt, model_name, add_special_tokens):
        tokens, offsets, pos = [], [], 0
        for word in prompt.split(" "):
            tokens.append(int(word[1:]) if word.startswith("t") else 0)
            offsets.append((pos, pos + len(word)))
            pos += len(word) + 1
        return Encoding(tokens=tokens, offsets=offsets)


def post_json(base: str, path: str, payload: dict) -> dict:
    req = urllib.request.Request(
        base + path,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as response:
        return json.loads(response.read())


def get_json(base: str, path: str) -> dict:
    with urllib.request.urlopen(base + path, timeout=10) as response:
        return json.loads(response.read())


def get_text(base: str, path: str) -> str:
    with urllib.request.urlopen(base + path, timeout=10) as response:
        return response.read().decode()


def stored_message(pod: str, seq: int, engine_key: int, tokens, parent):
    batch = EventBatch(
        ts=float(seq),
        events=[
            BlockStored(
                block_hashes=[engine_key],
                parent_block_hash=parent,
                token_ids=list(tokens),
                block_size=BLOCK_SIZE,
            )
        ],
    )
    return Message(
        topic=f"kv@{pod}@{MODEL}",
        payload=batch.encode(),
        pod_identifier=pod,
        model_name=MODEL,
        seq=seq,
    )


def main() -> None:
    with tempfile.TemporaryDirectory() as journal_root:
        cluster = LocalCluster(
            journal_root=journal_root,
            heartbeat_interval_s=0.2,
            follower_poll_s=0.05,
        )
        cluster.start()  # heartbeat + replication followers

        indexer = Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(
                    block_size=BLOCK_SIZE
                ),
                cache_stats=False,
            ),
            tokenizer=WordTokenizer(),
            kv_block_index=cluster.remote_index,
        )
        indexer.run()
        event_pool = Pool(
            cluster.remote_index,
            indexer.token_processor,
            PoolConfig(concurrency=2),
        )
        event_pool.start()
        server = serve(
            indexer,
            host="127.0.0.1",
            port=0,
            replica=None,
            cluster_status=cluster.status,
        )
        base = f"http://127.0.0.1:{server.server_address[1]}"

        # 1. Traffic: 3 pods each claim chained prefixes of 12 prompts
        # through the real event plane -> slice owners.
        prompts = []
        for p in range(12):
            tokens = [p * 100 + i + 1 for i in range(BLOCK_SIZE * 4)]
            prompts.append(" ".join(f"t{t}" for t in tokens))
            for pod_i in range(1 + p % 3):
                pod = f"pod-{pod_i}"
                parent = None
                for block in range(4 - pod_i):
                    engine_key = 10_000 + p * 100 + pod_i * 10 + block
                    chunk = tokens[
                        block * BLOCK_SIZE: (block + 1) * BLOCK_SIZE
                    ]
                    event_pool.add_task(
                        stored_message(
                            pod, p * 10 + block, engine_key, chunk, parent
                        )
                    )
                    parent = engine_key
        event_pool.drain()

        pre_kill = {}
        for prompt in prompts:
            scores = post_json(
                base, "/score_completions", {"prompt": prompt, "model": MODEL}
            )
            pre_kill[prompt] = scores
        assert any(pre_kill.values()), "no prompt scored before the kill"

        # 2. Probe the replica wire surface directly (the method table
        # the HTTP replica endpoint serves).
        transport = cluster.transports["replica-0"]
        assert transport.call("ping", []) == "replica-0"
        encode_request("ping", [])  # codec importable + callable

        # 3. Let the followers drain, then kill a replica MID-TRAFFIC.
        assert cluster.sync_followers() >= 0
        ring = cluster.membership.ring()
        sample_key = indexer.token_processor.tokens_to_kv_block_keys(
            0, [1, 2, 3, 4], MODEL
        )[0]
        victim = ring.owner(sample_key)
        cluster.kill(victim, notice=False)  # the heartbeat must notice

        degraded = 0
        deaths_noticed = False
        for round_i in range(50):
            for prompt in prompts:
                scores = post_json(
                    base,
                    "/score_completions",
                    {"prompt": prompt, "model": MODEL},
                )
                assert isinstance(scores, dict)  # scores keep flowing
            cluster.heartbeat.beat_once()
            if not cluster.membership.is_alive(victim):
                deaths_noticed = True
                break
        assert deaths_noticed, "heartbeat never removed the dead replica"

        # 4. Warm takeover: every pre-kill score reproduced exactly.
        for prompt, want in pre_kill.items():
            got = post_json(
                base, "/score_completions", {"prompt": prompt, "model": MODEL}
            )
            if got != want:
                degraded += 1
        assert degraded <= DEGRADED_PROMPT_BUDGET, (
            f"{degraded} prompts degraded after failover "
            f"(budget {DEGRADED_PROMPT_BUDGET})"
        )

        # 5. Debug + metrics surfaces.
        status = get_json(base, "/debug/cluster")
        membership = status["membership"]
        assert victim not in membership["alive"], membership
        assert membership["failovers"] >= 1, membership
        assert membership["ring_version"] >= 1, membership
        assert status["replication"], status

        metrics_text = get_text(base, "/metrics")
        assert "kvtpu_cluster_failovers_total" in metrics_text
        assert "kvtpu_cluster_ring_version" in metrics_text
        assert "kvtpu_cluster_replication_applied_total" in metrics_text

        server.shutdown()
        event_pool.shutdown()
        indexer.shutdown()
        cluster.close()
    print("cluster smoke completed successfully")


if __name__ == "__main__":
    main()
