"""Event-plane smoke: consolidated poller + flow control + gap resync.

CI gate (`make events-smoke`): boots the consolidated poller with ~64
inproc publishers through the REAL path (PUB socket -> PollerPool demux
-> shard lanes -> batched apply -> index) and asserts the event-plane
contracts from docs/event-plane.md:

* every pod's subscription becomes live and a modest throughput floor
  is sustained (machinery gate, deliberately far below real capacity —
  CI boxes are noisy, so the floor only catches wedges, not
  regressions-by-percent);
* the event plane runs within its thread ceiling
  (pollers + pool workers + resync worker), independent of pod count;
* per-pod flow control: a chatty pod's flood sheds ONLY the chatty pod
  (zero cross-pod sheds — the fairness property);
* a forced sequence gap marks the pod suspect and the anti-entropy
  resync repairs it: suspect set drains, the staleness histogram gains
  a sample, and the pod's inventory chain is re-claimed in the index;
* a publisher seq regression counts as a restart, not a gap;
* replica-local ingestion (cluster/ingest.py): 3 in-process replicas
  slice the fleet disjointly+completely, every pod's chain lands in
  the cluster index through its slice owner, and killing one replica
  mid-stream re-slices its pods onto the survivors whose takeover
  resyncs re-ingest the slice — with no purge-resurrection (a block
  the pod removed pre-kill must not reappear after failover).

The throughput floor runs on the consolidated fast-lane path (batched
sink + lock-free pre-decode), the production default.
"""

from __future__ import annotations

import os
import struct
import sys
import threading
import time
import uuid


def _replica_local_cell(context, block_size, run, model):
    """Replica-local ingestion contracts (docs/event-plane.md):

    * 3 in-process replicas (LocalCluster) each run their own poller
      pool + kvevents pool over a ``ReplicaIngestor``-sliced pod set —
      the slices are disjoint and complete;
    * every pod's stored chain is claimed in the CLUSTER index (the
      ingestors apply through the shared ``RemoteIndex`` view, so
      pod-sliced subscriptions and key-sliced applies compose);
    * killing one replica mid-stream re-slices its pods onto the
      survivors, whose takeover resyncs re-ingest the slice from the
      inventory source — full coverage restored;
    * no purge-resurrection: a block its pod removed BEFORE the kill
      must not reappear after the failover resync.
    """
    import struct
    import threading
    import time

    import zmq

    from llm_d_kv_cache_manager_tpu.cluster import LocalCluster
    from llm_d_kv_cache_manager_tpu.cluster.ingest import (
        ReplicaIngestor,
        pod_owner,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
        EMPTY_BLOCK_HASH,
        ChunkedTokenDatabase,
        TokenProcessorConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvevents.events import (
        BlockRemoved,
        BlockStored,
        EventBatch,
    )
    from llm_d_kv_cache_manager_tpu.kvevents.pool import Pool, PoolConfig
    from llm_d_kv_cache_manager_tpu.kvevents.resync import (
        CallableInventorySource,
        PodInventory,
        InventoryBlock,
        ResyncConfig,
        ResyncManager,
    )
    from llm_d_kv_cache_manager_tpu.kvevents.subscriber_manager import (
        SubscriberManager,
    )

    failures = []
    n_pods = 24
    pods = [f"ri-{run}-{i}" for i in range(n_pods)]
    endpoints = {pod: f"inproc://{pod}" for pod in pods}
    pub = {}
    seqs = {pod: 0 for pod in pods}
    for pod in pods:
        sock = context.socket(zmq.PUB)
        sock.setsockopt(zmq.LINGER, 0)
        sock.bind(endpoints[pod])
        pub[pod] = sock

    def publish(pod, *events):
        seqs[pod] += 1
        pub[pod].send_multipart(
            [
                f"kv@{pod}@{model}".encode(),
                struct.pack(">Q", seqs[pod]),
                EventBatch(ts=0.0, events=list(events)).encode(),
            ]
        )

    # Per-pod ground truth, served back by the takeover resyncs; the
    # driver mutates it when a pod removes a block.
    truth = {}
    for i, pod in enumerate(pods):
        base = 500_000 + 100 * i
        blocks = []
        parent = None
        for b in range(2):
            blocks.append(
                InventoryBlock(
                    block_hashes=[base + b],
                    token_ids=[
                        (base + b * block_size + j) % 5000 + 1
                        for j in range(block_size)
                    ],
                    block_size=block_size,
                    parent_block_hash=parent,
                    medium="hbm",
                )
            )
            parent = base + b
        truth[pod] = blocks

    source = CallableInventorySource(
        lambda pod: PodInventory(
            pod_identifier=pod,
            model_name=model,
            blocks=list(truth[pod]),
        )
    )

    cluster = LocalCluster()
    db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=block_size))
    pools, resyncs, managers, ingestors = {}, {}, {}, {}
    seen = {r: set() for r in cluster.replicas}
    seen_lock = threading.Lock()
    try:
        for replica_id in cluster.replicas:
            ri_pool = Pool(
                cluster.remote_index, db, PoolConfig(concurrency=2)
            )
            ri_pool.start()
            ri_resync = ResyncManager(
                ri_pool, source, ResyncConfig(apply_timeout_s=30)
            )
            ri_resync.start()

            def sink(message, replica_id=replica_id, ri_pool=ri_pool):
                with seen_lock:
                    seen[replica_id].add(message.pod_identifier)
                ri_pool.add_task(message)

            def sink_batch(
                messages, replica_id=replica_id, ri_pool=ri_pool
            ):
                with seen_lock:
                    for message in messages:
                        seen[replica_id].add(message.pod_identifier)
                ri_pool.add_tasks(messages)

            ri_manager = SubscriberManager(
                sink=sink,
                sink_batch=sink_batch,
                context=context,
                pollers=1,
                poll_interval_ms=10,
                on_gap=ri_resync.gap_listener,
            )
            ingestor = ReplicaIngestor(
                replica_id,
                ri_manager,
                membership=cluster.membership,
                resync=ri_resync,
            )
            for pod in pods:
                ingestor.ensure_subscriber(pod, endpoints[pod])
            pools[replica_id] = ri_pool
            resyncs[replica_id] = ri_resync
            managers[replica_id] = ri_manager
            ingestors[replica_id] = ingestor

        # Slices must partition the fleet.
        owned = {r: set(ing.owned_pods()) for r, ing in ingestors.items()}
        union = set().union(*owned.values())
        total = sum(len(s) for s in owned.values())
        if union != set(pods) or total != len(pods):
            failures.append(
                f"replica slices do not partition the fleet: "
                f"{ {r: len(s) for r, s in owned.items()} }"
            )
        ring = cluster.membership.ring()
        for replica_id, pods_owned in owned.items():
            for pod in pods_owned:
                if pod_owner(ring, pod) != replica_id:
                    failures.append(
                        f"pod {pod} subscribed by {replica_id} but "
                        f"owned by {pod_owner(ring, pod)}"
                    )

        # Join: PUB/SUB is lossy pre-subscribe — warm up until every
        # pod is seen by its owner.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            live = set().union(*seen.values())
            if live >= set(pods):
                break
            for pod in pods:
                if pod not in live:
                    publish(pod)
            time.sleep(0.05)
        else:
            failures.append("replica-local subscriptions never all joined")
        for ri_pool in pools.values():
            ri_pool.drain()

        # Live stream: each pod stores its 2-block truth chain.
        for pod in pods:
            for block in truth[pod]:
                publish(
                    pod,
                    BlockStored(
                        block_hashes=list(block.block_hashes),
                        parent_block_hash=block.parent_block_hash,
                        token_ids=list(block.token_ids),
                        block_size=block_size,
                        medium="hbm",
                    ),
                )
        for ri_pool in pools.values():
            ri_pool.drain()

        def chain_keys(pod):
            tokens = [
                t for block in truth[pod] for t in block.token_ids
            ]
            return db.tokens_to_kv_block_keys(
                EMPTY_BLOCK_HASH, tokens, model
            )

        def cluster_claims(pod, keys):
            found = cluster.remote_index.lookup(keys, None)
            return sum(
                1
                for entries in found.values()
                if any(e.pod_identifier == pod for e in entries)
            )

        # PUB delivery is async — poll until the claims land (drain()
        # only covers messages already in the shard queues).
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            missing = sum(
                1
                for pod in pods
                if cluster_claims(pod, chain_keys(pod))
                != len(truth[pod])
            )
            if not missing:
                break
            time.sleep(0.1)
        if missing:
            failures.append(
                f"{missing}/{n_pods} pods' chains not fully claimed in "
                "the cluster index before the kill"
            )

        # One pod REMOVES its 2nd block pre-kill (truth updated): the
        # resurrection bait for the failover resync.
        victim_replica = sorted(cluster.replicas)[0]
        victim_pods = sorted(owned[victim_replica])
        if not victim_pods:
            failures.append(
                f"replica {victim_replica} owns no pods; cannot "
                "exercise failover"
            )
            return failures
        bait_pod = victim_pods[0]
        bait_keys = chain_keys(bait_pod)
        removed_block = truth[bait_pod].pop()
        publish(
            bait_pod,
            BlockRemoved(
                block_hashes=list(removed_block.block_hashes),
                medium="hbm",
            ),
        )
        # The eviction must land BEFORE the kill or the bait is moot.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            found = cluster.remote_index.lookup([bait_keys[-1]], None)
            if not any(
                e.pod_identifier == bait_pod
                for entries in found.values()
                for e in entries
            ):
                break
            time.sleep(0.05)
        else:
            failures.append("pre-kill eviction never applied")

        # Kill the replica: membership listeners re-slice inline; the
        # survivors' takeover resyncs re-ingest the slice async.
        cluster.kill(victim_replica)
        ring = cluster.membership.ring()
        if any(
            pod_owner(ring, pod) == victim_replica for pod in pods
        ):
            failures.append("dead replica still owns pods on the ring")
        leftover = set(ingestors[victim_replica].owned_pods())
        if leftover:
            failures.append(
                f"dead replica's ingestor kept {len(leftover)} pods"
            )

        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if all(
                cluster_claims(pod, chain_keys(pod)) == len(truth[pod])
                for pod in victim_pods
            ):
                break
            time.sleep(0.1)
        else:
            failures.append(
                "failover owners never re-ingested the dead replica's "
                "slice"
            )

        # No purge-resurrection: the pre-kill removed block must stay
        # gone after the takeover resync (inventory no longer lists it).
        found = cluster.remote_index.lookup(bait_keys, None)
        resurrection = [
            key
            for key, entries in found.items()
            if key == bait_keys[-1]
            and any(e.pod_identifier == bait_pod for e in entries)
        ]
        if resurrection:
            failures.append(
                "failover resync resurrected a block the pod removed "
                "before the kill"
            )
    finally:
        for ri_manager in managers.values():
            ri_manager.shutdown()
        for ri_resync in resyncs.values():
            ri_resync.close()
        for ri_pool in pools.values():
            ri_pool.shutdown()
        cluster.close()
        for sock in pub.values():
            sock.close()
    return failures


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

    import zmq

    from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
        EMPTY_BLOCK_HASH,
        ChunkedTokenDatabase,
        TokenProcessorConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
        InMemoryIndex,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
        InMemoryIndexConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvevents.events import (
        BlockStored,
        EventBatch,
    )
    from llm_d_kv_cache_manager_tpu.kvevents.pool import (
        Message,
        Pool,
        PoolConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvevents.resync import (
        CallableInventorySource,
        InventoryBlock,
        PodInventory,
        ResyncConfig,
        ResyncManager,
    )
    from llm_d_kv_cache_manager_tpu.kvevents.subscriber_manager import (
        SubscriberManager,
    )
    from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS

    failures = []
    n_pods = int(os.environ.get("EVENTS_SMOKE_PODS", "64"))
    floor = float(os.environ.get("EVENTS_SMOKE_FLOOR_MSGS_S", "200"))
    window_s = float(os.environ.get("EVENTS_SMOKE_WINDOW_S", "2.0"))
    block_size = 16
    run = uuid.uuid4().hex[:8]
    model = "smoke/model"

    context = zmq.Context()
    context.set(zmq.MAX_SOCKETS, 4 * n_pods + 64)
    pods = [f"smoke-{run}-{i}" for i in range(n_pods)]
    endpoints = {pod: f"inproc://{pod}" for pod in pods}
    pub = {}
    for pod in pods:
        sock = context.socket(zmq.PUB)
        sock.setsockopt(zmq.LINGER, 0)
        sock.bind(endpoints[pod])
        pub[pod] = sock
    seqs = {pod: 0 for pod in pods}
    tokens = list(range(2 * block_size))
    payload = EventBatch(
        ts=0.0,
        events=[
            BlockStored(
                block_hashes=[1, 2],
                parent_block_hash=None,
                token_ids=tokens,
                block_size=block_size,
            )
        ],
    ).encode()

    def publish(pod, body=None, skip=0):
        seqs[pod] += 1 + skip
        pub[pod].send_multipart(
            [
                f"kv@{pod}@{model}".encode(),
                struct.pack(">Q", seqs[pod]),
                body if body is not None else payload,
            ]
        )

    index = InMemoryIndex(InMemoryIndexConfig(size=1_000_000))
    db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=block_size))
    pool = Pool(index, db, PoolConfig(concurrency=4))
    pool.start()

    # Ground truth for the resync: each pod owns one private block.
    truth = {}
    for i, pod in enumerate(pods):
        base = 1000 + i
        truth[pod] = InventoryBlock(
            block_hashes=[base],
            token_ids=[(base + j) % 5000 + 1 for j in range(block_size)],
            block_size=block_size,
            medium="hbm",
        )
    source = CallableInventorySource(
        lambda pod: PodInventory(
            pod_identifier=pod, model_name=model, blocks=[truth[pod]]
        )
    )
    resync = ResyncManager(pool, source, ResyncConfig(apply_timeout_s=30))
    resync.start()

    seen = set()
    seen_lock = threading.Lock()

    def sink(message):
        with seen_lock:
            seen.add(message.pod_identifier)
        pool.add_task(message)

    def sink_batch(messages):
        with seen_lock:
            for message in messages:
                seen.add(message.pod_identifier)
        pool.add_tasks(messages)

    manager = SubscriberManager(
        sink=sink,
        sink_batch=sink_batch,
        context=context,
        pollers=1,
        poll_interval_ms=10,
        on_gap=resync.gap_listener,
    )
    for pod in pods:
        manager.ensure_subscriber(pod, endpoints[pod])

    def hist_stats(hist):
        total = count = 0.0
        for metric in hist.collect():
            for sample in metric.samples:
                if sample.name.endswith("_sum"):
                    total = sample.value
                elif sample.name.endswith("_count"):
                    count = sample.value
        return total, count

    def labeled_total(counter, **labels):
        total = 0.0
        for metric in counter.collect():
            for sample in metric.samples:
                if sample.name.endswith("_total") and all(
                    sample.labels.get(k) == v for k, v in labels.items()
                ):
                    total += sample.value
        return total

    try:
        # -- join ----------------------------------------------------
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and len(seen) < n_pods:
            for pod in pods:
                if pod not in seen:
                    publish(pod)
            time.sleep(0.05)
        if len(seen) < n_pods:
            failures.append(
                f"only {len(seen)}/{n_pods} subscriptions became live"
            )
        pool.drain()

        # -- throughput floor + thread ceiling -----------------------
        _, drained_before = 0.0, None
        drained_before = hist_stats(METRICS.kvevents_batch_size)[0]
        threads = sum(
            1
            for t in threading.enumerate()
            if t.name.startswith(("kvtpu-evplane-", "kvtpu-events-"))
        )
        ceiling = 1 + 4 + 1  # pollers + pool workers + resync worker
        if threads > ceiling:
            failures.append(
                f"event plane runs {threads} threads for {n_pods} pods "
                f"(ceiling {ceiling})"
            )
        t0 = time.perf_counter()
        stop = time.perf_counter() + window_s
        while time.perf_counter() < stop:
            for pod in pods:
                publish(pod)
        pool.drain()
        elapsed = time.perf_counter() - t0
        applied = hist_stats(METRICS.kvevents_batch_size)[0] - drained_before
        rate = applied / elapsed
        if rate < floor:
            failures.append(
                f"apply throughput {rate:.0f} msgs/s below the "
                f"{floor:.0f} floor"
            )

        # -- zero cross-pod sheds under a chatty flood ---------------
        chatty, victims = pods[0], pods[1:]
        victim_shed_before = sum(
            labeled_total(METRICS.kvevents_pod_shed, pod=pod)
            for pod in victims
        )
        for _ in range(5000):
            publish(chatty)
        pool.drain()
        victim_shed = (
            sum(
                labeled_total(METRICS.kvevents_pod_shed, pod=pod)
                for pod in victims
            )
            - victim_shed_before
        )
        if victim_shed:
            failures.append(
                f"chatty flood shed {victim_shed:.0f} messages from "
                "other pods (fairness property violated)"
            )

        # -- forced gap -> resync ------------------------------------
        gap_pod = pods[1]
        # Seed the pod's ground-truth chain live, then lose 5 events.
        publish(
            gap_pod,
            EventBatch(
                ts=0.0,
                events=[
                    BlockStored(
                        block_hashes=list(truth[gap_pod].block_hashes),
                        parent_block_hash=None,
                        token_ids=list(truth[gap_pod].token_ids),
                        block_size=block_size,
                        medium="hbm",
                    )
                ],
            ).encode(),
        )
        pool.drain()
        staleness_n_before = hist_stats(METRICS.kvevents_resync_staleness)[1]
        publish(gap_pod, skip=5)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            stats = resync.stats()
            if stats["resyncs_ok"] >= 1 and not stats["suspect"]:
                break
            time.sleep(0.05)
        stats = resync.stats()
        if stats["resyncs_ok"] < 1 or stats["suspect"]:
            failures.append(f"forced gap did not resync: {stats}")
        if hist_stats(METRICS.kvevents_resync_staleness)[1] <= (
            staleness_n_before
        ):
            failures.append("resync staleness histogram gained no sample")
        keys = db.tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, truth[gap_pod].token_ids, model
        )
        found = index.lookup(keys)
        if set(found) != set(keys) or not all(
            any(e.pod_identifier == gap_pod for e in entries)
            for entries in found.values()
        ):
            failures.append(
                "post-resync index does not claim the pod's inventory"
            )

        # -- publisher restart classified, gaps not inflated ----------
        restarts_before = labeled_total(
            METRICS.kvevents_publisher_restarts, pod=gap_pod
        )
        gaps_before = labeled_total(METRICS.kvevents_seq_gaps, pod=gap_pod)
        seqs[gap_pod] = 0  # simulate engine restart: counter resets
        publish(gap_pod)
        deadline = time.monotonic() + 30
        while (
            time.monotonic() < deadline
            and labeled_total(
                METRICS.kvevents_publisher_restarts, pod=gap_pod
            )
            == restarts_before
        ):
            time.sleep(0.05)
        if (
            labeled_total(METRICS.kvevents_publisher_restarts, pod=gap_pod)
            != restarts_before + 1
        ):
            failures.append("publisher restart not detected")
        if labeled_total(METRICS.kvevents_seq_gaps, pod=gap_pod) != (
            gaps_before
        ):
            failures.append("publisher restart inflated the gap counter")

        # -- replica-local ingestion: slice, kill, failover ----------
        failures.extend(
            _replica_local_cell(context, block_size, run, model)
        )
    finally:
        manager.shutdown()
        resync.close()
        pool.shutdown()
        for sock in pub.values():
            sock.close()
        context.term()

    if failures:
        print("EVENTS SMOKE FAILED:", file=sys.stderr)
        for failure in failures:
            print(f"  - {failure}", file=sys.stderr)
        return 1
    print(
        f"events smoke ok: {n_pods} pods, {rate:.0f} msgs/s applied, "
        f"{threads} event-plane threads, gap resynced, restart "
        "classified, replica-local ingestion failover clean",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
