"""Event-plane smoke: consolidated poller + flow control + gap resync.

CI gate (`make events-smoke`): boots the consolidated poller with ~64
inproc publishers through the REAL path (PUB socket -> PollerPool demux
-> shard lanes -> batched apply -> index) and asserts the event-plane
contracts from docs/event-plane.md:

* every pod's subscription becomes live and a modest throughput floor
  is sustained (machinery gate, deliberately far below real capacity —
  CI boxes are noisy, so the floor only catches wedges, not
  regressions-by-percent);
* the event plane runs within its thread ceiling
  (pollers + pool workers + resync worker), independent of pod count;
* per-pod flow control: a chatty pod's flood sheds ONLY the chatty pod
  (zero cross-pod sheds — the fairness property);
* a forced sequence gap marks the pod suspect and the anti-entropy
  resync repairs it: suspect set drains, the staleness histogram gains
  a sample, and the pod's inventory chain is re-claimed in the index;
* a publisher seq regression counts as a restart, not a gap.
"""

from __future__ import annotations

import os
import struct
import sys
import threading
import time
import uuid


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

    import zmq

    from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
        EMPTY_BLOCK_HASH,
        ChunkedTokenDatabase,
        TokenProcessorConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
        InMemoryIndex,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
        InMemoryIndexConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvevents.events import (
        BlockStored,
        EventBatch,
    )
    from llm_d_kv_cache_manager_tpu.kvevents.pool import (
        Message,
        Pool,
        PoolConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvevents.resync import (
        CallableInventorySource,
        InventoryBlock,
        PodInventory,
        ResyncConfig,
        ResyncManager,
    )
    from llm_d_kv_cache_manager_tpu.kvevents.subscriber_manager import (
        SubscriberManager,
    )
    from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS

    failures = []
    n_pods = int(os.environ.get("EVENTS_SMOKE_PODS", "64"))
    floor = float(os.environ.get("EVENTS_SMOKE_FLOOR_MSGS_S", "200"))
    window_s = float(os.environ.get("EVENTS_SMOKE_WINDOW_S", "2.0"))
    block_size = 16
    run = uuid.uuid4().hex[:8]
    model = "smoke/model"

    context = zmq.Context()
    context.set(zmq.MAX_SOCKETS, 4 * n_pods + 64)
    pods = [f"smoke-{run}-{i}" for i in range(n_pods)]
    endpoints = {pod: f"inproc://{pod}" for pod in pods}
    pub = {}
    for pod in pods:
        sock = context.socket(zmq.PUB)
        sock.setsockopt(zmq.LINGER, 0)
        sock.bind(endpoints[pod])
        pub[pod] = sock
    seqs = {pod: 0 for pod in pods}
    tokens = list(range(2 * block_size))
    payload = EventBatch(
        ts=0.0,
        events=[
            BlockStored(
                block_hashes=[1, 2],
                parent_block_hash=None,
                token_ids=tokens,
                block_size=block_size,
            )
        ],
    ).encode()

    def publish(pod, body=None, skip=0):
        seqs[pod] += 1 + skip
        pub[pod].send_multipart(
            [
                f"kv@{pod}@{model}".encode(),
                struct.pack(">Q", seqs[pod]),
                body if body is not None else payload,
            ]
        )

    index = InMemoryIndex(InMemoryIndexConfig(size=1_000_000))
    db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=block_size))
    pool = Pool(index, db, PoolConfig(concurrency=4))
    pool.start()

    # Ground truth for the resync: each pod owns one private block.
    truth = {}
    for i, pod in enumerate(pods):
        base = 1000 + i
        truth[pod] = InventoryBlock(
            block_hashes=[base],
            token_ids=[(base + j) % 5000 + 1 for j in range(block_size)],
            block_size=block_size,
            medium="hbm",
        )
    source = CallableInventorySource(
        lambda pod: PodInventory(
            pod_identifier=pod, model_name=model, blocks=[truth[pod]]
        )
    )
    resync = ResyncManager(pool, source, ResyncConfig(apply_timeout_s=30))
    resync.start()

    seen = set()
    seen_lock = threading.Lock()

    def sink(message):
        with seen_lock:
            seen.add(message.pod_identifier)
        pool.add_task(message)

    manager = SubscriberManager(
        sink=sink,
        context=context,
        pollers=1,
        poll_interval_ms=10,
        on_gap=resync.gap_listener,
    )
    for pod in pods:
        manager.ensure_subscriber(pod, endpoints[pod])

    def hist_stats(hist):
        total = count = 0.0
        for metric in hist.collect():
            for sample in metric.samples:
                if sample.name.endswith("_sum"):
                    total = sample.value
                elif sample.name.endswith("_count"):
                    count = sample.value
        return total, count

    def labeled_total(counter, **labels):
        total = 0.0
        for metric in counter.collect():
            for sample in metric.samples:
                if sample.name.endswith("_total") and all(
                    sample.labels.get(k) == v for k, v in labels.items()
                ):
                    total += sample.value
        return total

    try:
        # -- join ----------------------------------------------------
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and len(seen) < n_pods:
            for pod in pods:
                if pod not in seen:
                    publish(pod)
            time.sleep(0.05)
        if len(seen) < n_pods:
            failures.append(
                f"only {len(seen)}/{n_pods} subscriptions became live"
            )
        pool.drain()

        # -- throughput floor + thread ceiling -----------------------
        _, drained_before = 0.0, None
        drained_before = hist_stats(METRICS.kvevents_batch_size)[0]
        threads = sum(
            1
            for t in threading.enumerate()
            if t.name.startswith(("kvtpu-evplane-", "kvtpu-events-"))
        )
        ceiling = 1 + 4 + 1  # pollers + pool workers + resync worker
        if threads > ceiling:
            failures.append(
                f"event plane runs {threads} threads for {n_pods} pods "
                f"(ceiling {ceiling})"
            )
        t0 = time.perf_counter()
        stop = time.perf_counter() + window_s
        while time.perf_counter() < stop:
            for pod in pods:
                publish(pod)
        pool.drain()
        elapsed = time.perf_counter() - t0
        applied = hist_stats(METRICS.kvevents_batch_size)[0] - drained_before
        rate = applied / elapsed
        if rate < floor:
            failures.append(
                f"apply throughput {rate:.0f} msgs/s below the "
                f"{floor:.0f} floor"
            )

        # -- zero cross-pod sheds under a chatty flood ---------------
        chatty, victims = pods[0], pods[1:]
        victim_shed_before = sum(
            labeled_total(METRICS.kvevents_pod_shed, pod=pod)
            for pod in victims
        )
        for _ in range(5000):
            publish(chatty)
        pool.drain()
        victim_shed = (
            sum(
                labeled_total(METRICS.kvevents_pod_shed, pod=pod)
                for pod in victims
            )
            - victim_shed_before
        )
        if victim_shed:
            failures.append(
                f"chatty flood shed {victim_shed:.0f} messages from "
                "other pods (fairness property violated)"
            )

        # -- forced gap -> resync ------------------------------------
        gap_pod = pods[1]
        # Seed the pod's ground-truth chain live, then lose 5 events.
        publish(
            gap_pod,
            EventBatch(
                ts=0.0,
                events=[
                    BlockStored(
                        block_hashes=list(truth[gap_pod].block_hashes),
                        parent_block_hash=None,
                        token_ids=list(truth[gap_pod].token_ids),
                        block_size=block_size,
                        medium="hbm",
                    )
                ],
            ).encode(),
        )
        pool.drain()
        staleness_n_before = hist_stats(METRICS.kvevents_resync_staleness)[1]
        publish(gap_pod, skip=5)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            stats = resync.stats()
            if stats["resyncs_ok"] >= 1 and not stats["suspect"]:
                break
            time.sleep(0.05)
        stats = resync.stats()
        if stats["resyncs_ok"] < 1 or stats["suspect"]:
            failures.append(f"forced gap did not resync: {stats}")
        if hist_stats(METRICS.kvevents_resync_staleness)[1] <= (
            staleness_n_before
        ):
            failures.append("resync staleness histogram gained no sample")
        keys = db.tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, truth[gap_pod].token_ids, model
        )
        found = index.lookup(keys)
        if set(found) != set(keys) or not all(
            any(e.pod_identifier == gap_pod for e in entries)
            for entries in found.values()
        ):
            failures.append(
                "post-resync index does not claim the pod's inventory"
            )

        # -- publisher restart classified, gaps not inflated ----------
        restarts_before = labeled_total(
            METRICS.kvevents_publisher_restarts, pod=gap_pod
        )
        gaps_before = labeled_total(METRICS.kvevents_seq_gaps, pod=gap_pod)
        seqs[gap_pod] = 0  # simulate engine restart: counter resets
        publish(gap_pod)
        deadline = time.monotonic() + 30
        while (
            time.monotonic() < deadline
            and labeled_total(
                METRICS.kvevents_publisher_restarts, pod=gap_pod
            )
            == restarts_before
        ):
            time.sleep(0.05)
        if (
            labeled_total(METRICS.kvevents_publisher_restarts, pod=gap_pod)
            != restarts_before + 1
        ):
            failures.append("publisher restart not detected")
        if labeled_total(METRICS.kvevents_seq_gaps, pod=gap_pod) != (
            gaps_before
        ):
            failures.append("publisher restart inflated the gap counter")
    finally:
        manager.shutdown()
        resync.close()
        pool.shutdown()
        for sock in pub.values():
            sock.close()
        context.term()

    if failures:
        print("EVENTS SMOKE FAILED:", file=sys.stderr)
        for failure in failures:
            print(f"  - {failure}", file=sys.stderr)
        return 1
    print(
        f"events smoke ok: {n_pods} pods, {rate:.0f} msgs/s applied, "
        f"{threads} event-plane threads, gap resynced, restart "
        "classified",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
