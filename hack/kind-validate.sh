#!/usr/bin/env bash
# Validate the serving-fleet chart against a REAL Kubernetes API server
# (counterpart of the reference's tests/kind-vllm-cpu.sh).
#
# Phases:
#   1. helm lint + helm template (several value permutations).
#   2. kubectl apply --dry-run=server — full server-side schema +
#      RBAC-object validation of every rendered manifest.
#   3. Install the indexer (vLLM replicas scaled to 0 — kind has no
#      TPUs; shared storage disabled — no Filestore CSI) and wait for
#      /healthz through a port-forward.
#   4. Deploy a stub "serving pod" carrying the discovery label that
#      publishes synthetic BlockStored KVEvents over ZMQ, then assert
#      (a) the reconciler subscribed, (b) the admissions counter moved
#      (events decoded AND indexed), (c) /score_completions answers —
#      the pod-discovery RBAC + subscription + ingestion wiring.
#
# Requires: kind, kubectl, helm, docker. Run from the repo root:
#   bash hack/kind-validate.sh [--keep]
set -euo pipefail

CLUSTER=${KVTPU_KIND_CLUSTER:-kvtpu-validate}
CHART=deploy/chart
IMAGE=kv-cache-indexer-tpu:kind
KEEP=${1:-}

cleanup() {
  if [ "$KEEP" != "--keep" ]; then
    kind delete cluster --name "$CLUSTER" >/dev/null 2>&1 || true
  fi
}
trap cleanup EXIT

echo "== phase 1: helm lint + template permutations"
helm lint "$CHART"
for args in \
  "" \
  "--set valkey.enabled=true" \
  "--set indexer.discovery=false" \
  "--set vllm.offload.enabled=false"; do
  # shellcheck disable=SC2086
  helm template kvtpu "$CHART" $args >/dev/null
  echo "   ok: helm template $args"
done

echo "== phase 2: server-side dry run against a real API server"
kind get clusters | grep -qx "$CLUSTER" || kind create cluster --name "$CLUSTER" --wait 120s
helm template kvtpu "$CHART" \
  --set sharedStorage.enabled=false \
  --set vllm.offload.enabled=false \
  | kubectl --context "kind-$CLUSTER" apply --dry-run=server -f -
echo "   ok: every manifest accepted server-side (schemas + RBAC)"

echo "== phase 3: boot the indexer for real"
docker build -t "$IMAGE" .
kind load docker-image "$IMAGE" --name "$CLUSTER"
helm upgrade --install kvtpu "$CHART" \
  --kube-context "kind-$CLUSTER" \
  --set vllm.replicaCount=0 \
  --set vllm.offload.enabled=false \
  --set sharedStorage.enabled=false \
  --set indexer.image.repository="${IMAGE%%:*}" \
  --set indexer.image.tag="${IMAGE##*:}" \
  --set indexer.image.pullPolicy=Never \
  --set indexer.resources.requests.cpu=100m \
  --set indexer.resources.requests.memory=256Mi \
  --wait --timeout 300s
kubectl --context "kind-$CLUSTER" rollout status deploy -l app.kubernetes.io/component=indexer --timeout=180s

kubectl --context "kind-$CLUSTER" port-forward deploy/kvtpu-kv-cache-indexer 18080:8080 &
PF_PID=$!
trap 'kill $PF_PID 2>/dev/null || true; cleanup' EXIT
sleep 3
curl -fsS http://127.0.0.1:18080/healthz
echo "   ok: indexer /healthz"

echo "== phase 4: discovery wiring via a stub serving pod"
kubectl --context "kind-$CLUSTER" apply -f - <<'EOF'
apiVersion: v1
kind: Pod
metadata:
  name: stub-engine
  labels:
    llm-d.ai/inferenceServing: "true"
spec:
  containers:
    - name: publisher
      image: python:3.12-slim
      ports: [{containerPort: 5557}]
      command: ["/bin/sh", "-c"]
      args:
        - |
          pip -q install pyzmq msgpack && python - <<'PY'
          import time, struct, msgpack, zmq
          sock = zmq.Context().socket(zmq.PUB)
          sock.bind("tcp://0.0.0.0:5557")
          time.sleep(2)  # slow joiner
          seq = 0
          while True:
              seq += 1
              batch = msgpack.packb([time.time(), [
                  ["BlockStored", [seq], None, [1, 2, 3, 4], 4,
                   None, "hbm", None],
              ], None])
              sock.send_multipart([
                  b"kv@stub-engine@stub-model",
                  struct.pack(">Q", seq), batch])
              time.sleep(1)
          PY
EOF
kubectl --context "kind-$CLUSTER" wait --for=condition=Ready pod/stub-engine --timeout=180s
sleep 10  # reconciler watch + subscription + a few events
kubectl --context "kind-$CLUSTER" logs deploy/kvtpu-kv-cache-indexer | grep -q "subscribed to pod" \
  || { echo "FAIL: reconciler never subscribed to the stub pod"; exit 1; }
echo "   ok: reconciler discovered the stub pod and subscribed"
# Ingestion proof: admissions counter > 0 means the stub's events were
# decoded and indexed (subscription alone would not move it).
ADMITTED=$(curl -fsS http://127.0.0.1:18080/metrics \
  | awk '/^kvtpu_kvcache_index_admissions_total/ {print $2}')
echo "   admissions_total=$ADMITTED"
python3 - "$ADMITTED" <<'PY'
import sys
assert float(sys.argv[1]) > 0, "no events were ingested"
PY
echo "   ok: stub events decoded and admitted into the index"
# API liveness for the scoring surface (a hash MATCH needs a real model
# tokenizer, which the stub fleet doesn't carry; ingestion is asserted
# via the metric above instead).
curl -fsS -X POST http://127.0.0.1:18080/score_completions \
  -H 'Content-Type: application/json' \
  -d '{"prompt": "probe", "model": "stub-model"}' >/dev/null \
  && echo "   ok: /score_completions answers"
echo "== all phases passed"
