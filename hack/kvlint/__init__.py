"""kvlint — project-invariant static analysis (stdlib ``ast``, no deps).

Generic linters can't see this project's correctness contracts; these
rules encode them (each in its own module, docs/static-analysis.md).

The analyzer is **two-phase**: every file is parsed once, the
*project-model* pass (model.py) builds a cross-file symbol table
(classes, locks and their guarded-by bindings, ``with``-lock nesting,
env-var reads, metric registrations, trace stage names, the documented
surface parsed from docs/), then rules run — per-file rules over each
:class:`SourceFile`, whole-program rules over the
:class:`~hack.kvlint.model.ProjectModel`.

Per-file rules:

* KV001 lock discipline — ``# guarded-by:`` attributes only touched
  under their lock (kv001_locks)
* KV002 tracer safety — no Python control flow / host calls on traced
  values in ``ops/`` and ``models/`` (kv002_tracer)
* KV003 canonical serialization — hashed/journaled bytes go through
  ``kvblock/cbor_canonical`` only (kv003_serialization)
* KV004 blocking-in-async — no sync sleep/socket/file I/O inside
  ``async def`` (kv004_async)
* KV005 swallowed errors — no bare/broad excepts that hide failures
  in worker loops (kv005_except)
* KV008 shutdown discipline — threads/executors/sockets a class
  creates need a reachable close/stop/shutdown path (kv008_resources)
* KV009 atomicity — a guarded attr read under one lock acquisition
  must not feed a write under a separate acquisition of the same lock
  (check-then-act), unless ``# kvlint: atomic-ok`` (kv009_atomicity)
* KV010 GIL-dependence — unguarded mutation of shared attrs on
  lock-owning classes needs ``# gil-atomic: <why>``; the annotated
  sites form the GIL-dependence inventory (kv010_gil)

Whole-program rules (consume the project model):

* KV006 lock order — the global lock-acquisition graph must be
  acyclic and consistent with declared
  ``# kvlint: lock-order: A < B`` intent (kv006_lockorder)
* KV007 contract-surface drift — env knobs, metric names, and trace
  stage names must agree between code and
  docs/configuration.md + docs/observability.md (kv007_contracts)

CLI: ``python -m hack.kvlint [paths...]`` — exit 0 clean, 1 findings,
2 usage/internal error.  Output: ``path:line: RULE: message``.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from hack.kvlint import (
    kv001_locks,
    kv002_tracer,
    kv003_serialization,
    kv004_async,
    kv005_except,
    kv006_lockorder,
    kv007_contracts,
    kv008_resources,
    kv009_atomicity,
    kv010_gil,
)
from hack.kvlint.base import Finding, SourceFile, SourceParseError
from hack.kvlint.model import ProjectModel, build_model

RULES = (
    kv001_locks,
    kv002_tracer,
    kv003_serialization,
    kv004_async,
    kv005_except,
    kv008_resources,
    kv009_atomicity,
    kv010_gil,
)
PROJECT_RULES = (
    kv006_lockorder,
    kv007_contracts,
)
RULE_IDS = tuple(rule.RULE for rule in RULES) + tuple(
    rule.RULE for rule in PROJECT_RULES
)


def collect_files(paths: Sequence[str]) -> List[str]:
    """Expand files/directories into a sorted .py file list."""
    out: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            for root, dirs, names in os.walk(path):
                dirs[:] = sorted(
                    d
                    for d in dirs
                    if d != "__pycache__" and not d.startswith(".")
                )
                for name in sorted(names):
                    if name.endswith(".py"):
                        out.append(os.path.join(root, name))
        elif path.endswith(".py"):
            out.append(path)
    return out


def _parse(path: str) -> SourceFile:
    with open(path, encoding="utf-8") as handle:
        text = handle.read()
    return SourceFile(path, text)


def check_file(
    path: str, rules: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Per-file rules over one file (the whole-program rules need the
    project model; use :func:`check_paths` for those)."""
    try:
        source = _parse(path)
    except SourceParseError as exc:
        return [Finding(path, 0, "KV000", str(exc))]
    findings: List[Finding] = []
    for rule in RULES:
        if rules and rule.RULE not in rules:
            continue
        findings.extend(rule.check(source))
    findings.sort(key=lambda f: (f.line, f.rule, f.message))
    return findings


def _parse_and_check(
    path: str, rules: Optional[Sequence[str]]
) -> "tuple[Optional[SourceFile], List[Finding]]":
    """Parse one file ONCE and run the per-file rules over it; the
    returned :class:`SourceFile` (tree + comments) is reused verbatim
    by phase 1 (``build_model``) and the manifest/inventory emitters —
    no path is ever read or parsed twice in a run."""
    try:
        source = _parse(path)
    except SourceParseError as exc:
        return None, [Finding(path, 0, "KV000", str(exc))]
    findings: List[Finding] = []
    for rule in RULES:
        if rules and rule.RULE not in rules:
            continue
        findings.extend(rule.check(source))
    return source, findings


def _parse_and_check_job(item):
    # ProcessPoolExecutor.map needs a single-argument top-level callable.
    return _parse_and_check(*item)


def analyze_paths(
    paths: Sequence[str],
    rules: Optional[Sequence[str]] = None,
    jobs: int = 1,
) -> "tuple[List[Finding], List[SourceFile]]":
    """Two-phase whole-program run: parse every file once (in parallel
    when ``jobs > 1``), run the per-file rules, build the project
    model, run the project rules.  Returns the findings AND the parsed
    sources so callers (manifest emission, staleness check, the GIL
    inventory) share the same single pass.

    ``jobs > 1`` fans the parse+per-file-rule stage out over a process
    pool; ``map`` preserves submission order and the final sort is
    total, so output is byte-identical to the sequential path (pinned
    by the CLI contract test).
    """
    files = collect_files(paths)
    rule_filter = tuple(rules) if rules else None
    if jobs > 1 and len(files) > 1:
        from concurrent.futures import ProcessPoolExecutor

        with ProcessPoolExecutor(
            max_workers=min(jobs, len(files))
        ) as pool:
            results = list(
                pool.map(
                    _parse_and_check_job,
                    ((path, rule_filter) for path in files),
                    chunksize=8,
                )
            )
    else:
        results = [_parse_and_check(path, rule_filter) for path in files]
    findings: List[Finding] = []
    sources: List[SourceFile] = []
    for source, file_findings in results:
        findings.extend(file_findings)
        if source is not None:
            sources.append(source)
    if any(not rules or rule.RULE in rules for rule in PROJECT_RULES):
        model = build_model(sources, paths)
        for rule in PROJECT_RULES:
            if rules and rule.RULE not in rules:
                continue
            findings.extend(rule.check_project(model))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings, sources


def check_paths(
    paths: Sequence[str], rules: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Findings-only wrapper over :func:`analyze_paths`."""
    return analyze_paths(paths, rules)[0]
