"""kvlint — project-invariant static analysis (stdlib ``ast``, no deps).

Generic linters can't see this project's correctness contracts; these
rules encode them (each in its own module, docs/static-analysis.md).

The analyzer is **two-phase**: every file is parsed once, the
*project-model* pass (model.py) builds a cross-file symbol table
(classes, locks and their guarded-by bindings, ``with``-lock nesting,
env-var reads, metric registrations, trace stage names, the documented
surface parsed from docs/), then rules run — per-file rules over each
:class:`SourceFile`, whole-program rules over the
:class:`~hack.kvlint.model.ProjectModel`.

Per-file rules:

* KV001 lock discipline — ``# guarded-by:`` attributes only touched
  under their lock (kv001_locks)
* KV002 tracer safety — no Python control flow / host calls on traced
  values in ``ops/`` and ``models/`` (kv002_tracer)
* KV003 canonical serialization — hashed/journaled bytes go through
  ``kvblock/cbor_canonical`` only (kv003_serialization)
* KV004 blocking-in-async — no sync sleep/socket/file I/O inside
  ``async def`` (kv004_async)
* KV005 swallowed errors — no bare/broad excepts that hide failures
  in worker loops (kv005_except)
* KV008 shutdown discipline — threads/executors/sockets a class
  creates need a reachable close/stop/shutdown path (kv008_resources)

Whole-program rules (consume the project model):

* KV006 lock order — the global lock-acquisition graph must be
  acyclic and consistent with declared
  ``# kvlint: lock-order: A < B`` intent (kv006_lockorder)
* KV007 contract-surface drift — env knobs, metric names, and trace
  stage names must agree between code and
  docs/configuration.md + docs/observability.md (kv007_contracts)

CLI: ``python -m hack.kvlint [paths...]`` — exit 0 clean, 1 findings,
2 usage/internal error.  Output: ``path:line: RULE: message``.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from hack.kvlint import (
    kv001_locks,
    kv002_tracer,
    kv003_serialization,
    kv004_async,
    kv005_except,
    kv006_lockorder,
    kv007_contracts,
    kv008_resources,
)
from hack.kvlint.base import Finding, SourceFile, SourceParseError
from hack.kvlint.model import ProjectModel, build_model

RULES = (
    kv001_locks,
    kv002_tracer,
    kv003_serialization,
    kv004_async,
    kv005_except,
    kv008_resources,
)
PROJECT_RULES = (
    kv006_lockorder,
    kv007_contracts,
)
RULE_IDS = tuple(rule.RULE for rule in RULES) + tuple(
    rule.RULE for rule in PROJECT_RULES
)


def collect_files(paths: Sequence[str]) -> List[str]:
    """Expand files/directories into a sorted .py file list."""
    out: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            for root, dirs, names in os.walk(path):
                dirs[:] = sorted(
                    d
                    for d in dirs
                    if d != "__pycache__" and not d.startswith(".")
                )
                for name in sorted(names):
                    if name.endswith(".py"):
                        out.append(os.path.join(root, name))
        elif path.endswith(".py"):
            out.append(path)
    return out


def _parse(path: str) -> SourceFile:
    with open(path, encoding="utf-8") as handle:
        text = handle.read()
    return SourceFile(path, text)


def check_file(
    path: str, rules: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Per-file rules over one file (the whole-program rules need the
    project model; use :func:`check_paths` for those)."""
    try:
        source = _parse(path)
    except SourceParseError as exc:
        return [Finding(path, 0, "KV000", str(exc))]
    findings: List[Finding] = []
    for rule in RULES:
        if rules and rule.RULE not in rules:
            continue
        findings.extend(rule.check(source))
    findings.sort(key=lambda f: (f.line, f.rule, f.message))
    return findings


def check_paths(
    paths: Sequence[str], rules: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Two-phase whole-program run: parse every file once, run the
    per-file rules, build the project model, run the project rules."""
    findings: List[Finding] = []
    sources: List[SourceFile] = []
    for path in collect_files(paths):
        try:
            source = _parse(path)
        except SourceParseError as exc:
            findings.append(Finding(path, 0, "KV000", str(exc)))
            continue
        sources.append(source)
        for rule in RULES:
            if rules and rule.RULE not in rules:
                continue
            findings.extend(rule.check(source))
    if any(not rules or rule.RULE in rules for rule in PROJECT_RULES):
        model = build_model(sources, paths)
        for rule in PROJECT_RULES:
            if rules and rule.RULE not in rules:
                continue
            findings.extend(rule.check_project(model))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings
