"""kvlint — project-invariant static analysis (stdlib ``ast``, no deps).

Generic linters can't see this project's correctness contracts; these
rules encode them (each in its own module, docs/static-analysis.md):

* KV001 lock discipline — ``# guarded-by:`` attributes only touched
  under their lock (kv001_locks)
* KV002 tracer safety — no Python control flow / host calls on traced
  values in ``ops/`` and ``models/`` (kv002_tracer)
* KV003 canonical serialization — hashed/journaled bytes go through
  ``kvblock/cbor_canonical`` only (kv003_serialization)
* KV004 blocking-in-async — no sync sleep/socket/file I/O inside
  ``async def`` (kv004_async)
* KV005 swallowed errors — no bare/broad excepts that hide failures
  in worker loops (kv005_except)

CLI: ``python -m hack.kvlint [paths...]`` — exit 0 clean, 1 findings,
2 usage/internal error.  Output: ``path:line: RULE: message``.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from hack.kvlint import (
    kv001_locks,
    kv002_tracer,
    kv003_serialization,
    kv004_async,
    kv005_except,
)
from hack.kvlint.base import Finding, SourceFile, SourceParseError

RULES = (
    kv001_locks,
    kv002_tracer,
    kv003_serialization,
    kv004_async,
    kv005_except,
)
RULE_IDS = tuple(rule.RULE for rule in RULES)


def collect_files(paths: Sequence[str]) -> List[str]:
    """Expand files/directories into a sorted .py file list."""
    out: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            for root, dirs, names in os.walk(path):
                dirs[:] = sorted(
                    d
                    for d in dirs
                    if d != "__pycache__" and not d.startswith(".")
                )
                for name in sorted(names):
                    if name.endswith(".py"):
                        out.append(os.path.join(root, name))
        elif path.endswith(".py"):
            out.append(path)
    return out


def check_file(
    path: str, rules: Optional[Sequence[str]] = None
) -> List[Finding]:
    with open(path, encoding="utf-8") as handle:
        text = handle.read()
    try:
        source = SourceFile(path, text)
    except SourceParseError as exc:
        return [Finding(path, 0, "KV000", str(exc))]
    findings: List[Finding] = []
    for rule in RULES:
        if rules and rule.RULE not in rules:
            continue
        findings.extend(rule.check(source))
    findings.sort(key=lambda f: (f.line, f.rule, f.message))
    return findings


def check_paths(
    paths: Sequence[str], rules: Optional[Sequence[str]] = None
) -> List[Finding]:
    findings: List[Finding] = []
    for path in collect_files(paths):
        findings.extend(check_file(path, rules))
    return findings
