"""CLI: ``python -m hack.kvlint [paths...]`` — see package docstring.

Exit codes: 0 clean, 1 findings (or a stale manifest under
``--check-manifest``), 2 usage error.  Findings go to stdout as
``path:line: RULE: message`` (the format is pinned by a contract
test); baseline/stale diagnostics go to stderr.

Raceguard-plane emitters (docs/static-analysis.md):

* ``--emit-manifest [FILE]`` — write the guarded-by manifest (phase
  1's class→{guarded attrs, lock, caller-locked} model) to FILE, the
  checked-in ``hack/kvlint/raceguard_manifest.json`` when omitted, or
  stdout for ``-``; exits 0.
* ``--check-manifest`` — additionally fail (exit 1) when the checked
  in manifest is stale vs the annotations (CI + pre-commit shape).
* ``--emit-gil-inventory [FILE]`` — write the GIL-dependence
  inventory (every ``# gil-atomic:`` site) as JSON; stdout default.
"""

from __future__ import annotations

import argparse
import os
import sys

from hack.kvlint import RULE_IDS, analyze_paths
from hack.kvlint import baseline as baseline_mod
from hack.kvlint import kv010_gil
from hack.kvlint import manifest as manifest_mod

DEFAULT_PATHS = ("llm_d_kv_cache_manager_tpu",)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hack.kvlint",
        description="Project-invariant static analysis (KV001-KV010).",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=list(DEFAULT_PATHS),
        help="files or directories (default: the package tree)",
    )
    parser.add_argument(
        "--rules",
        help="comma-separated rule subset, e.g. KV001,KV005",
    )
    parser.add_argument(
        "--baseline",
        default=baseline_mod.DEFAULT_BASELINE,
        help="baseline file of grandfathered findings",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline (report everything)",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="rewrite the baseline from current findings and exit 0",
    )
    parser.add_argument(
        "--jobs",
        type=int,
        default=1,
        metavar="N",
        help="parse files on N worker processes (same output, pinned "
        "by the contract test)",
    )
    parser.add_argument(
        "--emit-manifest",
        nargs="?",
        const="",
        default=None,
        metavar="FILE",
        help="write the raceguard guarded-by manifest (default: the "
        "checked-in hack/kvlint/raceguard_manifest.json; '-' = stdout) "
        "and exit",
    )
    parser.add_argument(
        "--check-manifest",
        action="store_true",
        help="fail when the checked-in raceguard manifest is stale "
        "vs the # guarded-by: annotations",
    )
    parser.add_argument(
        "--emit-gil-inventory",
        nargs="?",
        const="-",
        default=None,
        metavar="FILE",
        help="write the GIL-dependence inventory ('-' = stdout, the "
        "default) and exit",
    )
    args = parser.parse_args(argv)

    rules = None
    if args.rules:
        rules = tuple(r.strip() for r in args.rules.split(",") if r.strip())
        unknown = [r for r in rules if r not in RULE_IDS]
        if unknown:
            parser.error(f"unknown rule(s): {', '.join(unknown)}")

    findings, sources = analyze_paths(args.paths, rules, jobs=args.jobs)

    if args.emit_manifest is not None:
        rendered = manifest_mod.render(
            manifest_mod.build_manifest(sources, args.paths)
        )
        target = args.emit_manifest
        if target == "":
            target = manifest_mod.manifest_path(args.paths) or "-"
        if target == "-":
            sys.stdout.write(rendered)
        else:
            parent = os.path.dirname(os.path.abspath(target))
            os.makedirs(parent, exist_ok=True)
            with open(target, "w", encoding="utf-8") as handle:
                handle.write(rendered)
            print(f"kvlint: wrote manifest to {target}", file=sys.stderr)
        return 0

    if args.emit_gil_inventory is not None:
        rendered = kv010_gil.render_inventory(
            kv010_gil.collect_inventory(sources)
        )
        if args.emit_gil_inventory == "-":
            sys.stdout.write(rendered)
        else:
            with open(
                args.emit_gil_inventory, "w", encoding="utf-8"
            ) as handle:
                handle.write(rendered)
            print(
                "kvlint: wrote GIL-dependence inventory to "
                f"{args.emit_gil_inventory}",
                file=sys.stderr,
            )
        return 0

    manifest_diags = []
    if args.check_manifest:
        manifest_diags = manifest_mod.check_stale(sources, args.paths)

    if args.write_baseline:
        count = baseline_mod.write(args.baseline, findings, rules=rules)
        print(
            f"kvlint: wrote {count} baseline entr"
            f"{'y' if count == 1 else 'ies'} to {args.baseline}",
            file=sys.stderr,
        )
        return 0

    stale = []
    if not args.no_baseline:
        entries = baseline_mod.load(args.baseline)
        findings, stale = baseline_mod.apply(findings, entries)

    for finding in findings:
        print(finding.format())
    for entry in stale:
        print(f"kvlint: stale baseline entry: {entry}", file=sys.stderr)
    for diag in manifest_diags:
        print(f"kvlint: {diag}", file=sys.stderr)
    if findings:
        print(
            f"kvlint: {len(findings)} finding"
            f"{'' if len(findings) == 1 else 's'}",
            file=sys.stderr,
        )
    return 1 if findings or manifest_diags else 0


if __name__ == "__main__":
    sys.exit(main())
