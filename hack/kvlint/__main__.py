"""CLI: ``python -m hack.kvlint [paths...]`` — see package docstring.

Exit codes: 0 clean, 1 findings, 2 usage error.  Findings go to
stdout as ``path:line: RULE: message`` (the format is pinned by a
contract test); baseline/stale diagnostics go to stderr.
"""

from __future__ import annotations

import argparse
import sys

from hack.kvlint import RULE_IDS, check_paths
from hack.kvlint import baseline as baseline_mod

DEFAULT_PATHS = ("llm_d_kv_cache_manager_tpu",)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hack.kvlint",
        description="Project-invariant static analysis (KV001-KV008).",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=list(DEFAULT_PATHS),
        help="files or directories (default: the package tree)",
    )
    parser.add_argument(
        "--rules",
        help="comma-separated rule subset, e.g. KV001,KV005",
    )
    parser.add_argument(
        "--baseline",
        default=baseline_mod.DEFAULT_BASELINE,
        help="baseline file of grandfathered findings",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline (report everything)",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="rewrite the baseline from current findings and exit 0",
    )
    args = parser.parse_args(argv)

    rules = None
    if args.rules:
        rules = tuple(r.strip() for r in args.rules.split(",") if r.strip())
        unknown = [r for r in rules if r not in RULE_IDS]
        if unknown:
            parser.error(f"unknown rule(s): {', '.join(unknown)}")

    findings = check_paths(args.paths, rules)

    if args.write_baseline:
        count = baseline_mod.write(args.baseline, findings, rules=rules)
        print(
            f"kvlint: wrote {count} baseline entr"
            f"{'y' if count == 1 else 'ies'} to {args.baseline}",
            file=sys.stderr,
        )
        return 0

    stale = []
    if not args.no_baseline:
        entries = baseline_mod.load(args.baseline)
        findings, stale = baseline_mod.apply(findings, entries)

    for finding in findings:
        print(finding.format())
    for entry in stale:
        print(f"kvlint: stale baseline entry: {entry}", file=sys.stderr)
    if findings:
        print(
            f"kvlint: {len(findings)} finding"
            f"{'' if len(findings) == 1 else 's'}",
            file=sys.stderr,
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
