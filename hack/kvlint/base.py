"""Shared model for kvlint rules: parsed source, comments, suppression.

Every rule sees a :class:`SourceFile` — the AST plus the comment map the
AST drops (``ast`` has no comments; ``tokenize`` recovers them), which
is where the project conventions live:

* ``# guarded-by: <lock>`` declares a lock-guarded attribute (KV001)
* ``# kvlint: caller-locked`` marks a method whose callers hold the lock
* ``# kvlint: disable=KV001[,KV005]`` suppresses findings on that line
  (or the line directly below it, for wrapped statements)

Findings print as ``path:line: RULE: message`` — one per line, machine
parseable (pinned by tests/test_kvlint.py's contract test).
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"

    def baseline_key(self) -> str:
        """Line-number-free identity, so unrelated edits above a
        grandfathered finding don't invalidate the baseline entry."""
        return f"{self.path}: {self.rule}: {self.message}"


_DISABLE_RE = re.compile(r"kvlint:\s*disable=([A-Z0-9,\s]+)")
CALLER_LOCKED_MARK = "kvlint: caller-locked"


class SourceParseError(Exception):
    """The file could not be tokenized/parsed; reported as a finding."""


class SourceFile:
    """One parsed Python file: AST + comments + suppression map."""

    def __init__(self, path: str, text: str) -> None:
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        try:
            self.tree = ast.parse(text, filename=path)
        except SyntaxError as exc:
            raise SourceParseError(
                f"syntax error: {exc.msg} (line {exc.lineno})"
            ) from exc
        # line -> (col, comment text) for every comment token.
        self.comments: Dict[int, Tuple[int, str]] = {}
        try:
            for tok in tokenize.generate_tokens(
                io.StringIO(text).readline
            ):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = (tok.start[1], tok.string)
        except tokenize.TokenError:  # pragma: no cover - parse succeeded
            pass
        self._disabled: Dict[int, Set[str]] = {}
        for lineno, (_, comment) in self.comments.items():
            match = _DISABLE_RE.search(comment)
            if match:
                self._disabled[lineno] = {
                    rule.strip()
                    for rule in match.group(1).split(",")
                    if rule.strip()
                }

    def comment_on(self, lineno: int) -> Optional[str]:
        entry = self.comments.get(lineno)
        return entry[1] if entry else None

    def code_before_comment(self, lineno: int) -> str:
        """The source line with any trailing comment stripped."""
        line = self.lines[lineno - 1] if lineno <= len(self.lines) else ""
        entry = self.comments.get(lineno)
        if entry and entry[0] <= len(line):
            return line[: entry[0]]
        return line

    def suppressed(self, lineno: int, rule: str) -> bool:
        """``# kvlint: disable=RULE`` on the flagged line or the line
        above it (wrapped statements report their first line)."""
        for candidate in (lineno, lineno - 1):
            rules = self._disabled.get(candidate)
            if rules and rule in rules:
                return True
        return False


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains; None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def iter_functions(tree: ast.AST):
    """Every (Async)FunctionDef in the tree, outermost first."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def param_names(args: ast.arguments) -> List[str]:
    names = [a.arg for a in args.posonlyargs]
    names += [a.arg for a in args.args]
    if args.vararg:
        names.append(args.vararg.arg)
    names += [a.arg for a in args.kwonlyargs]
    if args.kwarg:
        names.append(args.kwarg.arg)
    return names
