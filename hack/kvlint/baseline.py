"""Baseline file: intentionally-grandfathered kvlint findings.

Format: one finding per line, ``path: RULE: message`` — the line number
is deliberately omitted so unrelated edits above a grandfathered site
don't invalidate its entry.  Lines starting with ``#`` are comments
(use them to justify every entry); blank lines are ignored.

Workflow (docs/static-analysis.md):

* new violations fail the build — fix them, suppress with a justified
  ``# kvlint: disable=KV00x``, or (last resort) baseline them with
  ``python -m hack.kvlint --write-baseline``;
* a baseline entry that no longer matches anything is reported as
  stale (stderr) so the file shrinks monotonically toward empty.
"""

from __future__ import annotations

import os
import re
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from hack.kvlint.base import Finding

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.txt"
)


def load(path: str) -> Dict[str, int]:
    """key -> grandfathered occurrence count.

    Counted, not set-matched: one baselined swallowed-except must not
    also grandfather a *second* identical finding added later to the
    same file (same rule, same message, line numbers omitted).  A
    duplicate line in the file grandfathers a second occurrence.
    """
    if not os.path.exists(path):
        return {}
    entries: Counter = Counter()
    with open(path, encoding="utf-8") as handle:
        for line in handle:
            line = line.strip()
            if line and not line.startswith("#"):
                entries[line] += 1
    return dict(entries)


def apply(
    findings: Iterable[Finding], entries: Dict[str, int]
) -> Tuple[List[Finding], List[str]]:
    """(surviving findings, stale baseline entries).

    Each baseline entry absorbs at most its counted occurrences; any
    finding beyond that budget survives and fails the build."""
    kept: List[Finding] = []
    remaining = Counter(entries)
    for finding in findings:
        key = finding.baseline_key()
        if remaining.get(key, 0) > 0:
            remaining[key] -= 1
        else:
            kept.append(finding)
    stale = sorted(
        key for key, count in remaining.items() if count > 0
        for _ in range(count)
    )
    return kept, stale


def _entry_rule(key: str) -> Optional[str]:
    """The rule id of a ``path: RULE: message`` baseline line."""
    match = re.search(r":\s*(KV\d{3}):", key)
    return match.group(1) if match else None


def write(
    path: str,
    findings: Iterable[Finding],
    rules: Optional[Sequence[str]] = None,
) -> int:
    """Rewrite the baseline from ``findings``.

    A scoped run (``--rules KV005 --write-baseline``) only saw KV005
    findings, so it may only rewrite KV005 *entries*: existing entries
    for unselected rules are carried over verbatim, never truncated.
    A full run (``rules is None``) replaces the whole file.
    """
    keys = sorted(f.baseline_key() for f in findings)
    if rules:
        selected = set(rules)
        carried: List[str] = []
        for key, count in sorted(load(path).items()):
            rule = _entry_rule(key)
            if rule is not None and rule not in selected:
                carried.extend([key] * count)
        keys = sorted(keys + carried)
    with open(path, "w", encoding="utf-8") as handle:
        handle.write(
            "# kvlint baseline — grandfathered findings (justify each "
            "entry;\n# see docs/static-analysis.md).  Regenerate with\n"
            "#   python -m hack.kvlint --write-baseline\n"
            "# One line per finding: a key occurring N times "
            "grandfathers N occurrences.\n"
        )
        for key in keys:
            handle.write(key + "\n")
    return len(keys)
