"""Shared guarded-by model: one parser for every consumer.

KV001 (lock discipline), KV009 (atomicity), KV010 (GIL dependence) and
the raceguard manifest emitter all need the same facts about a class:
which attributes are declared ``# guarded-by: <lock>``, which methods
are caller-locked, and which attributes hold locks.  PR 2 kept that
logic private to kv001_locks; this module is the single home so the
static rules, the runtime manifest, and the docs can never drift on
what the annotations *mean*.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set

from hack.kvlint.base import CALLER_LOCKED_MARK, SourceFile, dotted_name

GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(?:self\.)?([A-Za-z_]\w*)")
DECL_ATTR_RE = re.compile(r"self\.([A-Za-z_]\w*)\s*[:=]")

# `# gil-atomic: <why>` — a deliberate GIL-dependent mutation (KV010);
# every annotated site feeds the machine-readable GIL-dependence
# inventory (`--emit-gil-inventory`, the ROADMAP item-2 worklist).
GIL_ATOMIC_RE = re.compile(r"#\s*gil-atomic:\s*(.+?)\s*$")

# `# kvlint: atomic-ok` — a declared-benign check-then-act (KV009).
ATOMIC_OK_MARK = "kvlint: atomic-ok"

_LOCK_FACTORIES = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "Lock",
    "RLock",
    "Condition",
}


def is_lock_call(node: ast.AST) -> bool:
    """``threading.Lock()`` etc., optionally wrapped by
    ``lockorder.tracked(threading.Lock(), ...)``."""
    if not isinstance(node, ast.Call):
        return False
    callee = dotted_name(node.func)
    if callee in _LOCK_FACTORIES:
        return True
    if callee and callee.rsplit(".", 1)[-1] == "tracked" and node.args:
        return is_lock_call(node.args[0])
    return False


def class_span(cls: ast.ClassDef) -> range:
    end = cls.lineno
    for node in ast.walk(cls):
        end = max(end, getattr(node, "end_lineno", 0) or 0)
    return range(cls.lineno, end + 1)


def collect_guards(source: SourceFile, cls: ast.ClassDef) -> Dict[str, str]:
    """attr name -> guarding lock attr, from ``# guarded-by:`` comments
    on ``self.<attr> = ...`` lines inside the class body."""
    guards: Dict[str, str] = {}
    for lineno in class_span(cls):
        comment = source.comment_on(lineno)
        if not comment:
            continue
        match = GUARDED_RE.search(comment)
        if not match:
            continue
        decl = DECL_ATTR_RE.search(source.code_before_comment(lineno))
        if decl:
            guards[decl.group(1)] = match.group(1)
    return guards


def is_caller_locked(source: SourceFile, func: ast.AST) -> bool:
    if func.name.endswith("_locked"):
        return True
    comment = source.comment_on(func.lineno)
    return bool(comment and CALLER_LOCKED_MARK in comment)


def caller_locked_methods(
    source: SourceFile, cls: ast.ClassDef
) -> List[str]:
    """Names of the class's caller-locked methods (suffix or mark)."""
    out: List[str] = []
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if is_caller_locked(source, item):
                out.append(item.name)
    return out


def lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attributes the class assigns a lock to (``self.x = Lock()``),
    anywhere in its body — the per-file twin of the project model's
    ``ClassModel.lock_attrs``."""
    attrs: Set[str] = set()
    for node in ast.walk(cls):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign) and is_lock_call(node.value):
            targets = list(node.targets)
        elif (
            isinstance(node, ast.AnnAssign)
            and node.value is not None
            and is_lock_call(node.value)
        ):
            targets = [node.target]
        for target in targets:
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                attrs.add(target.attr)
            elif isinstance(target, ast.Name) and _in_class_body(
                cls, node
            ):
                # Dataclass field: `_done_lock: Lock = field(...)` is an
                # AnnAssign at class-body level — covered below via the
                # dataclass-field walk, not here.
                attrs.add(target.id)
    # Dataclass lock fields: `x: threading.Lock = field(default_factory=
    # threading.Lock)` at class-body level.
    for node in cls.body:
        if isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            ann = dotted_name(node.annotation)
            if ann and ann.rsplit(".", 1)[-1] in (
                "Lock",
                "RLock",
                "Condition",
            ):
                attrs.add(node.target.id)
    return attrs


def _in_class_body(cls: ast.ClassDef, node: ast.AST) -> bool:
    return node in cls.body


_SYNC_FACTORIES = {
    "Event",
    "Semaphore",
    "BoundedSemaphore",
    "Barrier",
    "Queue",
    "SimpleQueue",
    "LifoQueue",
    "PriorityQueue",
}


def sync_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attributes holding internally-synchronized primitives
    (``self._stop = threading.Event()`` etc.) — their mutator methods
    (``clear``, ``put``…) are thread-safe by contract, so KV010 must
    not read them as bare shared-state mutation."""
    attrs: Set[str] = set()
    for node in ast.walk(cls):
        if not (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
        ):
            continue
        callee = dotted_name(node.value.func)
        if not callee or callee.rsplit(".", 1)[-1] not in _SYNC_FACTORIES:
            continue
        for target in node.targets:
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                attrs.add(target.attr)
    return attrs


def with_locks(node: ast.With) -> Set[str]:
    """Lock attr names acquired by ``with self.<lock>[, ...]:``."""
    locks: Set[str] = set()
    for item in node.items:
        expr = item.context_expr
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        ):
            locks.add(expr.attr)
    return locks
