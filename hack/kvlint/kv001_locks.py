"""KV001 — lock discipline for ``# guarded-by:`` annotated attributes.

The Go reference gets its lock discipline checked by ``go vet`` and race
builds; CPython threads get neither, so this rule enforces the
declared-guard convention statically:

    self._cost = 0  # guarded-by: _lock

declares that ``self._cost`` may only be read or written

* inside a ``with self._lock:`` block (any ``with``-able sync
  primitive: Lock, RLock, Condition), or
* in a method whose callers hold the lock — name ending ``_locked``,
  or a ``# kvlint: caller-locked`` comment on its ``def`` line.

``__init__`` is exempt (the object is not yet shared).  Nested
functions (closures) are analyzed with an EMPTY held-lock set: a
closure can outlive the ``with`` block that created it, so assuming it
inherits the lock would be unsound.

Scope limits (documented, deliberate): only ``self.<attr>`` accesses
inside the declaring class are checked — foreign-object accesses
(``other._data``) and module-level globals are out of scope, as is
aliasing (``d = self._data`` then mutating ``d`` outside the lock
defeats the rule; don't do that).
"""

from __future__ import annotations

import ast
from typing import List, Set

from hack.kvlint import guards as guards_mod
from hack.kvlint.base import Finding, SourceFile

RULE = "KV001"

# The annotation grammar (regexes, caller-locked detection, class-span
# walking) lives in hack/kvlint/guards.py, shared with KV009, KV010 and
# the raceguard manifest emitter so every consumer reads the comments
# identically.
_collect_guards = guards_mod.collect_guards
_is_caller_locked = guards_mod.is_caller_locked
_with_locks = guards_mod.with_locks


def check(source: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(source.tree):
        if isinstance(node, ast.ClassDef):
            findings.extend(_check_class(source, node))
    return findings


def _check_class(source: SourceFile, cls: ast.ClassDef) -> List[Finding]:
    guards = _collect_guards(source, cls)
    if not guards:
        return []
    findings: List[Finding] = []

    def visit(node: ast.AST, held: Set[str]) -> None:
        if isinstance(node, ast.ClassDef):
            return  # nested classes have their own guard sets
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                visit(item.context_expr, held)
                if item.optional_vars is not None:
                    visit(item.optional_vars, held)
            inner = held | _with_locks(node)
            for stmt in node.body:
                visit(stmt, inner)
            return
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            # Closures may escape the guarded region; never inherit.
            body = (
                node.body
                if isinstance(node.body, list)
                else [node.body]
            )
            for stmt in body:
                visit(stmt, set())
            return
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr in guards
        ):
            lock = guards[node.attr]
            if lock not in held and not source.suppressed(
                node.lineno, RULE
            ):
                findings.append(
                    Finding(
                        source.path,
                        node.lineno,
                        RULE,
                        f"'self.{node.attr}' is guarded by "
                        f"'self.{lock}' but accessed without holding "
                        "it (wrap in `with self."
                        f"{lock}:` or mark the method caller-locked)",
                    )
                )
            # fall through: subscripts/attrs hang off this node
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if item.name == "__init__" or _is_caller_locked(source, item):
            continue
        for stmt in item.body:
            visit(stmt, set())
    return findings
