"""KV002 — tracer safety in ``ops/`` and ``models/``.

Inside a traced function (``@jax.jit``-decorated, wrapped by
``jax.jit(fn)``, or a kernel handed to ``pl.pallas_call`` — possibly
through a ``functools.partial`` binding), Python control flow on traced
values is a trace-time error or, worse, a silent specialization:

* ``if``/``while``/``assert``/ternary on a value derived from a traced
  parameter (``TracerBoolConversionError`` at best)
* ``bool()``/``int()``/``float()``/``.item()``/``.tolist()`` on one
* host-side nondeterminism in the traced body: ``random.*``,
  ``np.random.*`` (jax.random is fine), ``time.*`` — baked in at trace
  time, silently frozen across calls

Taint model (single forward pass, intra-function): parameters are
tainted except jit ``static_argnums``/``static_argnames`` and
``functools.partial``-bound arguments; assignment propagates; shape
metadata (``.shape``/``.dtype``/``.ndim``/``.size``) and ``len()`` are
static and scrub taint.  Nested defs (scan/fori_loop bodies, pallas
inner closures) inherit the enclosing taint and add their own params.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from hack.kvlint.base import (
    Finding,
    SourceFile,
    dotted_name,
    param_names,
)

RULE = "KV002"

SCOPE_SEGMENTS = ("ops", "models")
STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "itemsize", "sharding"}
STATIC_CALLS = {"len", "isinstance", "type", "getattr", "hasattr"}
_CAST_CALLS = {"bool", "int", "float"}
_HOST_VALUE_METHODS = {"item", "tolist", "__bool__", "__float__"}
# module-attribute prefixes that are nondeterministic on the host
_NONDET_PREFIXES = (
    "random.",
    "np.random.",
    "numpy.random.",
    "time.",
)


def in_scope(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return any(seg in parts for seg in SCOPE_SEGMENTS)


def _ends_with(name: Optional[str], suffix: str) -> bool:
    return bool(name) and (name == suffix or name.endswith("." + suffix))


def _static_from_jit_call(
    call: ast.Call, params: Sequence[str]
) -> Set[str]:
    """static_argnums/static_argnames keywords -> static param names."""
    static: Set[str] = set()
    for kw in call.keywords:
        if kw.arg not in ("static_argnums", "static_argnames"):
            continue
        values = (
            kw.value.elts
            if isinstance(kw.value, (ast.Tuple, ast.List))
            else [kw.value]
        )
        for value in values:
            if isinstance(value, ast.Constant):
                if isinstance(value.value, str):
                    static.add(value.value)
                elif isinstance(value.value, int) and 0 <= value.value < len(
                    params
                ):
                    static.add(params[value.value])
    return static


def _partial_bound(
    call: ast.Call, params: Sequence[str]
) -> Tuple[Optional[ast.AST], Set[str]]:
    """For ``functools.partial(f, a, kw=...)``: (f node, bound names)."""
    if not call.args:
        return None, set()
    bound: Set[str] = set()
    for i, _ in enumerate(call.args[1:]):
        if i < len(params):
            bound.add(params[i])
    for kw in call.keywords:
        if kw.arg:
            bound.add(kw.arg)
    return call.args[0], bound


class _TracedCollector:
    """Find traced defs and their static parameter names."""

    def __init__(self, tree: ast.Module) -> None:
        self.defs: Dict[str, ast.AST] = {}
        self.assigns: Dict[str, ast.expr] = {}
        for node in ast.walk(tree):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                self.defs[node.name] = node
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                if isinstance(target, ast.Name):
                    self.assigns[target.id] = node.value
        # def node -> static param-name set
        self.traced: Dict[ast.AST, Set[str]] = {}
        self._collect(tree)

    def _mark(self, func: ast.AST, static: Set[str]) -> None:
        if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            existing = self.traced.get(func)
            self.traced[func] = (
                static if existing is None else existing & static
            )

    def _resolve(
        self,
        expr: ast.AST,
        extra_static: Set[str],
        _seen: Optional[Set[str]] = None,
    ) -> None:
        """Mark the def a jit/pallas_call argument refers to."""
        seen = _seen if _seen is not None else set()
        if isinstance(expr, ast.Name):
            if expr.id in seen:
                return  # self-referential assignment chain
            seen.add(expr.id)
            if expr.id in self.defs:
                self._mark(self.defs[expr.id], set(extra_static))
            elif expr.id in self.assigns:
                self._resolve(self.assigns[expr.id], extra_static, seen)
        elif isinstance(expr, ast.Call):
            func_name = dotted_name(expr.func)
            if _ends_with(func_name, "partial"):
                inner, bound = self._partial_target(expr)
                if inner is not None:
                    self._resolve(inner, extra_static | bound, seen)

    def _partial_target(
        self, call: ast.Call
    ) -> Tuple[Optional[ast.AST], Set[str]]:
        target = call.args[0] if call.args else None
        params: Sequence[str] = []
        if isinstance(target, ast.Name) and target.id in self.defs:
            params = param_names(self.defs[target.id].args)
        return _partial_bound(call, params)

    def _collect(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                params = param_names(node.args)
                for dec in node.decorator_list:
                    static = self._decorator_static(dec, params)
                    if static is not None:
                        self._mark(node, static)
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if _ends_with(name, "jit") or _ends_with(
                    name, "pallas_call"
                ):
                    if node.args:
                        extra: Set[str] = set()
                        if _ends_with(name, "jit"):
                            # Resolve the target's params first so
                            # positional static_argnums map to names
                            # (jax.jit(f, static_argnums=(0,))).
                            target = node.args[0]
                            params: List[str] = []
                            if (
                                isinstance(target, ast.Name)
                                and target.id in self.defs
                            ):
                                params = param_names(
                                    self.defs[target.id].args
                                )
                            extra = _static_from_jit_call(node, params)
                        self._resolve(node.args[0], extra)

    def _decorator_static(
        self, dec: ast.AST, params: Sequence[str]
    ) -> Optional[Set[str]]:
        """Static names if ``dec`` marks the function as jitted."""
        name = dotted_name(dec)
        if _ends_with(name, "jit"):
            return set()
        if isinstance(dec, ast.Call):
            func_name = dotted_name(dec.func)
            if _ends_with(func_name, "jit"):
                return _static_from_jit_call(dec, params)
            if _ends_with(func_name, "partial") and dec.args:
                inner = dotted_name(dec.args[0])
                if _ends_with(inner, "jit"):
                    return _static_from_jit_call(dec, params)
        return None


def _expr_tainted(expr: ast.AST, tainted: Set[str]) -> bool:
    """Does ``expr`` reference a tainted name (shape/len-scrubbed)?"""
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                continue  # scrub: static metadata of a traced value
            stack.append(node.value)
            continue
        if isinstance(node, ast.Call):
            func_name = dotted_name(node.func)
            if func_name in STATIC_CALLS:
                continue  # len(x) etc. are trace-time constants
            stack.extend(ast.iter_child_nodes(node))
            continue
        if isinstance(node, ast.Name):
            if node.id in tainted:
                return True
            continue
        stack.extend(ast.iter_child_nodes(node))
    return False


def _flag(
    findings: List[Finding],
    source: SourceFile,
    lineno: int,
    message: str,
) -> None:
    if not source.suppressed(lineno, RULE):
        findings.append(Finding(source.path, lineno, RULE, message))


def _check_traced_body(
    source: SourceFile,
    func: ast.AST,
    static: Set[str],
    findings: List[Finding],
    inherited: Optional[Set[str]] = None,
) -> None:
    tainted: Set[str] = set(inherited or set())
    tainted |= set(param_names(func.args)) - static

    def assign(target: ast.AST, is_tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if is_tainted:
                tainted.add(target.id)
            else:
                tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                assign(elt, is_tainted)
        elif isinstance(target, ast.Starred):
            assign(target.value, is_tainted)

    def check_call(node: ast.Call) -> None:
        func_name = dotted_name(node.func)
        if func_name:
            for prefix in _NONDET_PREFIXES:
                if func_name.startswith(prefix):
                    _flag(
                        findings,
                        source,
                        node.lineno,
                        f"host-side '{func_name}' inside a traced "
                        "function is frozen at trace time (use "
                        "jax.random / pass values in)",
                    )
                    return
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in _CAST_CALLS
            and any(_expr_tainted(a, tainted) for a in node.args)
        ):
            _flag(
                findings,
                source,
                node.lineno,
                f"'{node.func.id}()' on a traced value forces "
                "concretization inside jit",
            )
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _HOST_VALUE_METHODS
            and _expr_tainted(node.func.value, tainted)
        ):
            _flag(
                findings,
                source,
                node.lineno,
                f"'.{node.func.attr}()' on a traced value forces a "
                "device sync inside jit",
            )

    def visit(node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # scan/fori_loop/cond bodies: params traced, closure taint
            # inherited.
            _check_traced_body(
                source, node, set(), findings, inherited=tainted
            )
            return
        if isinstance(node, ast.Lambda):
            inner = set(tainted) | set(param_names(node.args))
            if isinstance(node.body, ast.IfExp) and _expr_tainted(
                node.body.test, inner
            ):
                _flag(
                    findings,
                    source,
                    node.lineno,
                    "conditional on a traced value (use jnp.where / "
                    "lax.cond)",
                )
            return
        if isinstance(node, (ast.If, ast.While)):
            if _expr_tainted(node.test, tainted):
                kind = "if" if isinstance(node, ast.If) else "while"
                _flag(
                    findings,
                    source,
                    node.lineno,
                    f"'{kind}' on a traced value (use jnp.where / "
                    "lax.cond / lax.while_loop)",
                )
        elif isinstance(node, ast.IfExp):
            if _expr_tainted(node.test, tainted):
                _flag(
                    findings,
                    source,
                    node.lineno,
                    "ternary on a traced value (use jnp.where)",
                )
        elif isinstance(node, ast.Assert):
            if _expr_tainted(node.test, tainted):
                _flag(
                    findings,
                    source,
                    node.lineno,
                    "assert on a traced value (use "
                    "checkify / debug.check)",
                )
        elif isinstance(node, ast.Call):
            check_call(node)
        elif isinstance(node, ast.Assign):
            is_tainted = _expr_tainted(node.value, tainted)
            visit_children(node.value)
            for target in node.targets:
                assign(target, is_tainted)
            return
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if node.value is not None:
                is_tainted = _expr_tainted(node.value, tainted)
                visit_children(node.value)
                if isinstance(node, ast.AugAssign):
                    is_tainted = is_tainted or _expr_tainted(
                        node.target, tainted
                    )
                assign(node.target, is_tainted)
            return
        visit_children(node)

    def visit_children(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            visit(child)

    for stmt in func.body:
        visit(stmt)


def check(source: SourceFile) -> List[Finding]:
    if not in_scope(source.path):
        return []
    findings: List[Finding] = []
    collector = _TracedCollector(source.tree)
    for func, static in collector.traced.items():
        _check_traced_body(source, func, static, findings)
    # de-dup (a def can be both decorated and partial-wrapped)
    seen: Set[Tuple[int, str]] = set()
    unique: List[Finding] = []
    for finding in findings:
        key = (finding.line, finding.message)
        if key not in seen:
            seen.add(key)
            unique.append(finding)
    return unique
