"""KV003 — canonical serialization in hashing/persistence paths.

The cross-fleet block-hash contract (PAPER.md: exact hash parity with
the reference indexer) and the durability formats of ``persistence/``
both require *deterministic* bytes: everything hashed or written to
disk must go through ``kvblock/cbor_canonical.py`` (RFC 8949 §4.2.1
core deterministic encoding).  A stray ``msgpack.packb`` or
``cbor2.dumps`` in those paths silently breaks hash parity (map order,
float forms, indefinite lengths); ``pickle`` additionally executes
arbitrary code on load, so it is banned everywhere.

* ``pickle``/``cPickle``/``dill``/``shelve``/``marshal``: flagged in
  every analyzed file (import or call).
* ``msgpack``/``cbor2``/``cbor``/``json`` **in canonical scopes**
  (``kvcache/``, ``persistence/``, ``offload/``, ``scheduler/``):
  flagged outside ``cbor_canonical.py``.  ``json`` is included because
  its output is not canonical (dict order, whitespace, float repr) —
  the HTTP/API layer is out of scope and may use it freely.

``kvevents/`` is deliberately NOT a canonical scope: the wire format IS
msgpack (vLLM's publisher owns that contract, events.py decodes it).
"""

from __future__ import annotations

import ast
from typing import List, Optional

from hack.kvlint.base import Finding, SourceFile, dotted_name

RULE = "KV003"

BANNED_EVERYWHERE = {"pickle", "cPickle", "dill", "shelve", "marshal"}
NONCANONICAL = {"msgpack", "cbor2", "cbor", "json"}
CANONICAL_SCOPE_SEGMENTS = (
    "kvcache",
    "persistence",
    "offload",
    "scheduler",
)
ALLOWED_BASENAMES = ("cbor_canonical.py",)


def _in_canonical_scope(path: str) -> bool:
    normalized = path.replace("\\", "/")
    if normalized.endswith(ALLOWED_BASENAMES):
        return False
    parts = normalized.split("/")
    return any(seg in parts for seg in CANONICAL_SCOPE_SEGMENTS)


def _root(module: Optional[str]) -> str:
    return (module or "").split(".", 1)[0]


def check(source: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    canonical = _in_canonical_scope(source.path)

    def flag(lineno: int, module: str, what: str) -> None:
        if source.suppressed(lineno, RULE):
            return
        if module in BANNED_EVERYWHERE:
            message = (
                f"'{what}': {module} is banned (non-deterministic "
                "and/or code-executing); use kvblock/cbor_canonical "
                "or an explicit format"
            )
        else:
            message = (
                f"'{what}': non-canonical serializer in a "
                "hashing/persistence path; hashed or journaled bytes "
                "must go through kvblock/cbor_canonical"
            )
        findings.append(Finding(source.path, lineno, RULE, message))

    def is_banned(module: str) -> bool:
        return module in BANNED_EVERYWHERE or (
            canonical and module in NONCANONICAL
        )

    for node in ast.walk(source.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                root = _root(alias.name)
                if is_banned(root):
                    flag(node.lineno, root, f"import {alias.name}")
        elif isinstance(node, ast.ImportFrom):
            root = _root(node.module)
            if node.level == 0 and is_banned(root):
                flag(node.lineno, root, f"from {node.module} import ...")
        elif isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if not name:
                continue
            root = _root(name)
            if "." in name and is_banned(root):
                flag(node.lineno, root, f"{name}(...)")
    return findings
