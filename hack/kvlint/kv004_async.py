"""KV004 — blocking calls inside ``async def``.

One blocking call inside a coroutine stalls the whole event loop —
every in-flight request, not just the offending one.  Flagged inside
``async def`` bodies (nested sync ``def``s are skipped: they may be
shipped to a thread pool via ``to_thread``/``run_in_executor``):

* ``time.sleep`` (use ``asyncio.sleep``)
* ``open()`` and ``os``-level file I/O
* synchronous sockets: ``socket.*`` constructors, ``.recv``/
  ``.recv_multipart``/``.sendall``/``.accept`` method calls
* ``subprocess.run/call/check_*`` (use ``asyncio.create_subprocess_*``)
* ``urllib.request.urlopen`` / ``requests.*``

Deliberately NOT name-matched: ``.join``/``.wait``/``.result`` —
``', '.join(...)`` and ``os.path.join`` are idiomatic and an AST
cannot tell a str from a Thread; a name-only match would make the
hard gate fire on legitimate code.  Blocking waits on futures inside
coroutines are left to review.

The repo's API surface is currently thread-based (stdlib http.server,
gRPC sync stubs), so this rule mostly protects *future* async code —
it exists so the first coroutine added to ``api/`` or ``kvevents/``
inherits the discipline from day one.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from hack.kvlint.base import Finding, SourceFile, dotted_name

RULE = "KV004"

_BLOCKING_DOTTED = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "socket.create_connection": "use asyncio streams",
    "socket.socket": "use asyncio streams",
    "urllib.request.urlopen": "use an async HTTP client",
    "subprocess.run": "use asyncio.create_subprocess_exec",
    "subprocess.call": "use asyncio.create_subprocess_exec",
    "subprocess.check_call": "use asyncio.create_subprocess_exec",
    "subprocess.check_output": "use asyncio.create_subprocess_exec",
    "os.system": "use asyncio.create_subprocess_shell",
}
_BLOCKING_ROOTS = {"requests": "use an async HTTP client"}
# Socket-specific names only: generic wait-ish names (join, wait,
# result) collide with str.join / os.path.join etc. — see module
# docstring.
_BLOCKING_METHODS = {
    "recv": "sync socket read",
    "recv_multipart": "sync socket read",
    "sendall": "sync socket write",
    "accept": "sync socket accept",
}
_BLOCKING_NAMES = {"open": "use a thread (asyncio.to_thread) for file I/O"}


def check(source: SourceFile) -> List[Finding]:
    findings: List[Finding] = []

    def flag(lineno: int, what: str, hint: str) -> None:
        if not source.suppressed(lineno, RULE):
            findings.append(
                Finding(
                    source.path,
                    lineno,
                    RULE,
                    f"blocking '{what}' inside async def ({hint})",
                )
            )

    def check_call(node: ast.Call, awaited: bool) -> None:
        if awaited:
            return
        name = dotted_name(node.func)
        if name:
            if name in _BLOCKING_DOTTED:
                flag(node.lineno, name, _BLOCKING_DOTTED[name])
                return
            root = name.split(".", 1)[0]
            if root in _BLOCKING_ROOTS and "." in name:
                flag(node.lineno, name, _BLOCKING_ROOTS[root])
                return
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in _BLOCKING_NAMES
        ):
            flag(node.lineno, node.func.id, _BLOCKING_NAMES[node.func.id])
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _BLOCKING_METHODS
        ):
            flag(
                node.lineno,
                f".{node.func.attr}(...)",
                _BLOCKING_METHODS[node.func.attr],
            )

    def visit(node: ast.AST, parent_await: Optional[ast.AST]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.Lambda)):
            return  # sync helper: may legitimately run in a thread
        if isinstance(node, ast.AsyncFunctionDef):
            return  # nested coroutine: the outer walk visits it itself
        if isinstance(node, ast.Await):
            visit(node.value, node.value)
            return
        if isinstance(node, ast.Call):
            check_call(node, awaited=node is parent_await)
        for child in ast.iter_child_nodes(node):
            visit(child, parent_await)

    for node in ast.walk(source.tree):
        if isinstance(node, ast.AsyncFunctionDef):
            for stmt in node.body:
                visit(stmt, None)
    return findings
