"""KV005 — bare excepts and silently-swallowed broad exceptions.

Event and worker loops must survive bad input — but surviving
*silently* turns every bug into a missing-data mystery (an index that
quietly stops updating is worse than one that crashes).  Flagged:

* ``except:`` (bare) — anywhere; it catches ``KeyboardInterrupt`` and
  ``SystemExit`` too, wedging shutdown.
* ``except Exception:`` / ``except BaseException:`` (alone or in a
  tuple) whose body only ``pass``-es / ``continue``-s / ``return``-s
  nothing — the error is swallowed with no log, no metric, no state.

Any other statement in the handler body (a logging call, a metric
increment, a fallback assignment, a ``raise``) counts as handling.
Narrow-exception swallows (``except queue.Full: pass``) are control
flow, not error hiding, and are not flagged.  ``__del__`` bodies are
exempt: logging during interpreter teardown can itself raise.
"""

from __future__ import annotations

import ast
from typing import List

from hack.kvlint.base import Finding, SourceFile

RULE = "KV005"

_BROAD = {"Exception", "BaseException"}


def _is_broad(type_node: ast.AST) -> bool:
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(elt) for elt in type_node.elts)
    return False


def _swallows(body: List[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Return) and (
            stmt.value is None
            or (
                isinstance(stmt.value, ast.Constant)
                and stmt.value.value is None
            )
        ):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(
            stmt.value, ast.Constant
        ):
            continue  # docstring / ellipsis
        return False
    return True


def check(source: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    # Map handlers to their enclosing function (for the __del__ carve-out).
    enclosing = {}
    for func in ast.walk(source.tree):
        if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for node in ast.walk(func):
                if isinstance(node, ast.ExceptHandler):
                    enclosing[node] = func.name  # innermost wins (walk order)

    for node in ast.walk(source.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if enclosing.get(node) == "__del__":
            continue
        if source.suppressed(node.lineno, RULE):
            continue
        if node.type is None:
            findings.append(
                Finding(
                    source.path,
                    node.lineno,
                    RULE,
                    "bare 'except:' catches KeyboardInterrupt/"
                    "SystemExit; catch Exception (and log) at most",
                )
            )
        elif _is_broad(node.type) and _swallows(node.body):
            findings.append(
                Finding(
                    source.path,
                    node.lineno,
                    RULE,
                    "broad except swallows the error silently; log "
                    "with context (or narrow the exception type)",
                )
            )
    return findings
