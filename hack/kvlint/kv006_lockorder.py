"""KV006 — whole-program lock-order / deadlock analysis.

Phase 2 consumer of the project model (model.py): builds the global
lock-acquisition graph — an edge ``A -> B`` means some code path
acquires lock ``B`` while holding lock ``A`` — from

* lexically nested ``with`` blocks inside one method,
* calls made while holding a lock, resolved through the model's call
  resolution (same-class calls, attr-typed cross-class calls widened
  over subclasses), propagated to a transitive may-acquire set per
  method.

Locks aggregate per *class attribute* (``LRUCache._lock`` is one node
no matter how many instances exist), so striped structures show
multi-instance nesting as a self-edge — the classic
"two shards locked in opposite orders by two threads" deadlock.

Reported:

* **cycles** in the graph (including declared edges): potential
  deadlocks — two threads can enter the cycle from different points;
* **contradictions**: an observed edge ``B -> A`` where the project
  declared ``# kvlint: lock-order: A < B``;
* **undeclared self-edges**: the same lock class acquired while an
  instance of it is already held, without a
  ``# kvlint: lock-order: L ascending`` declaration promising a
  canonical instance order.

Declared intent vocabulary (comments anywhere in the tree; the runtime
watchdog in ``utils/lockorder.py`` asserts the same declarations under
the concurrency storm tests):

    # kvlint: lock-order: Pool._lock < LRUCache._lock
    # kvlint: lock-order: LRUCache._lock ascending

Soundness gaps (deliberate, documented in docs/static-analysis.md):
calls on receivers whose type the model cannot infer contribute no
edges, and locks passed across objects as plain arguments are
invisible.  The rule over-approximates where it can (subclass
widening) and stays silent where it cannot.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from hack.kvlint.base import Finding
from hack.kvlint.model import ClassModel, LockRef, MethodModel, ProjectModel

RULE = "KV006"


class _Edge:
    __slots__ = ("src", "dst", "path", "line", "via")

    def __init__(
        self, src: str, dst: str, path: str, line: int, via: str
    ) -> None:
        self.src = src
        self.dst = dst
        self.path = path
        self.line = line
        self.via = via


def _may_acquire(model: ProjectModel) -> Dict[Tuple[str, str], Set[str]]:
    """(class, method) -> lock names the method may acquire,
    transitively through resolvable calls (fixed point)."""
    acquire: Dict[Tuple[str, str], Set[str]] = {}
    for cls in model.classes.values():
        for method in cls.methods.values():
            acquire[(cls.name, method.name)] = {
                ref.name for ref, _ in method.acquires
            }
    changed = True
    while changed:
        changed = False
        for cls in model.classes.values():
            for method in cls.methods.values():
                key = (cls.name, method.name)
                current = acquire[key]
                for call in method.calls:
                    for target_cls, target in model.resolve_call(
                        cls, call
                    ):
                        extra = acquire.get(
                            (target_cls.name, target.name)
                        )
                        if extra and not extra.issubset(current):
                            current |= extra
                            changed = True
    return acquire


def _build_edges(model: ProjectModel) -> List[_Edge]:
    acquire = _may_acquire(model)
    edges: List[_Edge] = []
    for cls in model.classes.values():
        for method in cls.methods.values():
            for outer, inner, line in method.nested:
                edges.append(
                    _Edge(
                        outer.name,
                        inner.name,
                        method.path,
                        line,
                        f"{cls.name}.{method.name}",
                    )
                )
            for call in method.calls:
                if not call.held:
                    continue
                for target_cls, target in model.resolve_call(cls, call):
                    inner_locks = acquire.get(
                        (target_cls.name, target.name), set()
                    )
                    for held in call.held:
                        for inner_name in inner_locks:
                            edges.append(
                                _Edge(
                                    held.name,
                                    inner_name,
                                    call.path,
                                    call.line,
                                    f"{cls.name}.{method.name} -> "
                                    f"{target_cls.name}.{target.name}",
                                )
                            )
    return edges


def _declared(model: ProjectModel):
    ordered: Dict[Tuple[str, str], Tuple[str, int]] = {}
    ascending: Set[str] = set()
    for decl in model.order_decls:
        if decl.ascending:
            ascending.add(decl.first)
        elif decl.second:
            ordered.setdefault(
                (decl.first, decl.second), (decl.path, decl.line)
            )
    return ordered, ascending


def _suppressed(model: ProjectModel, path: str, line: int) -> bool:
    source = model.by_path.get(path)
    return bool(source and source.suppressed(line, RULE))


def _find_cycle(
    start: str, adjacency: Dict[str, Set[str]]
) -> Optional[List[str]]:
    """A simple cycle through ``start``, as a node list, or None."""
    stack: List[Tuple[str, List[str]]] = [(start, [start])]
    seen: Set[str] = set()
    while stack:
        node, trail = stack.pop()
        for nxt in sorted(adjacency.get(node, ())):
            if nxt == start:
                return trail
            if nxt in seen:
                continue
            seen.add(nxt)
            stack.append((nxt, trail + [nxt]))
    return None


def check_project(model: ProjectModel) -> List[Finding]:
    findings: List[Finding] = []
    edges = _build_edges(model)
    ordered, ascending = _declared(model)

    # 1. Observed edges that contradict a declaration.
    contradicted: Set[Tuple[str, str]] = set()
    for edge in edges:
        decl = ordered.get((edge.dst, edge.src))
        if decl is None or edge.src == edge.dst:
            continue
        if (edge.dst, edge.src) in contradicted:
            continue
        contradicted.add((edge.dst, edge.src))
        if _suppressed(model, edge.path, edge.line):
            continue
        findings.append(
            Finding(
                edge.path,
                edge.line,
                RULE,
                f"'{edge.dst}' is acquired while holding "
                f"'{edge.src}' (via {edge.via}), contradicting the "
                f"declared lock order '{edge.dst} < {edge.src}' "
                f"({decl[0]}:{decl[1]})",
            )
        )

    # 2. Self-edges: multi-instance acquisition of one lock class.
    reported_self: Set[str] = set()
    for edge in edges:
        if edge.src != edge.dst:
            continue
        if edge.src in ascending or edge.src in reported_self:
            continue
        reported_self.add(edge.src)
        if _suppressed(model, edge.path, edge.line):
            continue
        findings.append(
            Finding(
                edge.path,
                edge.line,
                RULE,
                f"'{edge.src}' is acquired while another instance of "
                f"it is already held (via {edge.via}); two threads "
                "taking instances in opposite orders deadlock — "
                "declare a canonical instance order with "
                f"'# kvlint: lock-order: {edge.src} ascending' and "
                "acquire in it, or restructure to avoid the nesting",
            )
        )

    # 3. Cycles over observed + declared edges (self-edges handled
    # above; contradicted pairs already reported).
    adjacency: Dict[str, Set[str]] = {}
    provenance: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for edge in edges:
        if edge.src == edge.dst:
            continue
        pair = (edge.src, edge.dst)
        if (edge.dst, edge.src) in contradicted or pair in contradicted:
            continue
        adjacency.setdefault(edge.src, set()).add(edge.dst)
        provenance.setdefault(pair, (edge.path, edge.line, edge.via))
    for (first, second), (path, line) in ordered.items():
        adjacency.setdefault(first, set()).add(second)
        provenance.setdefault(
            (first, second), (path, line, "declared order")
        )

    reported_cycles: Set[frozenset] = set()
    for node in sorted(adjacency):
        cycle = _find_cycle(node, adjacency)
        if cycle is None:
            continue
        key = frozenset(cycle)
        if key in reported_cycles:
            continue
        reported_cycles.add(key)
        # Anchor the finding at the first OBSERVED edge of the cycle
        # (a purely declared cycle anchors at a declaration site).
        anchor: Optional[Tuple[str, int]] = None
        for i, src in enumerate(cycle):
            dst = cycle[(i + 1) % len(cycle)]
            info = provenance.get((src, dst))
            if info is None:
                continue
            if info[2] != "declared order" or anchor is None:
                anchor = (info[0], info[1])
                if info[2] != "declared order":
                    break
        if anchor is None:  # pragma: no cover - provenance is complete
            continue
        if _suppressed(model, anchor[0], anchor[1]):
            continue
        chain = " -> ".join(cycle + [cycle[0]])
        findings.append(
            Finding(
                anchor[0],
                anchor[1],
                RULE,
                f"lock-order cycle (potential deadlock): {chain}; "
                "make every path acquire these locks in one global "
                "order and declare it with "
                "'# kvlint: lock-order: A < B'",
            )
        )
    return findings
