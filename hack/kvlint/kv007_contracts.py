"""KV007 — contract-surface drift between code and docs.

The operator-facing surface — env knobs, Prometheus metric names, the
trace stage vocabulary — is a contract: a knob that exists in code but
not in docs/configuration.md is unusable, a documented knob that no
code reads is a lie, a metric registered twice crashes the collector
registry at import, and a stage name outside the documented
``kvtpu_stage_latency_seconds{stage=...}`` vocabulary splinters the
dashboard/flight-recorder correlation PR 3 built.

Checks (all consume the project model; doc-dependent ones are skipped
when no ``docs/configuration.md`` is found above the analyzed paths):

* env var read in code but documented nowhere (exemptions: the
  Kubernetes service-account environment, which the platform owns);
* documented knob that nothing reads — code in the analyzed set, the
  native C++ sources, or repo-root scripts (**whole-program runs
  only**: a subtree run can't see the readers elsewhere);
* metric name registered more than once;
* metric registered but missing from the docs/observability.md
  inventory (``*`` wildcard rows cover families);
* documented metric that is never registered (whole-program only);
* span/stage name used in code but absent from docs/observability.md.

Suppression: ``# kvlint: disable=KV007`` on the flagged code line.
Doc-side findings anchor in the markdown file and cannot be
suppressed — fix the doc.
"""

from __future__ import annotations

from typing import Dict, List, Set, Tuple

from hack.kvlint.base import Finding
from hack.kvlint.model import ProjectModel

RULE = "KV007"

# The platform owns these; they are not project configuration surface.
EXEMPT_ENV = {
    "KUBERNETES_SERVICE_HOST",
    "KUBERNETES_SERVICE_PORT",
}

# Metric names carry this namespace prefix in code; the docs inventory
# omits it (docs/observability.md "Metrics inventory").
METRIC_NAMESPACE = "kvtpu_"


def _suppressed(model: ProjectModel, path: str, line: int) -> bool:
    source = model.by_path.get(path)
    return bool(source and source.suppressed(line, RULE))


def check_project(model: ProjectModel) -> List[Finding]:
    findings: List[Finding] = []
    docs = model.docs

    # -- metric uniqueness (needs no docs) ------------------------------
    seen: Dict[str, Tuple[str, int]] = {}
    for reg in model.metric_registrations:
        prior = seen.get(reg.name)
        if prior is None:
            seen[reg.name] = (reg.path, reg.line)
            continue
        if _suppressed(model, reg.path, reg.line):
            continue
        findings.append(
            Finding(
                reg.path,
                reg.line,
                RULE,
                f"metric '{reg.name}' is registered more than once "
                f"(first at {prior[0]}:{prior[1]}); a duplicate "
                "registration raises at import on a shared registry",
            )
        )

    if docs is None:
        return findings

    # -- env knobs ------------------------------------------------------
    reported_env: Set[Tuple[str, str]] = set()
    for read in model.env_reads:
        if read.name in EXEMPT_ENV or read.name in docs.knobs:
            continue
        key = (read.path, read.name)
        if key in reported_env:
            continue
        reported_env.add(key)
        if _suppressed(model, read.path, read.line):
            continue
        findings.append(
            Finding(
                read.path,
                read.line,
                RULE,
                f"env knob '{read.name}' is read here but not "
                "documented in docs/configuration.md (add a table "
                "row, or '# kvlint: disable=KV007' for a deliberately "
                "internal switch)",
            )
        )

    if model.whole_program:
        read_names = {r.name for r in model.env_reads}
        read_names |= docs.external_env_reads
        for knob, (doc_path, doc_line) in sorted(docs.knobs.items()):
            if knob in read_names:
                continue
            findings.append(
                Finding(
                    doc_path,
                    doc_line,
                    RULE,
                    f"documented env knob '{knob}' is read nowhere "
                    "(package code, native sources, or repo scripts) "
                    "— stale docs or a knob that silently stopped "
                    "working",
                )
            )

    # -- metrics vs inventory -------------------------------------------
    registered_short: Set[str] = set()
    for reg in model.metric_registrations:
        short = reg.name
        if short.startswith(METRIC_NAMESPACE):
            short = short[len(METRIC_NAMESPACE):]
        registered_short.add(short)
        if reg.kind == "Counter":
            # prometheus_client appends `_total` at exposition; the
            # docs inventory may show either form.
            registered_short.add(short + "_total")
        if short in docs.metrics:
            continue
        if reg.kind == "Counter" and short + "_total" in docs.metrics:
            continue
        if any(short.startswith(w) for w in docs.metric_wildcards):
            continue
        if _suppressed(model, reg.path, reg.line):
            continue
        findings.append(
            Finding(
                reg.path,
                reg.line,
                RULE,
                f"metric '{reg.name}' is not documented in the "
                "docs/observability.md metrics inventory",
            )
        )
    if model.whole_program:
        for short, (doc_path, doc_line) in sorted(docs.metrics.items()):
            if short in registered_short:
                continue
            # Counters register without the `_total` suffix the
            # exposition (and therefore the docs) shows.
            if short.endswith("_total") and short[:-6] in registered_short:
                continue
            findings.append(
                Finding(
                    doc_path,
                    doc_line,
                    RULE,
                    f"documented metric '{short}' is never registered "
                    "in code",
                )
            )

    # -- stage vocabulary -----------------------------------------------
    reported_stages: Set[str] = set()
    for use in model.stage_uses:
        if use.name in docs.stages or use.name in reported_stages:
            continue
        reported_stages.add(use.name)
        if _suppressed(model, use.path, use.line):
            continue
        findings.append(
            Finding(
                use.path,
                use.line,
                RULE,
                f"trace stage '{use.name}' is not part of the "
                "documented stage vocabulary (docs/observability.md); "
                "dashboards keyed on kvtpu_stage_latency_seconds"
                "{stage=...} won't correlate it",
            )
        )
    return findings
