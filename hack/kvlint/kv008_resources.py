"""KV008 — shutdown/resource discipline.

A worker thread with no reachable stop path outlives its owner and
keeps a dead subsystem's queue draining into nothing; an unclosed ZMQ
socket pins its context forever (``Context.term`` blocks).  This rule
checks that every thread / executor / socket a class creates has a
reachable ``close``/``stop``/``shutdown`` path:

* a resource **stored on self** (direct assignment, a local later
  assigned to a ``self.<attr>``, or a local appended to a
  ``self.<list>``) requires a *closer method* — named
  ``close``/``stop``/``shutdown``/``terminate``/``__exit__``/
  ``__del__``, or reachable from one through same-class calls — that
  references the attribute;
* a resource kept as a **local** must be cleaned up in the creating
  method itself: a ``join``/``close``/``shutdown``/``stop``/
  ``terminate`` call *on that local* (an unrelated ``", ".join(...)``
  exempts nothing), creation inside a ``with`` item, or — threads and
  executors only — the stop-event pattern (the method also creates a
  ``threading.Event`` whose wait bounds the worker loop — the
  ``start_*`` factory shape);
* a local that is **returned** transfers ownership to the caller and
  is exempt (the ``_open_socket`` factory shape — the caller's
  ``finally`` closes it; a leak there is the caller's finding).

Daemon-ness is deliberately not an excuse: a daemon thread dies with
the process, but its subsystem can be shut down and rebuilt many times
per process (tests do), and each leaked worker keeps consuming.

Suppression: ``# kvlint: disable=KV008`` on the creating line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from hack.kvlint.base import Finding, SourceFile, dotted_name
from hack.kvlint.model import _resource_kind

RULE = "KV008"

CLOSER_NAMES = {
    "close",
    "stop",
    "shutdown",
    "terminate",
    "disconnect",
    "__exit__",
    "__del__",
}

_CLEANUP_CALLS = {
    "join",
    "close",
    "shutdown",
    "stop",
    "terminate",
    "disconnect",
}


def check(source: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(source.tree):
        if isinstance(node, ast.ClassDef):
            findings.extend(_check_class(source, node))
    return findings


def _check_class(
    source: SourceFile, cls: ast.ClassDef
) -> List[Finding]:
    methods = {
        item.name: item
        for item in cls.body
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    closer_reachable = _closer_reachable(methods)
    closed_attrs = _attrs_touched_by(
        methods, closer_reachable
    )

    findings: List[Finding] = []
    for name, func in methods.items():
        if name in closer_reachable:
            continue
        findings.extend(
            _check_method(source, cls, func, closed_attrs)
        )
    return findings


def _closer_reachable(methods: Dict[str, ast.AST]) -> Set[str]:
    """Closer methods plus everything they call on self, transitively."""
    reachable = {name for name in methods if name in CLOSER_NAMES}
    frontier = list(reachable)
    while frontier:
        current = frontier.pop()
        func = methods.get(current)
        if func is None:
            continue
        for node in ast.walk(func):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"
                and node.func.attr in methods
                and node.func.attr not in reachable
            ):
                reachable.add(node.func.attr)
                frontier.append(node.func.attr)
    return reachable


def _attrs_touched_by(
    methods: Dict[str, ast.AST], names: Set[str]
) -> Set[str]:
    attrs: Set[str] = set()
    for name in names:
        func = methods.get(name)
        if func is None:
            continue
        for node in ast.walk(func):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                attrs.add(node.attr)
    return attrs


def _check_method(
    source: SourceFile,
    cls: ast.ClassDef,
    func: ast.AST,
    closed_attrs: Set[str],
) -> List[Finding]:
    findings: List[Finding] = []
    with_items: Set[int] = set()  # id() of context-managed Call nodes
    for node in ast.walk(func):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                with_items.add(id(item.context_expr))

    # Locals assigned from resource constructors, and where they
    # escape to: a self-list append (`t = Thread();
    # self._threads.append(t)`), a plain self-attr store
    # (`sock = socket(); self._sock = sock`), or a `return` (ownership
    # transfers to the caller — its cleanup, its finding).
    local_resources: Dict[str, ast.Call] = {}
    appended_to: Dict[str, str] = {}  # local name -> self attr
    stored_as: Dict[str, str] = {}  # local name -> self attr
    returned: Set[str] = set()
    cleaned_locals = _cleaned_local_names(func)
    makes_stop_event = _creates_event(func)

    for node in ast.walk(func):
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        ):
            kind = _resource_kind(node.value)
            if kind is not None:
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        local_resources[target.id] = node.value
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Name
        ):
            for target in node.targets:
                if (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    stored_as[node.value.id] = target.attr
        if isinstance(node, ast.Return) and isinstance(
            node.value, ast.Name
        ):
            returned.add(node.value.id)
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("append", "add")
            and isinstance(node.func.value, ast.Attribute)
            and isinstance(node.func.value.value, ast.Name)
            and node.func.value.value.id == "self"
            and node.args
            and isinstance(node.args[0], ast.Name)
        ):
            appended_to[node.args[0].id] = node.func.value.attr

    for node in ast.walk(func):
        if isinstance(node, ast.ClassDef):
            continue
        # self.<attr> = <resource>()
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        ):
            kind = _resource_kind(node.value)
            if kind is None:
                continue
            for target in node.targets:
                if (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    if target.attr in closed_attrs:
                        continue
                    if source.suppressed(node.lineno, RULE):
                        continue
                    findings.append(
                        _leak(source, node.lineno, cls, kind, target.attr)
                    )

    # Locals: returned -> the caller owns it; stored on / appended to
    # self -> that attr needs a closer; purely local -> the method
    # itself must clean up.
    for local, call in local_resources.items():
        if id(call) in with_items or local in returned:
            continue
        kind = _resource_kind(call)
        attr = appended_to.get(local) or stored_as.get(local)
        if attr is not None:
            if attr in closed_attrs:
                continue
            if source.suppressed(call.lineno, RULE):
                continue
            findings.append(
                _leak(source, call.lineno, cls, kind, attr)
            )
        else:
            if local in cleaned_locals:
                continue
            if kind in ("thread", "executor") and makes_stop_event:
                continue
            if source.suppressed(call.lineno, RULE):
                continue
            findings.append(
                Finding(
                    source.path,
                    call.lineno,
                    RULE,
                    f"{kind} created in '{cls.name}."
                    f"{func.name}' has no reachable stop path: the "
                    "method neither joins/closes it, manages it with "
                    "'with', nor creates a stop Event for its loop",
                )
            )
    return findings


def _leak(
    source: SourceFile,
    lineno: int,
    cls: ast.ClassDef,
    kind: Optional[str],
    attr: str,
) -> Finding:
    return Finding(
        source.path,
        lineno,
        RULE,
        f"{kind} stored on 'self.{attr}' has no reachable "
        f"close/stop/shutdown path: no closer method of "
        f"'{cls.name}' ({', '.join(sorted(CLOSER_NAMES))}) "
        "references it",
    )


def _cleaned_local_names(func: ast.AST) -> Set[str]:
    """Local names that receive a cleanup call (``t.join()``,
    ``sock.close()``).  Receiver-checked on purpose: a bare "does any
    join/close appear" test lets ``", ".join(parts)`` mask a leaked
    thread — the same name-matching false-match class KV004
    deliberately avoids."""
    names: Set[str] = set()
    for node in ast.walk(func):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _CLEANUP_CALLS
            and isinstance(node.func.value, ast.Name)
        ):
            names.add(node.func.value.id)
    return names


def _creates_event(func: ast.AST) -> bool:
    for node in ast.walk(func):
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func)
            if callee and callee.rsplit(".", 1)[-1] == "Event":
                return True
    return False
