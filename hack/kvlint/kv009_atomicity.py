"""KV009 — check-then-act atomicity for guarded attributes.

KV001 proves every guarded access holds the declared lock; it cannot
see that a *decision* made under one acquisition is *acted on* under a
different one:

    with self._lock:
        exists = key in self._data     # read
    ...
    with self._lock:
        self._data[key] = value        # write — stale decision!

Between the two ``with`` blocks any other thread may mutate ``_data``,
so the write acts on a stale read — the classic lost-update /
double-insert shape, and exactly the race class the GIL-escape plan
(ROADMAP item 2) stops serializing.  This rule flags a guarded
attribute that is read under one acquisition of its lock and written
under a *later, separate* acquisition of the same lock in the same
function.

Deliberate over-approximation (documented): "feeds" is approximated by
program order — any read-then-later-write pair across separate
acquisitions counts, without proving data flow.  Benign pairs are
declared with ``# kvlint: atomic-ok`` on the write line (or the line
above), which — unlike a bare disable — asserts the author *checked*
the interleaving.  ``__init__`` and caller-locked methods are exempt
(one acquisition spans the whole call by contract).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Set, Tuple

from hack.kvlint import guards
from hack.kvlint.base import Finding, SourceFile

RULE = "KV009"

_MUTATORS = {
    "append",
    "add",
    "extend",
    "insert",
    "update",
    "setdefault",
    "pop",
    "popitem",
    "remove",
    "discard",
    "clear",
    "appendleft",
    "popleft",
}


@dataclass
class _Acquisition:
    """One lexical ``with self.<lock>:`` entry (not already held)."""

    lock: str
    line: int
    reads: Dict[str, int] = field(default_factory=dict)  # attr -> line
    writes: Dict[str, int] = field(default_factory=dict)


def check(source: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(source.tree):
        if isinstance(node, ast.ClassDef):
            findings.extend(_check_class(source, node))
    return findings


def _check_class(source: SourceFile, cls: ast.ClassDef) -> List[Finding]:
    guarded = guards.collect_guards(source, cls)
    if not guarded:
        return []
    findings: List[Finding] = []
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if item.name == "__init__" or guards.is_caller_locked(
            source, item
        ):
            continue
        findings.extend(_check_function(source, guarded, item))
    return findings


def _check_function(
    source: SourceFile,
    guarded: Dict[str, str],
    func: ast.AST,
) -> List[Finding]:
    acquisitions: List[_Acquisition] = []
    nested_funcs: List[ast.AST] = []

    def record_access(
        node: ast.Attribute, held: Dict[str, _Acquisition], write: bool
    ) -> None:
        attr = node.attr
        lock = guarded.get(attr)
        if lock is None:
            return
        acq = held.get(lock)
        if acq is None:
            return  # unguarded access is KV001's finding, not ours
        book = acq.writes if write else acq.reads
        book.setdefault(attr, node.lineno)

    def visit(node: ast.AST, held: Dict[str, _Acquisition]) -> None:
        if isinstance(node, ast.ClassDef):
            return
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            # A closure runs at an unknowable time relative to the
            # enclosing acquisitions; analyze it as its own scope.
            nested_funcs.append(node)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                visit(item.context_expr, held)
            inner = dict(held)
            for lock in sorted(
                guards.with_locks(node) & set(guarded.values())
            ):
                if lock not in inner:  # re-entry is the same acquisition
                    acq = _Acquisition(lock, node.lineno)
                    acquisitions.append(acq)
                    inner[lock] = acq
            for stmt in node.body:
                visit(stmt, inner)
            return
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            record_access(node, held, write)
        if isinstance(node, ast.AugAssign):
            target = _self_attr_of(node.target)
            if target is not None:
                record_access(target, held, True)
        if isinstance(node, (ast.Subscript, ast.Call)):
            target = _mutated_attr(node)
            if target is not None:
                record_access(target, held, True)
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    body = func.body if isinstance(func.body, list) else [func.body]
    for stmt in body:
        visit(stmt, {})

    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    by_lock: Dict[str, List[_Acquisition]] = {}
    for acq in acquisitions:
        by_lock.setdefault(acq.lock, []).append(acq)
    for lock, acqs in by_lock.items():
        for i, earlier in enumerate(acqs):
            for later in acqs[i + 1:]:
                for attr, read_line in sorted(earlier.reads.items()):
                    write_line = later.writes.get(attr)
                    if write_line is None:
                        continue
                    if (attr, write_line) in seen:
                        continue
                    seen.add((attr, write_line))
                    if _atomic_ok(source, write_line):
                        continue
                    if source.suppressed(write_line, RULE):
                        continue
                    findings.append(
                        Finding(
                            source.path,
                            write_line,
                            RULE,
                            f"check-then-act: 'self.{attr}' read "
                            f"under 'with self.{lock}:' (line "
                            f"{read_line}) feeds this write under a "
                            "separate acquisition — merge into one "
                            "critical section or mark `# kvlint: "
                            "atomic-ok`",
                        )
                    )
    for nested in nested_funcs:
        findings.extend(_check_function(source, guarded, nested))
    return findings


def _self_attr_of(node: ast.AST) -> ast.Attribute | None:
    """``self.x`` or ``self.x[...]`` -> the Attribute node."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node
    return None


def _mutated_attr(node: ast.AST) -> ast.Attribute | None:
    """Attribute mutated through a subscript store/del or a known
    mutator call (``self.x[k] = v``, ``self.x.append(v)``)."""
    if isinstance(node, ast.Subscript) and isinstance(
        node.ctx, (ast.Store, ast.Del)
    ):
        return _self_attr_of(node)
    if isinstance(node, ast.Call) and isinstance(
        node.func, ast.Attribute
    ):
        if node.func.attr in _MUTATORS:
            return _self_attr_of(node.func.value)
    return None


def _atomic_ok(source: SourceFile, lineno: int) -> bool:
    for line in (lineno, lineno - 1):
        comment = source.comment_on(line)
        if comment and guards.ATOMIC_OK_MARK in comment:
            return True
    return False
