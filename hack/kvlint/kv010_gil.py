"""KV010 — GIL-dependence must be declared, not implied.

ROADMAP item 2 commits the scoring plane to escaping the GIL; the day
it does, every mutation that today survives only because the
interpreter serializes bytecodes becomes a real data race.  The
codebase's deliberate lock-free idioms (PR 4's plain-int shard version
bumps, single-reference snapshot swaps) were documented in prose only —
invisible to tooling and to the migration.

This rule makes the dependence explicit: on any class that declares a
lock, a mutation of a *shared* attribute (referenced by more than one
method) performed outside every ``with self.<lock>:`` block and not
covered by a ``# guarded-by:`` declaration must carry

    self._versions[shard] += 1  # gil-atomic: lone-writer counter

on the mutation line or the line above.  The annotation does double
duty: it asserts the author decided the site is GIL-safe, and it feeds
the machine-readable **GIL-dependence inventory**
(``python -m hack.kvlint --emit-gil-inventory``) that is item 2's
migration worklist — each site must become atomic/locked/CAS when the
GIL goes.

Scope (documented, deliberate): mutations are ``self.attr = ...``,
``self.attr op= ...``, ``self.attr[...] = ...``, ``del`` forms and
known container-mutator calls; ``__init__``/``__post_init__`` and
caller-locked methods are exempt; classes with no locks at all are out
of scope (single-threaded by construction until someone adds a lock —
at which point every pre-existing bare mutation surfaces, which is the
desired ratchet).
"""

from __future__ import annotations

import ast
import json
from typing import Dict, List, Optional, Sequence, Set

from hack.kvlint import guards
from hack.kvlint.base import Finding, SourceFile

RULE = "KV010"

_EXEMPT_METHODS = {"__init__", "__post_init__", "__new__"}

_MUTATORS = {
    "append",
    "add",
    "extend",
    "insert",
    "update",
    "setdefault",
    "pop",
    "popitem",
    "remove",
    "discard",
    "clear",
    "appendleft",
    "popleft",
}


def check(source: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(source.tree):
        if isinstance(node, ast.ClassDef):
            findings.extend(_check_class(source, node))
    return findings


def _attr_refs_by_method(cls: ast.ClassDef) -> Dict[str, Set[str]]:
    refs: Dict[str, Set[str]] = {}
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        names: Set[str] = set()
        for node in ast.walk(item):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                names.add(node.attr)
        refs[item.name] = names
    return refs


def _check_class(source: SourceFile, cls: ast.ClassDef) -> List[Finding]:
    locks = guards.lock_attrs(cls)
    guarded = guards.collect_guards(source, cls)
    locks |= set(guarded.values())
    if not locks:
        return []
    # Internally-synchronized primitives (Event, Queue, …): their
    # mutators are thread-safe by contract, same standing as locks.
    locks |= guards.sync_attrs(cls)
    refs = _attr_refs_by_method(cls)
    findings: List[Finding] = []
    seen: Set[tuple] = set()  # (attr, line): AugAssign targets match twice
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if item.name in _EXEMPT_METHODS:
            continue
        if guards.is_caller_locked(source, item):
            continue

        def shared(attr: str, method: str = item.name) -> bool:
            # __init__ referencing the attr does not make it shared:
            # construction precedes publication (happens-before), so
            # sharing requires a SECOND post-construction method.
            return any(
                attr in names
                for name, names in refs.items()
                if name != method and name not in _EXEMPT_METHODS
            )

        def flag(node: ast.Attribute) -> None:
            attr = node.attr
            if attr in guarded or attr in locks:
                return
            if not shared(attr):
                return
            if (attr, node.lineno) in seen:
                return
            seen.add((attr, node.lineno))
            if _gil_atomic_why(source, node.lineno) is not None:
                return
            if source.suppressed(node.lineno, RULE):
                return
            findings.append(
                Finding(
                    source.path,
                    node.lineno,
                    RULE,
                    f"unguarded write to shared 'self.{attr}' on a "
                    "lock-owning class relies on the GIL — guard it, "
                    "declare `# guarded-by:`, or annotate "
                    "`# gil-atomic: <why>` to enter the "
                    "GIL-dependence inventory",
                )
            )

        def visit(node: ast.AST, held: bool) -> None:
            if isinstance(node, ast.ClassDef):
                return
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                # Same soundness rule as KV001/KV009: a closure can
                # escape its `with` block, so it never inherits.
                body = (
                    node.body
                    if isinstance(node.body, list)
                    else [node.body]
                )
                for stmt in body:
                    visit(stmt, False)
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for with_item in node.items:
                    visit(with_item.context_expr, held)
                inner = held or bool(guards.with_locks(node) & locks)
                for stmt in node.body:
                    visit(stmt, inner)
                return
            if not held:
                target = _mutation_target(node)
                if target is not None:
                    flag(target)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in item.body:
            visit(stmt, False)
    return findings


def _self_attr_of(node: ast.AST) -> Optional[ast.Attribute]:
    if isinstance(node, ast.Subscript):
        node = node.value
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node
    return None


def _mutation_target(node: ast.AST) -> Optional[ast.Attribute]:
    if isinstance(node, (ast.Attribute, ast.Subscript)) and isinstance(
        node.ctx, (ast.Store, ast.Del)
    ):
        return _self_attr_of(node)
    if isinstance(node, ast.AugAssign):
        return _self_attr_of(node.target)
    if isinstance(node, ast.Call) and isinstance(
        node.func, ast.Attribute
    ):
        if node.func.attr in _MUTATORS:
            return _self_attr_of(node.func.value)
    return None


def _gil_atomic_why(source: SourceFile, lineno: int) -> Optional[str]:
    for line in (lineno, lineno - 1):
        comment = source.comment_on(line)
        if comment:
            match = guards.GIL_ATOMIC_RE.search(comment)
            if match:
                return match.group(1)
    return None


def _mutations_by_line(source: SourceFile) -> Dict[int, str]:
    """Line -> mutated self attr, every line the *statement* spans, so
    an annotation on the closing paren of a multi-line assignment still
    resolves its attribute."""
    mut_at: Dict[int, str] = {}

    def record(stmt: ast.stmt, target: Optional[ast.Attribute]) -> None:
        if target is None:
            return
        end = getattr(stmt, "end_lineno", None) or stmt.lineno
        for lineno in range(stmt.lineno, end + 1):
            mut_at.setdefault(lineno, target.attr)

    for node in ast.walk(source.tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                record(node, _self_attr_of(tgt))
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            record(node, _self_attr_of(node.target))
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                record(node, _self_attr_of(tgt))
        elif isinstance(node, ast.Expr):
            record(node, _mutation_target(node.value))
    return mut_at


# -- GIL-dependence inventory -------------------------------------------


def collect_inventory(
    sources: Sequence[SourceFile],
) -> List[Dict[str, object]]:
    """Every ``# gil-atomic:`` site in the analyzed set — the ROADMAP
    item-2 migration worklist, one entry per annotated line."""
    sites: List[Dict[str, object]] = []
    for source in sources:
        class_at: Dict[int, str] = {}
        for node in ast.walk(source.tree):
            if isinstance(node, ast.ClassDef):
                for lineno in guards.class_span(node):
                    class_at.setdefault(lineno, node.name)
        mut_at = _mutations_by_line(source)
        for lineno, (_, comment) in sorted(source.comments.items()):
            match = guards.GIL_ATOMIC_RE.search(comment)
            if not match:
                continue
            code = source.code_before_comment(lineno).strip()
            if not code and lineno < len(source.lines):
                # Annotation on its own line covers the line below.
                code = source.lines[lineno].strip()
            attr = mut_at.get(lineno) or mut_at.get(lineno + 1)
            if attr is None:
                decl = guards.DECL_ATTR_RE.search(code)
                attr = decl.group(1) if decl else None
            sites.append(
                {
                    "path": source.path,
                    "line": lineno,
                    "class": class_at.get(lineno),
                    "attr": attr,
                    "why": match.group(1),
                    "code": code,
                }
            )
    sites.sort(key=lambda s: (s["path"], s["line"]))
    return sites


def render_inventory(sites: List[Dict[str, object]]) -> str:
    return (
        json.dumps(
            {"version": 1, "sites": sites}, indent=2, sort_keys=True
        )
        + "\n"
    )
