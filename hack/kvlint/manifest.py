"""Raceguard manifest: kvlint's guarded-by model, exported for runtime.

Phase 1 already knows, for every class, which attributes are declared
``# guarded-by: <lock>``, which attributes hold locks, and which
methods are caller-locked.  ``build_manifest`` serializes that model
keyed by *importable* dotted class path so
``llm_d_kv_cache_manager_tpu/utils/raceguard.py`` can import each class
and instrument it when ``KVTPU_RACEGUARD=1`` — the static contract
becomes an executable one.

The rendered JSON is byte-deterministic (sorted keys, fixed indent), so
the checked-in copy (``hack/kvlint/raceguard_manifest.json``) can be
staleness-pinned: ``python -m hack.kvlint --check-manifest`` (CI, the
pre-commit hook, and a tier-1 test) re-derives it from source and fails
on any drift, exactly like the kvlint baseline contract.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional, Sequence

from hack.kvlint import guards
from hack.kvlint.base import SourceFile
from hack.kvlint.model import find_project_root

MANIFEST_VERSION = 1

# Checked-in location, relative to the repo root.
MANIFEST_RELPATH = os.path.join("hack", "kvlint", "raceguard_manifest.json")


def module_name(path: str, root: Optional[str]) -> Optional[str]:
    """Importable dotted module for ``path`` relative to ``root``.

    ``pkg/sub/mod.py`` -> ``pkg.sub.mod``; ``pkg/__init__.py`` ->
    ``pkg``.  None when the path escapes the root (not importable from
    the repo checkout — such classes can't be instrumented and are
    skipped rather than guessed at).
    """
    abspath = os.path.abspath(path)
    if root is None:
        return None
    rel = os.path.relpath(abspath, root)
    if rel.startswith(os.pardir):
        return None
    rel = rel[: -len(".py")] if rel.endswith(".py") else rel
    parts = rel.split(os.sep)
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    if not all(part.isidentifier() for part in parts):
        return None
    return ".".join(parts)


def _class_entries(
    source: SourceFile, module: str
) -> Dict[str, Dict[str, object]]:
    """Dotted class path -> manifest entry, nested classes included."""
    entries: Dict[str, Dict[str, object]] = {}

    def walk(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                qual = f"{prefix}{child.name}"
                guarded = guards.collect_guards(source, child)
                if guarded:
                    entries[f"{module}:{qual}"] = {
                        "guarded": dict(sorted(guarded.items())),
                        "locks": sorted(guards.lock_attrs(child)),
                        "caller_locked": sorted(
                            guards.caller_locked_methods(source, child)
                        ),
                    }
                walk(child, f"{qual}.")
            elif isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                # Classes defined inside functions are not importable
                # by dotted path; raceguard can't reach them.
                continue
            else:
                walk(child, prefix)

    walk(source.tree, "")
    return entries


def build_manifest(
    sources: Sequence[SourceFile], paths: Sequence[str]
) -> Dict[str, object]:
    root = find_project_root(paths)
    classes: Dict[str, Dict[str, object]] = {}
    for source in sources:
        module = module_name(source.path, root)
        if module is None:
            continue
        classes.update(_class_entries(source, module))
    return {
        "version": MANIFEST_VERSION,
        "classes": {key: classes[key] for key in sorted(classes)},
    }


def render(manifest: Dict[str, object]) -> str:
    return json.dumps(manifest, indent=2, sort_keys=True) + "\n"


def manifest_path(paths: Sequence[str]) -> Optional[str]:
    root = find_project_root(paths)
    if root is None:
        return None
    return os.path.join(root, MANIFEST_RELPATH)


def check_stale(
    sources: Sequence[SourceFile], paths: Sequence[str]
) -> List[str]:
    """Empty when the checked-in manifest matches the sources; else a
    list of human-readable diagnostics (missing file counts too)."""
    target = manifest_path(paths)
    if target is None:
        return ["--check-manifest: no project root (docs/) found"]
    expected = render(build_manifest(sources, paths))
    try:
        with open(target, encoding="utf-8") as handle:
            current = handle.read()
    except OSError:
        return [
            f"{os.path.relpath(target)}: missing — regenerate with "
            "`python -m hack.kvlint --emit-manifest`"
        ]
    if current == expected:
        return []
    try:
        have = json.loads(current)
    except ValueError:
        have = {"classes": {}}
    want = json.loads(expected)
    have_classes = have.get("classes", {})
    want_classes = want.get("classes", {})
    changed = sorted(
        key
        for key in set(have_classes) | set(want_classes)
        if have_classes.get(key) != want_classes.get(key)
    )
    detail = ", ".join(changed[:4]) + ("…" if len(changed) > 4 else "")
    return [
        f"{os.path.relpath(target)}: stale vs `# guarded-by:` "
        f"annotations ({detail or 'formatting'}) — regenerate with "
        "`python -m hack.kvlint --emit-manifest`"
    ]
