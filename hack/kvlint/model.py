"""Phase 1 of the whole-program analyzer: the project model.

PR 2's rules were file-local visitors; the bug classes PRs 1-4 kept
adding — lock-order cycles *between* components, silent drift between
code and the documented config/metrics/trace surface — are invisible
to any single file.  This module builds the cross-file symbol table the
project rules (KV006-KV008) consume:

* **classes** — every class in the analyzed set: its lock attributes
  (``threading.Lock/RLock/Condition`` assignments, including ones
  wrapped by ``lockorder.tracked``), its attribute->class type bindings
  (``self._index = index`` with an annotated parameter, or a direct
  ``self._x = ClassName(...)``), and per-method lock behavior: which
  locks a method acquires, which calls it makes while holding which
  locks, and the lexically nested ``with <lock>`` pairs.
* **lock-order declarations** — the annotation vocabulary:
  ``# kvlint: lock-order: A < B`` (A is always acquired before B) and
  ``# kvlint: lock-order: L ascending`` (multiple instances of L are
  only ever acquired in ascending instance order).
* **env reads** — every literal ``os.environ[...]`` /
  ``os.environ.get`` / ``os.getenv`` name, including names passed
  through a same-module helper that forwards its first parameter to
  ``os.environ`` (the ``_env_int("TRACE_RING_SIZE", ...)`` pattern).
* **metric registrations** — ``Counter/Gauge/Histogram/Summary(...)``
  first-argument names, with module-level string constants resolved
  through f-strings (the ``f"{_NAMESPACE}_..."`` pattern).
* **stage names** — string literals handed to ``span``/``obs_span``,
  ``add_completed`` and ``start_trace``: the
  ``kvtpu_stage_latency_seconds{stage=...}`` label vocabulary.
* **the documented surface** — knobs parsed from the env-var tables of
  ``docs/configuration.md`` and ``docs/observability.md``, metric
  names (with ``*`` wildcards) from the metrics-inventory table, and
  every backticked token of ``docs/observability.md`` as the stage
  vocabulary.  Native C++ sources and repo-root scripts are scanned
  for ``getenv("...")`` so knobs read outside Python (e.g.
  ``KVTPU_NATIVE_DEBUG``) don't read as doc-only drift.

The model is deliberately an over-approximation where it must be (a
call on an attribute typed as a base class resolves to every subclass
that defines the method) and silent where it cannot know (calls on
unresolvable receivers are skipped); docs/static-analysis.md documents
both choices.
"""

from __future__ import annotations

import ast
import glob
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from hack.kvlint.base import SourceFile, dotted_name
from hack.kvlint.guards import is_lock_call as _is_lock_call

_METRIC_FACTORIES = {"Counter", "Gauge", "Histogram", "Summary"}

_SPAN_CALLS = {"span", "obs_span", "add_completed", "start_trace"}

LOCK_ORDER_RE = re.compile(
    r"kvlint:\s*lock-order:\s*"
    r"([A-Za-z_][\w.]*)\s*(?:<\s*([A-Za-z_][\w.]*)|(ascending))"
)

_ENV_NAME_RE = re.compile(r"^[A-Z][A-Z0-9_]{2,}$")
_GETENV_SRC_RE = re.compile(r"getenv\(\s*\"([A-Z][A-Z0-9_]{2,})\"")

DOCS_CONFIG = os.path.join("docs", "configuration.md")
DOCS_OBSERVABILITY = os.path.join("docs", "observability.md")


@dataclass(frozen=True)
class LockRef:
    """One lock identity, aggregated across instances.

    ``owner`` is the declaring class name (or ``module:<stem>`` for a
    module-level lock), ``attr`` the attribute name — shard stripes of
    one class collapse onto a single node, which is exactly what makes
    same-node nesting (two shards of one striped structure) visible as
    a self-edge.
    """

    owner: str
    attr: str

    @property
    def name(self) -> str:
        return f"{self.owner}.{self.attr}"


@dataclass
class CallSite:
    """A call made while holding ``held`` locks."""

    receiver: Optional[str]  # "self", attr chain ("self._index"), name
    method: str
    held: Tuple[LockRef, ...]
    path: str
    line: int


@dataclass
class MethodModel:
    name: str
    path: str
    line: int
    # Locks this method acquires directly (lexical `with`).
    acquires: List[Tuple[LockRef, int]] = field(default_factory=list)
    # (outer, inner, line-of-inner) for lexically nested acquisition.
    nested: List[Tuple[LockRef, LockRef, int]] = field(default_factory=list)
    calls: List[CallSite] = field(default_factory=list)


@dataclass
class ClassModel:
    name: str
    path: str
    line: int
    bases: List[str] = field(default_factory=list)
    lock_attrs: Set[str] = field(default_factory=set)
    # self.<attr> -> inferred class name (constructor call or annotated
    # parameter assignment).
    attr_types: Dict[str, str] = field(default_factory=dict)
    methods: Dict[str, MethodModel] = field(default_factory=dict)
    # Resource attrs for KV008: attr -> (kind, line).
    resources: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    # Attr names referenced by each method (KV008 close-path search).
    method_attr_refs: Dict[str, Set[str]] = field(default_factory=dict)


@dataclass
class EnvRead:
    name: str
    path: str
    line: int


@dataclass
class MetricRegistration:
    name: str
    path: str
    line: int
    # Factory class name ("Counter", "Gauge", ...). Counters gain a
    # `_total` suffix at exposition, so docs may show either form.
    kind: str = ""


@dataclass
class StageUse:
    name: str
    path: str
    line: int


@dataclass
class OrderDecl:
    """One `# kvlint: lock-order:` annotation."""

    first: str
    second: Optional[str]  # None for `ascending`
    ascending: bool
    path: str
    line: int


@dataclass
class DocSurface:
    """The documented contract surface parsed from docs/."""

    root: str
    # knob name -> (doc path, line) of its table row.
    knobs: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    # exact metric name (namespace stripped) -> (doc path, line)
    metrics: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    metric_wildcards: List[str] = field(default_factory=list)
    stages: Set[str] = field(default_factory=set)
    # env names read outside the analyzed Python set (native C++,
    # repo-root scripts): documented-but-unread must not fire on them.
    external_env_reads: Set[str] = field(default_factory=set)


class ProjectModel:
    """The cross-file symbol table rule phases consume."""

    def __init__(self, sources: Sequence[SourceFile]) -> None:
        self.sources = list(sources)
        self.by_path: Dict[str, SourceFile] = {s.path: s for s in sources}
        self.classes: Dict[str, ClassModel] = {}
        self.subclasses: Dict[str, Set[str]] = {}
        self.env_reads: List[EnvRead] = []
        self.metric_registrations: List[MetricRegistration] = []
        self.stage_uses: List[StageUse] = []
        self.order_decls: List[OrderDecl] = []
        self.docs: Optional[DocSurface] = None
        # True when the analyzed roots cover a whole top-level package
        # (the CI invocation); whole-program-only checks key off this.
        self.whole_program = False
        for source in self.sources:
            self._scan_source(source)
        self._link_subclasses()

    # -- per-file scan --------------------------------------------------

    def _scan_source(self, source: SourceFile) -> None:
        self._collect_order_decls(source)
        env_helpers = _env_helper_params(source.tree)
        module_consts = _module_str_constants(source.tree)
        # Module-level locks first, so a function defined above the
        # lock assignment still resolves `with _lock:` against it.
        for node in source.tree.body:
            self._scan_module_level(source, node)
        module_cls = self.classes.get(_module_owner(source.path))
        for node in source.tree.body:
            if isinstance(node, ast.ClassDef):
                self._scan_class(source, node, module_consts)
            elif isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                # Module-level functions acquire module-level locks by
                # bare name (`with _lock:`) — scan them as methods of
                # the synthetic module class so KV006 sees the edges.
                if module_cls is not None:
                    self._scan_method(
                        source, module_cls, node, {}, module_scope=True
                    )
        for node in ast.walk(source.tree):
            self._maybe_env_read(source, node, env_helpers)
            self._maybe_metric(source, node, module_consts)
            self._maybe_stage(source, node)

    def _collect_order_decls(self, source: SourceFile) -> None:
        for lineno, (_, comment) in sorted(source.comments.items()):
            match = LOCK_ORDER_RE.search(comment)
            if not match:
                continue
            first, second, ascending = match.groups()
            self.order_decls.append(
                OrderDecl(
                    first=first,
                    second=second,
                    ascending=bool(ascending),
                    path=source.path,
                    line=lineno,
                )
            )

    def _scan_module_level(
        self, source: SourceFile, node: ast.AST
    ) -> None:
        """Module-level locks: ``_lock = threading.Lock()``."""
        if isinstance(node, ast.Assign) and _is_lock_call(node.value):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    owner = _module_owner(source.path)
                    cls = self.classes.setdefault(
                        owner, ClassModel(owner, source.path, node.lineno)
                    )
                    cls.lock_attrs.add(target.id)

    def _scan_class(
        self,
        source: SourceFile,
        node: ast.ClassDef,
        module_consts: Dict[str, str],
    ) -> None:
        existing = self.classes.get(node.name)
        cls = ClassModel(node.name, source.path, node.lineno)
        cls.bases = [
            base_name
            for base in node.bases
            if (base_name := dotted_name(base)) is not None
        ]
        if existing is not None:
            # Same class name in two files: merge (rule output degrades
            # to the union, which over-reports rather than missing).
            cls = existing
            cls.bases.extend(
                b
                for base in node.bases
                if (b := dotted_name(base)) is not None and b not in cls.bases
            )
        self.classes[node.name] = cls

        # Parameter annotations of every method feed attr typing:
        #   def __init__(self, index: Index): self._index = index
        param_types: Dict[str, str] = {}
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for arg in item.args.args + item.args.kwonlyargs:
                    ann = arg.annotation
                    if ann is not None:
                        ann_name = _annotation_class(ann)
                        if ann_name:
                            param_types[arg.arg] = ann_name

        for item in node.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            self._scan_method(source, cls, item, param_types)

    def _scan_method(
        self,
        source: SourceFile,
        cls: ClassModel,
        func: ast.AST,
        param_types: Dict[str, str],
        module_scope: bool = False,
    ) -> None:
        method = MethodModel(func.name, source.path, func.lineno)
        cls.methods[func.name] = method
        refs: Set[str] = set()
        cls.method_attr_refs[func.name] = refs

        def self_attr(node: ast.AST) -> Optional[str]:
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                return node.attr
            if module_scope and isinstance(node, ast.Name):
                # `with _lock:` on a module-level lock.
                return node.id
            return None

        def visit(node: ast.AST, held: Tuple[LockRef, ...]) -> None:
            if isinstance(node, ast.ClassDef):
                return
            attr = self_attr(node)
            if attr is not None:
                refs.add(attr)
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                self._scan_attr_assign(cls, node, param_types)
            if isinstance(node, (ast.With, ast.AsyncWith)):
                # `with a, b:` acquires a then b — items nest left to
                # right exactly like the nested-with form, so each item
                # sees every earlier item of the same statement as held.
                acquired: List[LockRef] = []
                for item in node.items:
                    visit(item.context_expr, held + tuple(acquired))
                    lock_attr = self_attr(item.context_expr)
                    if (
                        lock_attr is not None
                        and lock_attr in cls.lock_attrs
                    ):
                        ref = LockRef(cls.name, lock_attr)
                        method.acquires.append((ref, node.lineno))
                        for outer in held + tuple(acquired):
                            method.nested.append(
                                (outer, ref, node.lineno)
                            )
                        acquired.append(ref)
                inner = held + tuple(acquired)
                for stmt in node.body:
                    visit(stmt, inner)
                return
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                # Same soundness rule as KV001: a closure can escape
                # the `with` block, so it never inherits held locks.
                body = (
                    node.body
                    if isinstance(node.body, list)
                    else [node.body]
                )
                for stmt in body:
                    visit(stmt, ())
                return
            if isinstance(node, ast.Call):
                self._record_call(source, method, node, held)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in func.body:
            visit(stmt, ())

    def _scan_attr_assign(
        self,
        cls: ClassModel,
        node: ast.AST,
        param_types: Dict[str, str],
    ) -> None:
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        else:  # AnnAssign
            targets, value = [node.target], node.value
        for target in targets:
            if not (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                continue
            attr = target.attr
            if value is None:
                continue
            if _is_lock_call(value):
                cls.lock_attrs.add(attr)
                continue
            kind = _resource_kind(value)
            if kind is not None:
                cls.resources.setdefault(attr, (kind, node.lineno))
            if isinstance(value, ast.Call):
                callee = dotted_name(value.func)
                if callee:
                    # self._x = Foo(...) / pkg.Foo(...) -> type Foo
                    cls.attr_types.setdefault(
                        attr, callee.rsplit(".", 1)[-1]
                    )
            elif isinstance(value, ast.Name):
                inferred = param_types.get(value.id)
                if inferred:
                    cls.attr_types.setdefault(attr, inferred)

    def _record_call(
        self,
        source: SourceFile,
        method: MethodModel,
        node: ast.Call,
        held: Tuple[LockRef, ...],
    ) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            receiver = dotted_name(func.value)
            method.calls.append(
                CallSite(
                    receiver=receiver,
                    method=func.attr,
                    held=held,
                    path=source.path,
                    line=node.lineno,
                )
            )
        elif isinstance(func, ast.Name):
            method.calls.append(
                CallSite(
                    receiver=None,
                    method=func.id,
                    held=held,
                    path=source.path,
                    line=node.lineno,
                )
            )

    # -- env / metrics / stages ----------------------------------------

    def _maybe_env_read(
        self,
        source: SourceFile,
        node: ast.AST,
        env_helpers: Set[str],
    ) -> None:
        name: Optional[str] = None
        line = getattr(node, "lineno", 0)
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func)
            if callee in ("os.environ.get", "os.getenv", "environ.get"):
                name = _literal_str(node.args[0]) if node.args else None
            elif (
                callee in env_helpers
                or (
                    callee
                    and callee.rsplit(".", 1)[-1] in env_helpers
                )
            ):
                name = _literal_str(node.args[0]) if node.args else None
        elif isinstance(node, ast.Subscript):
            base = dotted_name(node.value)
            if base in ("os.environ", "environ"):
                name = _literal_str(node.slice)
        if name and _ENV_NAME_RE.match(name):
            self.env_reads.append(EnvRead(name, source.path, line))

    def _maybe_metric(
        self,
        source: SourceFile,
        node: ast.AST,
        module_consts: Dict[str, str],
    ) -> None:
        if not isinstance(node, ast.Call):
            return
        callee = dotted_name(node.func)
        if not callee:
            return
        kind = callee.rsplit(".", 1)[-1]
        if kind not in _METRIC_FACTORIES:
            return
        if not node.args:
            return
        name = _resolve_str(node.args[0], module_consts)
        if name:
            self.metric_registrations.append(
                MetricRegistration(name, source.path, node.lineno, kind)
            )

    def _maybe_stage(self, source: SourceFile, node: ast.AST) -> None:
        if not isinstance(node, ast.Call):
            return
        callee = dotted_name(node.func)
        if not callee:
            return
        if callee.rsplit(".", 1)[-1] not in _SPAN_CALLS:
            return
        if not node.args:
            return
        name = _literal_str(node.args[0])
        if name:
            self.stage_uses.append(
                StageUse(name, source.path, node.lineno)
            )

    # -- subclass map ---------------------------------------------------

    def _link_subclasses(self) -> None:
        for cls in self.classes.values():
            for base in cls.bases:
                base_name = base.rsplit(".", 1)[-1]
                self.subclasses.setdefault(base_name, set()).add(cls.name)

    def transitive_subclasses(self, name: str) -> Set[str]:
        out: Set[str] = set()
        frontier = [name]
        while frontier:
            current = frontier.pop()
            for sub in self.subclasses.get(current, ()):
                if sub not in out:
                    out.add(sub)
                    frontier.append(sub)
        return out

    # -- call resolution ------------------------------------------------

    def resolve_call(
        self, caller: ClassModel, call: CallSite
    ) -> List[Tuple[ClassModel, MethodModel]]:
        """Possible (class, method) targets of a call site.

        ``self.m()`` resolves within the class (and its subclasses —
        a template method may run overridden under the base's lock).
        ``self._attr.m()`` resolves through the attr's inferred type,
        widened to every subclass defining ``m`` (an attr typed as the
        ``Index`` ABC may hold any backend).  Unresolvable receivers
        resolve to nothing — the documented soundness gap.
        """
        targets: List[Tuple[ClassModel, MethodModel]] = []

        def add_type(type_name: str) -> None:
            seen: Set[str] = set()
            for candidate in [type_name, *self.transitive_subclasses(
                type_name
            )]:
                if candidate in seen:
                    continue
                seen.add(candidate)
                cls = self.classes.get(candidate)
                if cls is None:
                    continue
                target = cls.methods.get(call.method)
                if target is not None:
                    targets.append((cls, target))

        if call.receiver == "self":
            add_type(caller.name)
        elif call.receiver and call.receiver.startswith("self."):
            attr = call.receiver.split(".", 1)[1]
            if "." not in attr:
                type_name = caller.attr_types.get(attr)
                if type_name:
                    add_type(type_name)
        return targets


# -- docs parsing -------------------------------------------------------


def find_project_root(paths: Sequence[str]) -> Optional[str]:
    """Nearest ancestor of an analyzed path holding docs/configuration.md.

    No cwd fallback: an ad-hoc file outside any project tree gets no
    documented surface, and the doc-dependent KV007 checks stay off.
    """
    for path in paths:
        current = os.path.abspath(path)
        if os.path.isfile(current):
            current = os.path.dirname(current)
        while True:
            if os.path.isfile(os.path.join(current, DOCS_CONFIG)):
                return current
            parent = os.path.dirname(current)
            if parent == current:
                break
            current = parent
    return None


_TABLE_ROW_RE = re.compile(r"^\s*\|(.+)\|\s*$")
_BACKTICK_RE = re.compile(r"`([^`]+)`")


def _row_cells(line: str) -> List[str]:
    match = _TABLE_ROW_RE.match(line)
    if not match:
        return []
    return [cell.strip() for cell in match.group(1).split("|")]


def parse_docs(root: str) -> DocSurface:
    docs = DocSurface(root=root)
    config_path = os.path.join(root, DOCS_CONFIG)
    obs_path = os.path.join(root, DOCS_OBSERVABILITY)
    for doc_path in (config_path, obs_path):
        if not os.path.isfile(doc_path):
            continue
        rel = os.path.relpath(doc_path, os.getcwd())
        with open(doc_path, encoding="utf-8") as handle:
            for lineno, line in enumerate(handle, start=1):
                cells = _row_cells(line)
                if not cells:
                    continue
                # Env knobs: first-cell backticked ALL-CAPS tokens of
                # any table (the env tables; invariant rows that quote
                # e.g. `PYTHONHASHSEED` in cell one count too, which
                # is correct — the knob IS documented there).
                for token in _BACKTICK_RE.findall(cells[0]):
                    if _ENV_NAME_RE.match(token):
                        docs.knobs.setdefault(token, (rel, lineno))
    if os.path.isfile(obs_path):
        rel = os.path.relpath(obs_path, os.getcwd())
        with open(obs_path, encoding="utf-8") as handle:
            in_inventory = False
            for lineno, line in enumerate(handle, start=1):
                if line.startswith("#"):
                    in_inventory = "metrics inventory" in line.lower()
                for token in _BACKTICK_RE.findall(line):
                    docs.stages.add(token)
                if not in_inventory:
                    continue
                cells = _row_cells(line)
                if not cells:
                    continue
                for token in _BACKTICK_RE.findall(cells[0]):
                    if token.endswith("*"):
                        docs.metric_wildcards.append(token[:-1])
                    elif re.match(r"^[a-z][a-z0-9_]+$", token):
                        docs.metrics.setdefault(token, (rel, lineno))
    docs.external_env_reads = _scan_external_env_reads(root)
    return docs


def _scan_external_env_reads(root: str) -> Set[str]:
    """Env names read outside the analyzed Python set: native C++
    (``std::getenv``) and repo-root scripts (bench.py etc.)."""
    names: Set[str] = set()
    patterns = [
        os.path.join(root, "*.py"),
        os.path.join(root, "hack", "*.py"),
        os.path.join(root, "**", "native", "src", "*.cpp"),
        os.path.join(root, "**", "native", "src", "*.hpp"),
    ]
    for pattern in patterns:
        for path in glob.glob(pattern, recursive=True):
            try:
                with open(path, encoding="utf-8", errors="ignore") as fh:
                    text = fh.read()
            except OSError:
                continue
            for match in _GETENV_SRC_RE.finditer(text):
                names.add(match.group(1))
            # Python-side literal reads in scripts.
            for match in re.finditer(
                r"environ(?:\.get)?[\[(]\s*[\"']([A-Z][A-Z0-9_]{2,})[\"']",
                text,
            ):
                names.add(match.group(1))
    return names


def attach_docs(model: ProjectModel, paths: Sequence[str]) -> None:
    """Locate and parse the documented surface; mark whole-program
    scope (an analyzed directory directly under the project root —
    the ``python -m hack.kvlint <package>`` CI shape)."""
    root = find_project_root(paths)
    if root is None:
        return
    model.docs = parse_docs(root)
    for path in paths:
        abspath = os.path.abspath(path)
        if os.path.isdir(abspath) and os.path.dirname(abspath) == root:
            model.whole_program = True
            break


# -- small AST helpers --------------------------------------------------


def _module_owner(path: str) -> str:
    """Unique synthetic owner for a file's module-level locks.

    Path-derived (not the bare stem): every package has an
    ``__init__.py``, and merging their same-named module locks onto one
    node would invent self-edges that exist in no program."""
    rel = os.path.splitext(path)[0].replace(os.sep, ".").lstrip(".")
    return f"module:{rel}"


def _resource_kind(node: ast.AST) -> Optional[str]:
    if not isinstance(node, ast.Call):
        return None
    callee = dotted_name(node.func)
    if not callee:
        return None
    leaf = callee.rsplit(".", 1)[-1]
    if leaf == "Thread":
        return "thread"
    if leaf in ("ThreadPoolExecutor", "ProcessPoolExecutor"):
        return "executor"
    if callee in ("socket.socket",):
        return "socket"
    if leaf == "socket" and callee != "socket.socket":
        # ctx.socket(zmq.SUB) — the ZMQ socket-from-context shape.
        return "zmq socket"
    if callee in ("zmq.Context", "Context"):
        return "zmq context"
    return None


def _annotation_class(node: ast.AST) -> Optional[str]:
    """Class name of a simple annotation; Optional[X] unwraps to X."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.rsplit(".", 1)[-1] or None
    name = dotted_name(node)
    if name:
        return name.rsplit(".", 1)[-1]
    if isinstance(node, ast.Subscript):
        base = dotted_name(node.value)
        if base and base.rsplit(".", 1)[-1] == "Optional":
            return _annotation_class(node.slice)
    return None


def _literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _module_str_constants(tree: ast.AST) -> Dict[str, str]:
    consts: Dict[str, str] = {}
    for node in getattr(tree, "body", []):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            value = _literal_str(node.value)
            if isinstance(target, ast.Name) and value is not None:
                consts[target.id] = value
    return consts


def _resolve_str(
    node: ast.AST, consts: Dict[str, str]
) -> Optional[str]:
    """Literal, module-constant, f-string-of-constants, or
    constant-concatenation string value; None when dynamic."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    if isinstance(node, ast.JoinedStr):
        parts: List[str] = []
        for value in node.values:
            if isinstance(value, ast.Constant):
                parts.append(str(value.value))
            elif isinstance(value, ast.FormattedValue):
                resolved = _resolve_str(value.value, consts)
                if resolved is None:
                    return None
                parts.append(resolved)
            else:
                return None
        return "".join(parts)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _resolve_str(node.left, consts)
        right = _resolve_str(node.right, consts)
        if left is not None and right is not None:
            return left + right
    return None


def _env_helper_params(tree: ast.AST) -> Set[str]:
    """Names of module functions that forward their first parameter to
    ``os.environ`` (``def _env_int(name, default): os.environ.get(name)``
    — call sites with a literal first arg then count as env reads)."""
    helpers: Set[str] = set()
    for node in getattr(tree, "body", []):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        params = [a.arg for a in node.args.args]
        if not params:
            continue
        first = params[0]
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                callee = dotted_name(sub.func)
                if (
                    callee in ("os.environ.get", "os.getenv", "environ.get")
                    and sub.args
                    and isinstance(sub.args[0], ast.Name)
                    and sub.args[0].id == first
                ):
                    helpers.add(node.name)
                    break
            elif isinstance(sub, ast.Subscript):
                base = dotted_name(sub.value)
                if (
                    base in ("os.environ", "environ")
                    and isinstance(sub.slice, ast.Name)
                    and sub.slice.id == first
                ):
                    helpers.add(node.name)
                    break
    return helpers


def build_model(
    sources: Sequence[SourceFile], paths: Sequence[str]
) -> ProjectModel:
    model = ProjectModel(sources)
    attach_docs(model, paths)
    return model
