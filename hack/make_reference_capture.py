"""Deterministic generator for the pinned what-if reference capture.

``tests/testdata/whatif_reference.cbor`` is the capture
``hack/perf_trend.py`` replays (shards=1 vs shards=8 A/B) to gate
capacity regressions, and the seed ``hack/whatif_smoke.py`` composes
storms from.  It must be BYTE-STABLE across machines and package
versions, so this generator:

* drives a REAL stack (indexer + kvevents pool + flight recorder) with
  a seeded workload — recorded score maps and the canonical state
  section are measured truth, not hand-written fixtures;
* then rewrites the nondeterministic envelope: record timestamps
  become a seeded bursty schedule over a ~60 s virtual window, and the
  header gets the PINNED fingerprint/knobs below (the live fingerprint
  hashes the package version, which would churn the artifact every
  release; what-if loads with ``allow_mismatch=True`` by design).

Everything else (global seq order, payload bytes, score maps, state)
is already deterministic: ingress is single-threaded, block hashing is
FNV-64a over canonical CBOR, and the pool fully drains before every
score.  ``tests/test_whatif.py::test_reference_capture_is_current``
rebuilds the bytes and compares against the checked-in file, so a
drift in ANY of those layers fails CI with this script as the fix.

Run: ``python hack/make_reference_capture.py`` (writes the artifact
in place).
"""

import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TOKENIZERS_PARALLELISM", "false")

BLOCK = 4
MODEL = "whatif-ref"
SEED = 20260806
PODS = 3
ROUNDS = 24
# Pinned header identity — survives version bumps by construction.
FINGERPRINT = "whatif-reference-v1"
KNOBS = [["BLOCK_SIZE", str(BLOCK)], ["MODEL_NAME", MODEL]]
# Virtual origin: 2026-01-01T00:00:00Z in microseconds.
T0_US = 1_767_225_600_000_000

OUTPUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests",
    "testdata",
    "whatif_reference.cbor",
)


def _drive(recorder) -> bytes:
    """Seeded mixed workload against a fresh stack; returns the live
    artifact bytes (real score maps + canonical state)."""
    from llm_d_kv_cache_manager_tpu.kvcache.indexer import (
        Indexer,
        IndexerConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
        TokenProcessorConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvevents.events import (
        BlockRemoved,
        BlockStored,
        EventBatch,
    )
    from llm_d_kv_cache_manager_tpu.kvevents.pool import (
        Message,
        Pool,
        PoolConfig,
    )
    from llm_d_kv_cache_manager_tpu.obs.replay import (
        _ReplayTokenizer,
        render_prompt,
    )

    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK, hash_seed=""
            ),
            cache_stats=False,
        ),
        tokenizer=_ReplayTokenizer(),
        capture_recorder=recorder,
    )
    indexer.run()
    pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(concurrency=2, max_queue_depth=1 << 30),
        capture=recorder,
    )
    pool.start()
    rng = random.Random(SEED)
    seqs = {}

    def send(pod, payload):
        seqs[pod] = seqs.get(pod, 0) + 1
        pool.add_task(
            Message(
                topic=f"kv@{pod}@{MODEL}",
                payload=payload,
                pod_identifier=pod,
                model_name=MODEL,
                seq=seqs[pod],
            )
        )

    def stored(hashes, tokens, parent=None, medium="hbm"):
        return EventBatch(
            ts=1.0,
            events=[
                BlockStored(
                    block_hashes=list(hashes),
                    parent_block_hash=parent,
                    token_ids=list(tokens),
                    block_size=BLOCK,
                    medium=medium,
                )
            ],
        ).encode()

    try:
        convo = []
        for round_i in range(ROUNDS):
            convo.extend(
                rng.randrange(1, 30_000) for _ in range(BLOCK * 3)
            )
            for pod_i in range(PODS):
                if rng.random() < 0.25:
                    continue
                pod = f"pod-{pod_i}"
                claimed = rng.randrange(1, len(convo) // BLOCK + 1)
                medium = "host" if rng.random() < 0.3 else "hbm"
                send(
                    pod,
                    stored(
                        [
                            90_000 + round_i * 500 + pod_i * 100 + b
                            for b in range(claimed)
                        ],
                        convo[: claimed * BLOCK],
                        medium=medium,
                    ),
                )
                if rng.random() < 0.35:
                    private_hash = 800_000 + pod_i * 1_000 + round_i
                    send(
                        pod,
                        stored(
                            [private_hash],
                            [
                                40_000
                                + pod_i * 5_000
                                + round_i * BLOCK
                                + j
                                + 1
                                for j in range(BLOCK)
                            ],
                        ),
                    )
                    if rng.random() < 0.5:
                        send(
                            pod,
                            EventBatch(
                                ts=0.0,
                                events=[
                                    BlockRemoved(
                                        block_hashes=[private_hash]
                                    )
                                ],
                            ).encode(),
                        )
            # Every admitted write visible before the round's scores —
            # what replay AND what-if's unbounded-drain mode reproduce.
            pool.drain()
            hit_prompt = render_prompt(convo)
            pod_filter = (
                [f"pod-{i}" for i in range(PODS)]
                if rng.random() < 0.5
                else None
            )
            for _ in range(rng.randrange(2, 5)):
                indexer.get_pod_scores(hit_prompt, MODEL, pod_filter)
            # Cold prompts keep the measured hit rate honestly < 1.
            miss_tokens = [
                900_000 + round_i * 100 + j for j in range(BLOCK * 2)
            ]
            indexer.get_pod_scores(
                render_prompt(miss_tokens), MODEL, None
            )
        pool.drain()
        return recorder.dump_bytes(index=indexer.kv_block_index)
    finally:
        pool.shutdown()
        indexer.shutdown()


def _schedule(count: int) -> list:
    """Seeded bursty offsets (microseconds from T0): bursts of 5-20
    records 2-15 ms apart, separated by 0.5-4 s idle gaps — the shape
    time compression turns into arrival pressure."""
    rng = random.Random(SEED + 1)
    offsets = []
    t = 0
    remaining_in_burst = 0
    for _ in range(count):
        if remaining_in_burst == 0:
            remaining_in_burst = rng.randrange(5, 21)
            t += rng.randrange(500_000, 4_000_001)
        else:
            t += rng.randrange(2_000, 15_001)
        remaining_in_burst -= 1
        offsets.append(t)
    return offsets


def build_reference_capture() -> bytes:
    """The full pipeline: drive, re-stamp, pin the header.  Importable
    so the staleness test rebuilds and compares bytes."""
    from llm_d_kv_cache_manager_tpu.obs.capture import (
        CaptureConfig,
        InputCaptureRecorder,
        encode_capture,
        load_artifact,
    )

    recorder = InputCaptureRecorder(
        CaptureConfig(window_s=3600.0, max_bytes=32 << 20),
        meta={
            "block_size": BLOCK,
            "hash_seed": "",
            "model": MODEL,
        },
    )
    art = load_artifact(_drive(recorder))
    records = art["records"]
    offsets = _schedule(len(records))
    for record, offset in zip(records, offsets):
        record[2] = T0_US + offset
    meta = dict(art["meta"])
    meta["generator"] = "hack/make_reference_capture.py"
    meta["seed"] = str(SEED)
    return encode_capture(
        records,
        fingerprint=FINGERPRINT,
        knobs=KNOBS,
        created_us=T0_US,
        window_s=3600,
        max_bytes=0,
        truncated=[],
        meta=meta,
        state=art["state"],
    )


def main() -> int:
    payload = build_reference_capture()
    with open(OUTPUT, "wb") as handle:
        handle.write(payload)
    print(f"wrote {OUTPUT} ({len(payload)} bytes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
