"""CI smoke gate for the XLA host-offload staging engine.

End-to-end over the REAL data plane (docs/host-offload.md):

* **store -> evict -> load round trip** through the per-chip staging
  lanes: pool blocks staged to shared-storage files (atomic layout),
  the pool overwritten (eviction stand-in), then paged back through
  the staged load pipeline — bytes bit-identical;
* **demotion moves bytes**: a DemotionWorker cycle over the
  StagedDemotionTarget pages the group hbm -> host (readable from the
  HostTierCache) and then host -> shared_storage (readable from the
  file), with the medium-tagged events riding the real kvevents pool
  so the index tier AND the live score follow each rung
  (1.0 -> 0.8 -> 0.5 per block);
* **measured RTT feeds the advisor**: `/debug/tiering` shows read- and
  write-side estimator observations from the real transfers (not
  simulated), and the writeback gauge is on `/metrics`.

Run: ``python hack/offload_smoke.py`` (CI step "Host-offload smoke",
``make offload-smoke``).  Prints "offload smoke completed successfully"
on success; any assertion exits non-zero.
"""

import json
import os
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TOKENIZERS_PARALLELISM", "false")
os.environ.setdefault("CACHESTATS_SAMPLE_RATE", "1")
os.environ.setdefault("TIERING_REFRESH_S", "0")

import numpy as np  # noqa: E402

from llm_d_kv_cache_manager_tpu.api.http_service import serve  # noqa: E402
from llm_d_kv_cache_manager_tpu.kvcache.indexer import (  # noqa: E402
    Indexer,
    IndexerConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (  # noqa: E402,E501
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (  # noqa: E402
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (  # noqa: E402
    Message,
    Pool,
    PoolConfig,
)
from llm_d_kv_cache_manager_tpu.models.kv_cache_pool import (  # noqa: E402
    KVCachePool,
    KVCachePoolConfig,
)
from llm_d_kv_cache_manager_tpu.native.engine import JobStatus  # noqa: E402
from llm_d_kv_cache_manager_tpu.offload.host_tier import (  # noqa: E402
    HostTierCache,
)
from llm_d_kv_cache_manager_tpu.offload.spec import (  # noqa: E402
    TPUOffloadConnector,
    TPUOffloadSpec,
)
from llm_d_kv_cache_manager_tpu.offload.worker import (  # noqa: E402
    group_blocks_per_file,
    host_dtype,
)
from llm_d_kv_cache_manager_tpu.tiering import (  # noqa: E402
    DemotionConfig,
    PolicyEngine,
    StagedDemotionTarget,
    pool_event_sink,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (  # noqa: E402
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (  # noqa: E402
    Encoding,
)

MODEL = "test-model"
BLOCK_SIZE = 4  # indexer-side tokens per block


class WordTokenizer:
    """Deterministic whitespace tokenizer: 'tN' -> N."""

    def type(self) -> str:
        return "word"

    def encode(self, prompt, model_name, add_special_tokens=True):
        tokens, offsets, pos = [], [], 0
        for word in prompt.split(" "):
            tokens.append(int(word[1:]))
            offsets.append((pos, pos + len(word)))
            pos += len(word) + 1
        return Encoding(tokens, offsets)


def post(base, path, obj):
    request = urllib.request.Request(
        base + path,
        data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(request, timeout=30) as response:
        return json.load(response)


def get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as response:
        return json.load(response)


def get_text(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as response:
        return response.read().decode()


def main() -> None:  # noqa: PLR0915 — one linear smoke story
    storage_root = tempfile.mkdtemp(prefix="kvtpu-offload-smoke-")

    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=2, model_name=MODEL
            ),
        ),
        tokenizer=WordTokenizer(),
    )
    indexer.run()
    engine = PolicyEngine(ledger=indexer.cache_stats)
    indexer.set_policy_engine(engine)
    event_pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(concurrency=2),
    )
    event_pool.start()

    # --- the real data plane: pool + staged connector + policy feeds ---
    pool_config = KVCachePoolConfig(
        num_layers=2,
        num_blocks=64,
        block_size=8,
        num_kv_heads=2,
        head_dim=16,
        dtype="bfloat16",
    )
    pool = KVCachePool(pool_config)
    spec = TPUOffloadSpec(
        shared_storage_path=storage_root,
        model_name=MODEL,
        device_block_size=8,
        offloaded_block_size=16,
        threads_per_chip=2,
        host_cache_bytes=0,
        staging_lanes=2,
    )
    connector = TPUOffloadConnector(spec, pool, policy_engine=engine)
    assert connector.staging is not None, "staging knob did not engage"

    # 1. store -> evict -> load round trip through the staging engine.
    rng = np.random.default_rng(7)
    block_ids = [3, 4, 7, 9]
    originals = {}
    for block_id in block_ids:
        data = rng.standard_normal(
            (
                pool_config.num_layers,
                2,
                pool_config.block_size,
                pool_config.num_kv_heads,
                pool_config.head_dim,
            )
        ).astype(host_dtype(pool_config.dtype))
        pool.write_block(block_id, data)
        originals[block_id] = data
    file_hashes = [0xA1, 0xA2]
    groups = group_blocks_per_file(file_hashes, block_ids, 2)
    connector.store_handler.transfer_async(1, groups)
    assert connector.store_handler.wait(1) == JobStatus.SUCCEEDED
    for file_hash in file_hashes:
        path = connector.file_mapper.get_file_name(file_hash)
        assert os.path.exists(path), f"missing block file {path}"

    zero = np.zeros_like(next(iter(originals.values())))
    for block_id in block_ids:  # "evict": the pool forgets the bytes
        pool.write_block(block_id, zero)
    connector.load_handler.transfer_async(
        2, group_blocks_per_file(file_hashes, block_ids, 2)
    )
    assert connector.load_handler.wait(2) == JobStatus.SUCCEEDED
    restored = pool.gather_to_host(block_ids)
    for i, block_id in enumerate(block_ids):
        np.testing.assert_array_equal(restored[:, i], originals[block_id])
    print("store -> evict -> load round trip: bytes bit-identical")

    # Both estimator directions observed REAL transfers.
    advisor_stats = engine.advisor.stats()
    assert advisor_stats["rtt"]["observations"] >= 1, advisor_stats
    assert advisor_stats["rtt_store"]["observations"] >= 1, advisor_stats

    # 2. the index side: seed a chain on pod-1 at hbm, teach the feed.
    tokens = list(range(1, 33))  # 8 blocks of 4
    n_blocks = len(tokens) // BLOCK_SIZE
    prompt = " ".join(f"t{t}" for t in tokens)
    engine_hashes = [0x300 + i for i in range(n_blocks)]
    batch = EventBatch(
        ts=1.0,
        events=[
            BlockStored(
                block_hashes=list(engine_hashes),
                parent_block_hash=None,
                token_ids=tokens,
                block_size=BLOCK_SIZE,
                medium="hbm",
            )
        ],
    )
    event_pool.add_task(
        Message(
            topic=f"kv@pod-1@{MODEL}",
            payload=batch.encode(),
            pod_identifier="pod-1",
            model_name=MODEL,
        )
    )
    event_pool.drain()

    server = serve(indexer, host="127.0.0.1", port=0, tiering=engine)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    scores = post(
        base, "/score_completions", {"prompt": prompt, "model": MODEL}
    )
    assert scores.get("pod-1") == n_blocks, scores

    # 3. demotion cycles MOVE BYTES, and index tier + score follow.
    demo_ids = [11, 12]
    for block_id in demo_ids:
        pool.write_block(
            block_id,
            rng.standard_normal(zero.shape).astype(zero.dtype),
        )
    expected_group = pool.gather_block_major(demo_ids)
    host_cache = HostTierCache(1 << 22)
    group_key = 0xFACE
    target = StagedDemotionTarget(
        capacity_bytes=64 * pool.block_nbytes,
        pool=pool,
        file_mapper=connector.file_mapper,
        host_cache=host_cache,
        event_sink=pool_event_sink(event_pool, "pod-1", MODEL),
        feed=engine.feed,
        store_rtt_observer=engine.advisor.observe_store,
    )
    target.register_pool_group(
        group_key,
        block_ids=demo_ids,
        engine_hashes=engine_hashes,
        token_ids=tokens,
        block_size=BLOCK_SIZE,
        now=time.monotonic() - 600,
    )
    worker = engine.start_demotion(
        target,
        DemotionConfig(
            demote_host_idle_s=0.0,
            demote_storage_idle_s=0.0,
            require_prediction=False,
        ),
        start=False,
    )

    # Rung 1: hbm -> host — bytes readable from the host tier.
    assert worker.run_cycle() == 1, "expected the hbm->host move"
    cached = host_cache.get(group_key)
    assert cached is not None, "demotion advertised host without bytes"
    np.testing.assert_array_equal(cached, expected_group)
    event_pool.drain()
    scores = post(
        base, "/score_completions", {"prompt": prompt, "model": MODEL}
    )
    assert abs(scores["pod-1"] - 0.8 * n_blocks) < 1e-9, scores
    print("demotion hbm -> host: bytes in host tier, score 1.0 -> 0.8")

    # Rung 2: host -> shared_storage — bytes readable from the file.
    assert worker.run_cycle() == 1, "expected the host->storage move"
    path = connector.file_mapper.get_file_name(group_key)
    with open(path, "rb") as handle:
        on_disk = np.frombuffer(
            handle.read(), dtype=expected_group.dtype
        ).reshape(expected_group.shape)
    np.testing.assert_array_equal(on_disk, expected_group)
    assert host_cache.get(group_key) is None, "host entry must retire"
    event_pool.drain()
    scores = post(
        base, "/score_completions", {"prompt": prompt, "model": MODEL}
    )
    assert abs(scores["pod-1"] - 0.5 * n_blocks) < 1e-9, scores
    print(
        "demotion host -> shared_storage: bytes on disk, score 0.8 -> 0.5"
    )

    # 4. measured RTT visible in /debug/tiering; gauge on /metrics.
    status = get(base, "/debug/tiering")
    advisor_block = status["advisor"]
    assert advisor_block["rtt"]["observations"] >= 1, advisor_block
    assert advisor_block["rtt"]["per_byte_s"] is not None, advisor_block
    assert advisor_block["rtt_store"]["observations"] >= 2, advisor_block
    demotion_block = status["demotion"][0]
    assert demotion_block["moves"] == 2, demotion_block

    text = get_text(base, "/metrics")
    assert "kvtpu_tiering_writeback_rtt_seconds" in text
    assert "kvtpu_offload_staging_lane_waits_total" in text
    assert 'kvtpu_offload_bytes_total{direction="store"}' in text
    assert 'kvtpu_tiering_demotions_total{transition="host_to_storage"}' in text

    server.shutdown()
    engine.close()
    connector.close()
    event_pool.shutdown()
    indexer.shutdown()
    print("offload smoke completed successfully")


if __name__ == "__main__":
    main()
