"""Read-path perf smoke: a few seconds of the bench's read_path regime.

CI gate (`make perf-smoke`): runs the scoring read path (tokenize ->
hash -> lookup -> score) for real on CPU at tiny geometry and asserts
the regime completes with sane output — every workload cell produced a
positive scores/sec, and the fast-lane parity check passed (identical
scores with READ_PATH_FAST_LANE semantics on vs off).  This is a
smoke/regression gate for the machinery, deliberately NOT a performance
assertion: CI boxes are noisy, so thresholds on absolute numbers would
flake.  See docs/performance.md for the regime and its knobs.
"""

from __future__ import annotations

import json
import os
import sys


def main() -> int:
    # Tiny geometry + CPU platform must be pinned BEFORE bench import
    # (bench.py reads both at module scope).
    os.environ.setdefault("KVTPU_BENCH_TINY", "1")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("KVTPU_BENCH_PLATFORM", "cpu")

    # bench.py lives at the repo root, one level above hack/.
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import bench

    cell_s = float(os.environ.get("PERF_SMOKE_CELL_S", "0.8"))
    result = bench.bench_read_path(cell_seconds=cell_s)
    print(json.dumps(result, indent=2))

    failures = []
    for cell in (
        "warm_multi_turn",
        "warm_multi_turn_no_memo",
        "cold",
        "mixed",
        "warm_multi_turn_fastlane_off",
        "cold_fastlane_off",
    ):
        stats = result.get(cell) or {}
        if not stats.get("scores_per_sec", 0) > 0:
            failures.append(f"{cell}: scores_per_sec not > 0 ({stats})")
        if not stats.get("p50_us", 0) > 0:
            failures.append(f"{cell}: p50_us not > 0 ({stats})")
    if result.get("parity") != "ok":
        failures.append(
            f"fast-lane parity check failed: {result.get('parity')!r}"
        )
    if failures:
        print("PERF SMOKE FAILED:", file=sys.stderr)
        for failure in failures:
            print(f"  - {failure}", file=sys.stderr)
        return 1
    warm = result["warm_multi_turn"]["scores_per_sec"]
    print(
        f"perf smoke ok: warm {warm}/s, parity {result['parity']}",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
