"""Perf-trend gate over the BENCH_r*.json artifact trajectory.

Every bench run the driver keeps lands as ``BENCH_r<NN>.json`` at the
repo root — the project's only longitudinal perf record — but until
this tool nothing READ the trajectory: a PR could halve a headline
the previous bench pinned and no gate would notice.  This script:

* parses every artifact, tolerating the three shapes the trajectory
  actually contains (the early ``parsed`` metric/value/detail form,
  the ``headline``+regime-block form, and the compact-headline form
  ``emit_result`` prints today), plus errored runs (``rc != 0`` or a
  device-unavailable ``error`` field) which are shown but never used
  as a baseline;
* prints a per-regime headline trend table (value per run, newest
  delta vs the most recent prior run that measured the same
  headline);
* exits non-zero when the NEWEST artifact regresses any
  higher-is-better headline by more than ``--threshold`` (default
  10%) against that prior value;
* also folds the ``MULTICHIP_r<NN>.json`` trajectory (the driver's
  virtual-multichip dryrun artifacts, including the PR-11 staged
  offload-lanes cell) into a DISPLAY-ONLY table — pass/fail status,
  device count, and any numeric throughput fields the dryrun grows —
  so the offload-lanes trajectory is visible in ``make perf-trend``
  without gating on it (the dryrun is a compile check, not a perf
  measurement);
* gates the what-if capacity trajectory (``WHATIF_r<NN>.json``,
  written by ``hack/whatif_smoke.py``): the table/threshold treatment
  above, PLUS a LIVE check — when the trajectory is non-empty and the
  pinned reference capture is present, it replays the shards=1 vs
  shards=8 A/B (``obs/whatif.reference_ab``) in-process and fails if
  any deterministic headline (hit rate, recorded-score parity, A/B
  hit parity) fell more than ``--threshold`` below the newest
  artifact.  Unlike the bench numbers these are machine-independent,
  so the live check catches a capacity regression in the PR ITSELF,
  not just between recorded runs.  ``--skip-whatif`` disables the
  live replay (table still shown); ``--reference`` points at a
  different capture.

Regimes rotate between runs, so a headline absent from the newest
artifact is simply not compared — only measured regressions fail.

Run: ``python hack/perf_trend.py`` (CI step "Perf trend", also
``make perf-trend``); ``--dir`` points at a different artifact
directory (tests use a tmpdir).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

DEFAULT_THRESHOLD = 0.10

_ARTIFACT_RE = re.compile(r"BENCH_r(\d+)\.json$")
_MULTICHIP_RE = re.compile(r"MULTICHIP_r(\d+)\.json$")
_WHATIF_RE = re.compile(r"WHATIF_r(\d+)\.json$")

# Default reference capture for the live what-if check (relative to
# the repo root this script lives under).
_WHATIF_REFERENCE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests",
    "testdata",
    "whatif_reference.cbor",
)

# Headline keys gated by the regression check.  All are
# higher-is-better by construction (throughputs, speedups,
# consistency ratios) — latency percentiles and workload-dependent
# hit rates are shown in the table but never gated, because their
# direction or baseline is not stable across regime rotations.
GATED_HEADLINES = (
    "ttft.speedup",
    "read_path.warm_sps",
    "read_path.cold_sps",
    "read_path.mixed_sps",
    "event_storm.apply_sps",
    "event_storm.consistency",
    "replica_scaleout.single_sps",
    "replica_scaleout.cluster3_sps",
    "replica_scaleout.pipelined_sps",
)


def _num(value) -> Optional[float]:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    return float(value)


def _merged_containers(artifact: dict) -> dict:
    """One flat view over the shapes the driver has stored: top-level
    keys, plus whatever sits under ``parsed`` / ``headline`` /
    ``compact`` when those are dicts (later containers never clobber
    earlier keys)."""
    merged: dict = {}
    for container in (
        artifact,
        artifact.get("parsed"),
        artifact.get("headline"),
        artifact.get("compact"),
    ):
        if not isinstance(container, dict):
            continue
        for key, value in container.items():
            merged.setdefault(key, value)
    return merged


def _block(merged: dict, name: str) -> dict:
    """A regime sub-block by name; a ``headline`` whose ``regime``
    field names the block (the BENCH_r06 shape) counts too."""
    candidate = merged.get(name)
    if isinstance(candidate, dict):
        return candidate
    if merged.get("regime") == name:
        return merged
    return {}


def extract_headlines(artifact: dict) -> Dict[str, float]:
    """headline key -> value for one artifact; empty when the run was
    errored (rc != 0, or an ``error`` marker with a zeroed value)."""
    rc = artifact.get("rc", 0)
    if rc not in (0, None):
        return {}
    merged = _merged_containers(artifact)
    out: Dict[str, float] = {}
    errored = "error" in merged or "error" in artifact
    metric = merged.get("metric")
    value = _num(merged.get("value"))
    if (
        not errored
        and isinstance(metric, str)
        and metric.startswith("p50_ttft_speedup")
        and value is not None
        and value > 0
    ):
        out["ttft.speedup"] = value

    read_path = _block(merged, "read_path")
    for key, compact_name, full_path in (
        ("read_path.warm_sps", "warm_sps", ("warm_multi_turn",)),
        ("read_path.cold_sps", "cold_sps", ("cold",)),
        ("read_path.mixed_sps", "mixed_sps", ("mixed",)),
    ):
        value = _num(read_path.get(compact_name))
        if value is None:
            cell = read_path.get(full_path[0])
            if isinstance(cell, dict):
                value = _num(cell.get("scores_per_sec"))
        if value is not None and value > 0:
            out[key] = value

    storm = _block(merged, "event_storm")
    apply_sps = _num(storm.get("apply_sps"))
    if apply_sps is None:
        apply_sps = _num(storm.get("apply_msgs_per_sec"))
    if apply_sps is None:
        cell = storm.get("consolidated_pollers_1")
        if isinstance(cell, dict):
            apply_sps = _num(cell.get("apply_msgs_per_sec"))
    if apply_sps is not None and apply_sps > 0:
        out["event_storm.apply_sps"] = apply_sps
    consistency = _num(storm.get("consistency"))
    if consistency is None:
        gap = storm.get("gap_storm")
        if isinstance(gap, dict):
            consistency = _num(gap.get("post_resync_consistency"))
    if consistency is not None and consistency > 0:
        out["event_storm.consistency"] = consistency

    scaleout = _block(merged, "replica_scaleout")
    for key, compact_name, full_name in (
        ("replica_scaleout.single_sps", "single_sps", "single"),
        (
            "replica_scaleout.cluster3_sps",
            "cluster3_sps",
            "cluster_3_replicas",
        ),
    ):
        value = _num(scaleout.get(compact_name))
        if value is None:
            cell = scaleout.get(full_name)
            if isinstance(cell, dict):
                value = _num(cell.get("scores_per_sec"))
        if value is not None and value > 0:
            out[key] = value
    # Pipelined read-path A/B (RTT-injected 3-replica warm cell):
    # compact carries the terse pipelined_sps; the full artifact nests
    # it under pipelined_ab.pipelined_warm.
    value = _num(scaleout.get("pipelined_sps"))
    if value is None:
        ab = scaleout.get("pipelined_ab")
        if isinstance(ab, dict):
            cell = ab.get("pipelined_warm")
            if isinstance(cell, dict):
                value = _num(cell.get("scores_per_sec"))
    if value is not None and value > 0:
        out["replica_scaleout.pipelined_sps"] = value
    return out


def load_trajectory(
    directory: str,
) -> List[Tuple[int, str, Dict[str, float]]]:
    """[(run number, filename, headlines)] sorted oldest first."""
    runs: List[Tuple[int, str, Dict[str, float]]] = []
    for path in glob.glob(os.path.join(directory, "BENCH_r*.json")):
        match = _ARTIFACT_RE.search(os.path.basename(path))
        if not match:
            continue
        try:
            with open(path) as handle:
                artifact = json.load(handle)
        except (OSError, ValueError) as exc:
            print(f"perf-trend: skipping unreadable {path}: {exc}")
            continue
        if not isinstance(artifact, dict):
            print(f"perf-trend: skipping non-object {path}")
            continue
        runs.append(
            (
                int(match.group(1)),
                os.path.basename(path),
                extract_headlines(artifact),
            )
        )
    runs.sort(key=lambda item: item[0])
    return runs


def extract_multichip(artifact: dict) -> Dict[str, object]:
    """Display-only facts from one MULTICHIP artifact: run status,
    device count, the staged-offload dry-run marker, and any numeric
    throughput fields a future dryrun grows (``*_mb_s`` / ``*_sps`` /
    ``*_gbps`` at any merged container level)."""
    rc = artifact.get("rc", 0)
    if artifact.get("skipped"):
        status = "skipped"
    elif rc not in (0, None):
        status = f"FAIL(rc={rc})"
    else:
        status = "ok"
    out: Dict[str, object] = {"status": status}
    devices = _num(artifact.get("n_devices"))
    if devices is not None:
        out["n_devices"] = int(devices)
    merged = _merged_containers(artifact)
    # Numeric throughput fields at the merged top level OR one regime
    # block down (e.g. a future host_offload lanes cell).
    containers = [merged] + [
        value for value in merged.values() if isinstance(value, dict)
    ]
    for container in containers:
        for key in sorted(container):
            if isinstance(key, str) and key.endswith(
                ("_mb_s", "_sps", "_gbps")
            ):
                value = _num(container[key])
                if value is not None:
                    out.setdefault(key, value)
    tail = artifact.get("tail") or ""
    if "staged offload dry run ok" in str(tail):
        out["staged_offload"] = "ok"
    return out


def load_multichip_trajectory(
    directory: str,
) -> List[Tuple[int, str, Dict[str, object]]]:
    """[(run number, filename, facts)] sorted oldest first."""
    runs: List[Tuple[int, str, Dict[str, object]]] = []
    for path in glob.glob(os.path.join(directory, "MULTICHIP_r*.json")):
        match = _MULTICHIP_RE.search(os.path.basename(path))
        if not match:
            continue
        try:
            with open(path) as handle:
                artifact = json.load(handle)
        except (OSError, ValueError) as exc:
            print(f"perf-trend: skipping unreadable {path}: {exc}")
            continue
        if not isinstance(artifact, dict):
            print(f"perf-trend: skipping non-object {path}")
            continue
        runs.append(
            (
                int(match.group(1)),
                os.path.basename(path),
                extract_multichip(artifact),
            )
        )
    runs.sort(key=lambda item: item[0])
    return runs


def multichip_lines(
    runs: List[Tuple[int, str, Dict[str, object]]],
) -> List[str]:
    """Display-only table for the MULTICHIP trajectory (never gated:
    the dryrun is a compile check whose absolute numbers, when they
    appear, depend on the host)."""
    if not runs:
        return []
    lines = [
        f"perf-trend: multichip trajectory ({len(runs)} artifacts, "
        "display-only, never gated)"
    ]
    for n, _name, facts in runs:
        parts = [str(facts.get("status", "?"))]
        for key, value in facts.items():
            if key == "status":
                continue
            if isinstance(value, float):
                parts.append(f"{key}={value:.3f}")
            else:
                parts.append(f"{key}={value}")
        lines.append(f"  r{n:02d}  " + "  ".join(parts))
    return lines


def extract_whatif(artifact: dict) -> Dict[str, float]:
    """Gated headline values from one WHATIF artifact (the
    ``headlines`` dict ``hack/whatif_smoke.py`` stores — the
    ``obs/whatif.gate_headlines`` output)."""
    if artifact.get("rc", 0) not in (0, None):
        return {}
    headlines = artifact.get("headlines")
    if not isinstance(headlines, dict):
        return {}
    out: Dict[str, float] = {}
    for key, raw in headlines.items():
        value = _num(raw)
        if isinstance(key, str) and value is not None and value > 0:
            out[key] = value
    return out


def load_whatif_trajectory(
    directory: str,
) -> List[Tuple[int, str, Dict[str, float]]]:
    """[(run number, filename, headlines)] sorted oldest first."""
    runs: List[Tuple[int, str, Dict[str, float]]] = []
    for path in glob.glob(os.path.join(directory, "WHATIF_r*.json")):
        match = _WHATIF_RE.search(os.path.basename(path))
        if not match:
            continue
        try:
            with open(path) as handle:
                artifact = json.load(handle)
        except (OSError, ValueError) as exc:
            print(f"perf-trend: skipping unreadable {path}: {exc}")
            continue
        if not isinstance(artifact, dict):
            print(f"perf-trend: skipping non-object {path}")
            continue
        runs.append(
            (
                int(match.group(1)),
                os.path.basename(path),
                extract_whatif(artifact),
            )
        )
    runs.sort(key=lambda item: item[0])
    return runs


def whatif_evaluate(
    runs: List[Tuple[int, str, Dict[str, float]]],
    threshold: float,
    reference: str,
    skip_live: bool,
) -> Tuple[List[str], List[str]]:
    """(table lines, regression messages) for the what-if capacity
    trajectory, including the live reference A/B when available.
    Every ``whatif.*`` headline is higher-is-better and gated — they
    are deterministic measurements of the pinned capture, so any drop
    past the threshold is a real capacity/behavior change, never
    machine noise."""
    lines: List[str] = []
    regressions: List[str] = []
    if not runs:
        return [], []
    newest_n, newest_name, newest = runs[-1]
    keys = sorted({key for _, _, headlines in runs for key in headlines})
    lines.append(
        f"perf-trend: what-if trajectory ({len(runs)} artifacts, "
        f"newest {newest_name}; deterministic headlines, all gated)"
    )
    for key in keys:
        row = [key.ljust(30)]
        prior: Optional[float] = None
        for n, _, headlines in runs:
            value = headlines.get(key)
            row.append(
                f"{value:10.4f}" if value is not None else " " * 9 + "—"
            )
            if n != newest_n and value is not None:
                prior = value
        verdict = ""
        current = newest.get(key)
        if current is not None and prior is not None and prior > 0:
            delta = (current - prior) / prior
            verdict = f"{delta:+.1%}"
            if delta < -threshold:
                verdict += "  REGRESSED"
                regressions.append(
                    f"{key}: {current:.4f} vs prior {prior:.4f} "
                    f"({delta:+.1%} < -{threshold:.0%})"
                )
        elif current is not None:
            verdict = "(no prior)"
        lines.append("  ".join(row) + f"   {verdict}")

    if skip_live:
        lines.append("perf-trend: live what-if check skipped (--skip-whatif)")
        return lines, regressions
    if not os.path.isfile(reference):
        lines.append(
            f"perf-trend: live what-if check skipped (no reference "
            f"capture at {reference})"
        )
        return lines, regressions
    live, error = _live_whatif_headlines(reference)
    if live is None:
        lines.append(
            f"perf-trend: live what-if check unavailable: {error}"
        )
        return lines, regressions
    lines.append(
        "perf-trend: live reference A/B (shards=1 vs shards=8) vs "
        f"{newest_name}:"
    )
    for key in sorted(live):
        current = live[key]
        baseline = newest.get(key)
        verdict = "(no baseline)"
        if baseline is not None and baseline > 0:
            delta = (current - baseline) / baseline
            verdict = f"baseline {baseline:.4f}  {delta:+.1%}"
            if delta < -threshold:
                verdict += "  REGRESSED"
                regressions.append(
                    f"{key} (live): {current:.4f} vs recorded "
                    f"{baseline:.4f} ({delta:+.1%} < -{threshold:.0%})"
                )
        lines.append(f"  {key.ljust(28)} live {current:10.4f}   {verdict}")
    return lines, regressions


def _live_whatif_headlines(
    reference: str,
) -> Tuple[Optional[Dict[str, float]], Optional[str]]:
    """Run the reference A/B in-process; (headlines, None) on success,
    (None, reason) when the stack cannot run here."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    )
    try:
        from llm_d_kv_cache_manager_tpu.obs import whatif as whatif_mod

        ab = whatif_mod.reference_ab(reference)
        return whatif_mod.gate_headlines(ab), None
    except Exception as exc:  # noqa: BLE001 — report, don't crash the gate
        return None, f"{type(exc).__name__}: {exc}"


def evaluate(
    runs: List[Tuple[int, str, Dict[str, float]]],
    threshold: float,
) -> Tuple[List[str], List[str]]:
    """(table lines, regression messages) for a loaded trajectory."""
    lines: List[str] = []
    regressions: List[str] = []
    if not runs:
        return ["perf-trend: no BENCH_r*.json artifacts found"], []
    newest_n, newest_name, newest = runs[-1]
    keys = sorted({key for _, _, headlines in runs for key in headlines})
    lines.append(
        f"perf-trend: {len(runs)} artifacts, newest {newest_name}, "
        f"regression threshold {threshold:.0%}"
    )
    if not keys:
        lines.append(
            "perf-trend: no recognizable headlines in any artifact"
        )
        return lines, []
    header = ["headline".ljust(30)] + [
        f"r{n:02d}".rjust(10) for n, _, _ in runs
    ]
    lines.append("  ".join(header) + "   newest-vs-prior")
    for key in keys:
        row = [key.ljust(30)]
        prior: Optional[float] = None
        for n, _, headlines in runs:
            value = headlines.get(key)
            row.append(
                f"{value:10.3f}" if value is not None else " " * 9 + "—"
            )
            if n != newest_n and value is not None:
                prior = value  # most recent prior measurement wins
        verdict = ""
        current = newest.get(key)
        if current is not None and prior is not None and prior > 0:
            delta = (current - prior) / prior
            verdict = f"{delta:+.1%}"
            if key in GATED_HEADLINES and delta < -threshold:
                verdict += "  REGRESSED"
                regressions.append(
                    f"{key}: {current:.3f} vs prior {prior:.3f} "
                    f"({delta:+.1%} < -{threshold:.0%})"
                )
        elif current is not None:
            verdict = "(no prior)"
        elif prior is not None:
            verdict = "(not in newest run)"
        lines.append("  ".join(row) + f"   {verdict}")
    return lines, regressions


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="BENCH_r*.json headline trend table + >threshold "
        "regression gate (docs/benchmarks.md)"
    )
    parser.add_argument(
        "--dir",
        default=os.path.join(os.path.dirname(__file__), ".."),
        help="directory holding BENCH_r*.json (default: repo root)",
    )
    parser.add_argument(
        "--threshold",
        type=float,
        default=DEFAULT_THRESHOLD,
        help="fractional regression that fails the gate (default 0.10)",
    )
    parser.add_argument(
        "--skip-whatif",
        action="store_true",
        help="skip the live reference what-if A/B (trajectory table "
        "still shown and gated)",
    )
    parser.add_argument(
        "--reference",
        default=_WHATIF_REFERENCE,
        help="reference capture for the live what-if check (default: "
        "tests/testdata/whatif_reference.cbor)",
    )
    args = parser.parse_args(argv)
    runs = load_trajectory(args.dir)
    lines, regressions = evaluate(runs, args.threshold)
    for line in lines:
        print(line)
    for line in multichip_lines(load_multichip_trajectory(args.dir)):
        print(line)
    whatif_lines, whatif_regressions = whatif_evaluate(
        load_whatif_trajectory(args.dir),
        args.threshold,
        args.reference,
        args.skip_whatif,
    )
    for line in whatif_lines:
        print(line)
    regressions.extend(whatif_regressions)
    if regressions:
        print(
            f"perf-trend: FAIL — {len(regressions)} headline(s) "
            "regressed beyond threshold:"
        )
        for message in regressions:
            print(f"  {message}")
        return 1
    print("perf-trend: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
