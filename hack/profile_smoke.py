"""CI smoke gate for the continuous profiling plane.

Boots the HTTP scoring service with the sampling profiler, the gauge
timeline, and lock-contention timing armed, drives real traffic
(scored requests from named load threads + kvevents through the
pool), plants a two-thread lock fight, and asserts the whole plane
closes (docs/observability.md "Continuous profiling"):

* ``GET /debug/`` indexes every debug surface, profile/timeline
  enabled;
* ``GET /debug/profile`` returns collapsed stacks and a top table
  with >= 90% of samples attributed to named ``kvtpu-*`` thread
  roles (the no-anonymous-threads contract);
* the planted lock fight is visible per lock name in
  ``/debug/profile?kind=locks`` AND as ``kvtpu_lock_wait_seconds`` /
  ``kvtpu_lock_contention_total`` on ``/metrics``;
* ``GET /debug/timeline`` shows the traffic ramp (score_requests
  climbs across the window) and live process gauges;
* the off paths are zero-cost: ``PROFILE_HZ=0`` never starts a
  thread, ``LOCK_CONTENTION_SAMPLE=0`` hands back the raw lock
  object.

Run: ``python hack/profile_smoke.py`` (CI step "Profiling smoke",
``make profile-smoke``).  Prints "profiling smoke completed
successfully" on success; any assertion exits non-zero.
"""

import json
import os
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TOKENIZERS_PARALLELISM", "false")
# Before any package import: lockorder and the profiler read these at
# import/construction time.
os.environ["LOCK_CONTENTION_SAMPLE"] = "1"
os.environ["PROFILE_HZ"] = "80"
os.environ["TIMELINE_WINDOW_S"] = "120"
os.environ.setdefault("TRACE_SAMPLE_RATE", "0.05")

from llm_d_kv_cache_manager_tpu.api.http_service import serve  # noqa: E402
from llm_d_kv_cache_manager_tpu.kvcache.indexer import (  # noqa: E402
    Indexer,
    IndexerConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (  # noqa: E402,E501
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (  # noqa: E402
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (  # noqa: E402
    Message,
    Pool,
    PoolConfig,
)
from llm_d_kv_cache_manager_tpu.metrics.collector import (  # noqa: E402
    install_gc_metrics,
)
from llm_d_kv_cache_manager_tpu.obs.profiler import (  # noqa: E402
    ProfilerConfig,
    SamplingProfiler,
)
from llm_d_kv_cache_manager_tpu.obs.timeline import (  # noqa: E402
    GaugeTimeline,
    register_default_series,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (  # noqa: E402
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (  # noqa: E402,E501
    LocalFastTokenizer,
)
from llm_d_kv_cache_manager_tpu.utils import lockorder  # noqa: E402
from tests.helpers.tiny_tokenizer import save_tokenizer_json  # noqa: E402

MODEL = "test-model"
BLOCK_SIZE = 4
PROMPT = "the quick brown fox jumps over the lazy dog . " * 8
TRAFFIC_SECONDS = 4.0
LOAD_THREADS = 4
ATTRIBUTION_FLOOR = 0.90
FIGHT_LOCK_NAME = "ProfileSmoke._fight_lock"


def post(base, path, obj):
    request = urllib.request.Request(
        base + path,
        data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(request, timeout=30) as response:
        return json.load(response)


def get(base, path, as_text=False):
    with urllib.request.urlopen(base + path, timeout=30) as response:
        if as_text:
            return response.read().decode()
        return json.load(response)


def main() -> None:
    assert lockorder.contention_sample() == 1

    tokenizer_dir = save_tokenizer_json(tempfile.mkdtemp(), MODEL)
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=2, model_name=MODEL
            ),
        ),
        tokenizer=LocalFastTokenizer(tokenizer_dir),
    )
    indexer.run()
    event_pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(concurrency=2),
    )
    event_pool.start()

    install_gc_metrics()
    profiler = SamplingProfiler()  # ProfilerConfig.from_env: 80 Hz
    assert profiler.start(), "PROFILE_HZ=80 must start the sampler"
    timeline = GaugeTimeline()
    register_default_series(timeline, pool=event_pool)
    assert timeline.start()

    server = serve(
        indexer,
        host="127.0.0.1",
        port=0,
        profiler=profiler,
        timeline=timeline,
    )
    base = f"http://127.0.0.1:{server.server_address[1]}"

    # Seed the index so scoring does real lookup work.
    tokens = indexer.tokenization_pool.tokenize(PROMPT, MODEL, None)
    n_blocks = len(tokens) // BLOCK_SIZE
    batch = EventBatch(
        ts=1.0,
        events=[
            BlockStored(
                block_hashes=list(range(0x100, 0x100 + n_blocks // 2)),
                parent_block_hash=None,
                token_ids=tokens[: (n_blocks // 2) * BLOCK_SIZE],
                block_size=BLOCK_SIZE,
                medium="hbm",
            )
        ],
    )

    # A clean pre-traffic timeline slot, so the ramp is observable.
    time.sleep(1.5)

    # -- drive traffic + the planted lock fight ------------------------
    stop = threading.Event()
    errors: list = []

    def load_loop() -> None:
        while not stop.is_set():
            try:
                post(
                    base,
                    "/score_completions",
                    {"prompt": PROMPT, "model": MODEL},
                )
            except Exception as exc:  # noqa: BLE001 — fail via errors
                errors.append(repr(exc))
                return

    def events_loop() -> None:
        seq = 0
        while not stop.is_set():
            event_pool.add_task(
                Message(
                    topic=f"kv@pod-1@{MODEL}",
                    payload=batch.encode(),
                    pod_identifier="pod-1",
                    model_name=MODEL,
                    seq=seq,
                )
            )
            seq += 1
            time.sleep(0.005)

    fight_lock = lockorder.tracked(threading.Lock(), FIGHT_LOCK_NAME)
    assert type(fight_lock).__name__ == "ContentionTimedLock", (
        "LOCK_CONTENTION_SAMPLE=1 must wrap tracked locks"
    )

    def fight_loop() -> None:
        while not stop.is_set():
            with fight_lock:
                time.sleep(0.002)

    threads = [
        threading.Thread(
            target=load_loop, name=f"kvtpu-smoke-load-{i}", daemon=True
        )
        for i in range(LOAD_THREADS)
    ]
    threads.append(
        threading.Thread(
            target=events_loop, name="kvtpu-smoke-events", daemon=True
        )
    )
    threads.extend(
        threading.Thread(
            target=fight_loop, name=f"kvtpu-smoke-fight-{i}", daemon=True
        )
        for i in range(2)
    )
    for thread in threads:
        thread.start()
    time.sleep(TRAFFIC_SECONDS)
    stop.set()
    for thread in threads:
        thread.join(timeout=10)
    assert not errors, errors[:3]

    # 1. /debug/ index lists the new surfaces as enabled.
    index = get(base, "/debug/")
    by_path = {s["path"]: s for s in index["surfaces"]}
    assert by_path["/debug/profile"]["enabled"], by_path
    assert by_path["/debug/timeline"]["enabled"], by_path
    assert "/metrics" in index["also"], index

    # 2. Profiler: samples flowed and attribute to named roles.
    profile = get(base, "/debug/profile?top=50")
    assert profile["running"], profile
    assert profile["samples"] > 100, profile["samples"]
    assert profile["attributed_fraction"] >= ATTRIBUTION_FLOOR, (
        f"only {profile['attributed_fraction']:.1%} of samples "
        f"attributed to kvtpu-* roles; roles={profile['roles']}"
    )
    roles = profile["roles"]
    assert "smoke-load" in roles and "smoke-fight" in roles, roles
    assert any(
        role.startswith("http") for role in roles
    ), roles  # service + handler threads carry kvtpu-http-* names
    collapsed = get(base, "/debug/profile?kind=stacks", as_text=True)
    lines = [line for line in collapsed.splitlines() if line]
    assert lines and all(
        line.rsplit(" ", 1)[1].isdigit() for line in lines
    ), lines[:3]
    assert any(line.startswith("smoke-fight;") for line in lines), (
        lines[:5]
    )

    # 3. The planted lock fight is visible per lock name.
    locks = get(base, "/debug/profile?kind=locks")
    assert locks["sample"] == 1, locks
    fight = locks["locks"].get(FIGHT_LOCK_NAME)
    assert fight and fight["contended"] > 0, locks["locks"].keys()
    assert fight["wait_ewma_us"] > 0, fight
    exposition = get(base, "/metrics", as_text=True)
    assert (
        f'kvtpu_lock_contention_total{{lock="{FIGHT_LOCK_NAME}"}}'
        in exposition
    ), "lock contention counter missing from /metrics"
    assert f'lock="{FIGHT_LOCK_NAME}"' in exposition
    assert "kvtpu_lock_wait_seconds_bucket" in exposition
    assert "kvtpu_process_rss_bytes" in exposition

    # 4. Timeline: the traffic ramp is walk-backable.
    ramp = get(base, "/debug/timeline?series=score_requests_total")
    points = ramp["series"]["score_requests_total"]["points"]
    assert len(points) >= 3, points
    values = [value for _, value in points if value is not None]
    assert values[-1] > values[0] >= 0, values
    full_timeline = get(base, "/debug/timeline")
    assert "process_rss_bytes" in full_timeline["series"]
    rss = [
        value
        for _, value in full_timeline["series"]["process_rss_bytes"][
            "points"
        ]
        if value is not None
    ]
    assert rss and rss[-1] > 0, rss[-5:]

    # 5. Off paths are zero-cost.
    inert = SamplingProfiler(ProfilerConfig(hz=0))
    assert inert.start() is False and not inert.running()
    previous = lockorder.set_contention_sample(0)
    try:
        raw = threading.Lock()
        assert lockorder.tracked(raw, "ProfileSmoke._off") is raw, (
            "LOCK_CONTENTION_SAMPLE=0 must hand back the raw lock"
        )
    finally:
        lockorder.set_contention_sample(previous)

    timeline.close()
    profiler.close()
    server.shutdown()
    event_pool.shutdown()
    indexer.shutdown()
    print("profiling smoke completed successfully")


if __name__ == "__main__":
    main()
