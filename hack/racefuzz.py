#!/usr/bin/env python3
"""Preemption fuzzer: storms re-run under raceguard with hostile
scheduling.

The concurrency storms (TestBackendStorm, TestShardedIndexStorm, the
write-path and event-plane storms) normally run with CPython's default
5 ms switch interval, which hides narrow race windows: a thread that
reads a guarded value and writes it back two bytecodes later almost
never gets preempted in between.  This harness re-runs them with

* ``KVTPU_RACEGUARD=1`` semantics (guarded-by runtime enforcement,
  installed in-process from the kvlint manifest),
* ``sys.setswitchinterval(1e-6)`` — preemption every ~microsecond,
* seeded yield injection at guarded-access and lock-acquire
  boundaries: the raceguard descriptors and every lockorder wrapper
  fire the fuzz hook registered via ``lockorder.set_fuzz_hook``, and
  the hook — driven by a per-thread ``random.Random`` derived from
  ``--seed`` — sleeps at a seeded subset of those boundaries, forcing
  the interleavings the default scheduler never explores.

Python 3.10 has no ``sys.monitoring`` (3.12+), so the injection points
are the instrumentation boundaries themselves rather than per-opcode
callbacks; every guarded read/write and every lock acquire is a
boundary, which is exactly where check-then-act windows live.

Failures report the seed and BOTH thread stacks (raceguard violations
embed them already; planted lost-update collisions capture them via
``sys._current_frames`` at overlap time), so
``python -m hack.racefuzz --seed N`` deterministically replays a
reported failure.

Planted defects (``--plant``) prove the harness can see what it claims
to see:

* ``guarded-write``  — a thread writes a guarded attr lockless;
  raceguard must raise.
* ``caller-locked``  — a method statically claims
  ``# kvlint: caller-locked`` but a caller invokes it without the
  lock; the runtime check must catch the false claim kvlint phase 1
  trusted.
* ``check-then-act`` — the KV009 shape at runtime: read under one
  acquisition feeds a write under a second one; two threads must lose
  an update, and the harness reports the overlapping stacks.

Exit codes: 0 = no race found (storm mode) / plant reproduced (plant
mode, which is the *expected* outcome); 1 = race found (storm mode) /
plant NOT reproduced; 2 = usage.
"""

from __future__ import annotations

import argparse
import os
import random
import sys
import threading
import time
import traceback
from typing import List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

DEFAULT_STORMS = [
    "tests/test_concurrency.py::TestBackendStorm",
    "tests/test_concurrency.py::TestShardedIndexStorm",
    "tests/test_concurrency.py::TestScoreMemoStorm",
    "tests/test_concurrency.py::TestClusterFanoutStorm",
    "tests/test_concurrency.py",
    "tests/test_kvevents_fuzz.py::TestPoolSurvivesStorm",
]

# Yield probability per fuzz boundary.  High enough to shuffle
# interleavings hard, low enough that a storm still finishes inside a
# CI smoke budget.
YIELD_RATE = 0.15


class _SeededYielder:
    """Fuzz hook: per-thread deterministic RNG, seeded yields.

    Each thread draws from ``Random(seed ^ arrival_index)`` so the
    yield pattern a thread sees depends only on the seed and the order
    threads first hit a boundary — replaying a seed replays the
    per-thread decision streams.
    """

    def __init__(self, seed: int, yield_rate: float = YIELD_RATE) -> None:
        self.seed = seed
        self.yield_rate = yield_rate
        self.boundaries = 0  # lone-advance statistic, races tolerated
        self.yields = 0
        self._local = threading.local()
        self._index_lock = threading.Lock()
        self._next_index = 0

    def _rng(self) -> random.Random:
        rng = getattr(self._local, "rng", None)
        if rng is None:
            with self._index_lock:
                index = self._next_index
                self._next_index += 1
            rng = self._local.rng = random.Random(self.seed ^ index)
        return rng

    def __call__(self, kind: str, name: str) -> None:
        self.boundaries += 1
        rng = self._rng()
        roll = rng.random()
        if roll < self.yield_rate:
            self.yields += 1
            # Mix zero-length yields (run queue rotation) with short
            # sleeps (force another thread deep into the window).
            if roll < self.yield_rate / 3:
                time.sleep(rng.uniform(1e-6, 5e-5))
            else:
                time.sleep(0)


def _arm(seed: int):
    from llm_d_kv_cache_manager_tpu.utils import lockorder, raceguard

    raceguard.install_from_env() if raceguard.armed_from_env() else None
    if not raceguard.installed():
        raceguard.install()
    lockorder.set_guard_recording(True)
    hook = _SeededYielder(seed)
    lockorder.set_fuzz_hook(hook)
    sys.setswitchinterval(1e-6)
    return hook


def _disarm() -> None:
    from llm_d_kv_cache_manager_tpu.utils import lockorder

    sys.setswitchinterval(0.005)
    lockorder.set_fuzz_hook(None)


# --------------------------- planted defects ---------------------------


class _PlantReport:
    def __init__(self) -> None:
        self.reproduced = False
        self.detail = ""
        self.stacks: List[str] = []


def _plant_guarded_write(seed: int, report: _PlantReport) -> None:
    """A guarded attr written without its lock: raceguard must raise
    on the very first write, no scheduling luck required."""
    from llm_d_kv_cache_manager_tpu.utils import raceguard

    class PlantedGuardedWrite:
        def __init__(self) -> None:
            self._lock = threading.Lock()
            self._value = 0  # guarded-by: _lock

        def buggy_write(self, value: int) -> None:
            self._value = value  # missing `with self._lock:`

    raceguard.guard_class(PlantedGuardedWrite, {"_value": "_lock"})
    obj = PlantedGuardedWrite()
    try:
        obj.buggy_write(7)
    except raceguard.RaceGuardViolation as exc:
        report.reproduced = True
        report.detail = str(exc).splitlines()[0]
        report.stacks = [str(exc)]


def _plant_caller_locked(seed: int, report: _PlantReport) -> None:
    """A method statically annotated caller-locked (kvlint phase 1
    trusts the claim and skips it) called WITHOUT the lock — the
    runtime check catches the lie."""
    from llm_d_kv_cache_manager_tpu.utils import raceguard

    class PlantedCallerLocked:
        def __init__(self) -> None:
            self._lock = threading.Lock()
            self._items: List[int] = []  # guarded-by: _lock

        def _append_locked(self, item: int) -> None:  # kvlint: caller-locked
            self._items.append(item)

        def honest_caller(self, item: int) -> None:
            with self._lock:
                self._append_locked(item)

        def lying_caller(self, item: int) -> None:
            self._append_locked(item)  # claim is false: no lock held

    raceguard.guard_class(PlantedCallerLocked, {"_items": "_lock"})
    obj = PlantedCallerLocked()
    obj.honest_caller(1)  # must pass: claim honoured
    try:
        obj.lying_caller(2)
    except raceguard.RaceGuardViolation as exc:
        report.reproduced = True
        report.detail = str(exc).splitlines()[0]
        report.stacks = [str(exc)]


def _plant_check_then_act(seed: int, report: _PlantReport) -> None:
    """The KV009 shape, live: read under one acquisition feeds a write
    under a second acquisition of the same lock.  Every access holds
    the lock, so raceguard stays silent — the fuzzer has to surface it
    as a lost update, and reports the two overlapping thread stacks
    captured the moment both threads sat inside the gap."""
    threads = 2
    increments = 400

    gap_lock = threading.Lock()
    in_gap: dict = {}  # thread ident -> True while inside the window

    class PlantedCounter:
        def __init__(self) -> None:
            self._lock = threading.Lock()
            self._value = 0  # guarded-by: _lock

        def buggy_increment(self) -> None:
            with self._lock:
                current = self._value
            me = threading.get_ident()
            with gap_lock:
                in_gap[me] = True
                others = [t for t in in_gap if t != me]
                if others and not report.stacks:
                    frames = sys._current_frames()
                    for ident in (me, others[0]):
                        frame = frames.get(ident)
                        if frame is not None:
                            report.stacks.append(
                                f"thread {ident}:\n"
                                + "".join(traceback.format_stack(frame))
                            )
            try:
                time.sleep(0)  # the gap the fuzz scheduling widens
                with self._lock:
                    self._value = current + 1
            finally:
                with gap_lock:
                    in_gap.pop(me, None)

    from llm_d_kv_cache_manager_tpu.utils import raceguard

    raceguard.guard_class(PlantedCounter, {"_value": "_lock"})
    counter = PlantedCounter()

    def worker() -> None:
        for _ in range(increments):
            counter.buggy_increment()

    pool = [
        threading.Thread(target=worker, name=f"racefuzz-{i}")
        for i in range(threads)
    ]
    for thread in pool:
        thread.start()
    for thread in pool:
        thread.join()

    expected = threads * increments
    with counter._lock:
        final = counter._value
    if final < expected:
        report.reproduced = True
        report.detail = (
            f"lost update: {expected - final} of {expected} increments "
            f"vanished (final={final}) — read and write sit in separate "
            f"acquisitions of the same lock"
        )


_PLANTS = {
    "guarded-write": _plant_guarded_write,
    "caller-locked": _plant_caller_locked,
    "check-then-act": _plant_check_then_act,
}


def _run_plant(kind: str, seed: int) -> int:
    hook = _arm(seed)
    report = _PlantReport()
    try:
        _PLANTS[kind](seed, report)
    finally:
        _disarm()
    print(
        f"racefuzz: plant={kind} seed={seed} "
        f"boundaries={hook.boundaries} yields={hook.yields}"
    )
    if report.reproduced:
        print(f"racefuzz: REPRODUCED: {report.detail}")
        for stack in report.stacks:
            print(stack)
        return 0
    print(f"racefuzz: plant '{kind}' NOT reproduced under seed {seed}")
    return 1


# ----------------------------- storm mode ------------------------------


def _run_storms(
    storms: List[str], seed: int, time_budget_s: Optional[float]
) -> int:
    import pytest

    hook = _arm(seed)
    deadline = (
        time.monotonic() + time_budget_s if time_budget_s else None
    )
    failed: List[str] = []
    try:
        for node in storms:
            if deadline is not None and time.monotonic() >= deadline:
                print(
                    f"racefuzz: time budget exhausted before {node!r}",
                    flush=True,
                )
                break
            print(f"racefuzz: seed={seed} storm={node}", flush=True)
            code = pytest.main(
                [
                    node,
                    "-q",
                    "-x",
                    "-p",
                    "no:cacheprovider",
                    "-p",
                    "no:randomly",
                ]
            )
            if code != 0:
                failed.append(node)
    finally:
        _disarm()
    print(
        f"racefuzz: seed={seed} boundaries={hook.boundaries} "
        f"yields={hook.yields} failed={len(failed)}"
    )
    if failed:
        print(
            f"racefuzz: RACE (or storm failure) under seed {seed}: "
            + ", ".join(failed)
        )
        print(
            f"racefuzz: replay with `python -m hack.racefuzz "
            f"--seed {seed} --storms {' '.join(failed)}` — raceguard "
            f"violations above carry both thread stacks"
        )
        return 1
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="racefuzz",
        description=(
            "re-run concurrency storms under raceguard with "
            "microsecond preemption and seeded yield injection"
        ),
    )
    parser.add_argument(
        "--seed",
        type=int,
        default=None,
        help="fuzz seed (default: derived from time; always printed)",
    )
    parser.add_argument(
        "--storms",
        nargs="+",
        default=None,
        metavar="NODE",
        help="pytest node ids to storm (default: the known storms)",
    )
    parser.add_argument(
        "--plant",
        choices=sorted(_PLANTS),
        default=None,
        help="run a planted defect instead of the storms; exit 0 iff "
        "the harness reproduces it",
    )
    parser.add_argument(
        "--time-budget",
        type=float,
        default=None,
        metavar="SECONDS",
        help="stop starting new storms after this budget (CI smoke)",
    )
    args = parser.parse_args(argv)

    seed = args.seed
    if seed is None:
        seed = int.from_bytes(os.urandom(4), "big")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    if args.plant is not None:
        return _run_plant(args.plant, seed)
    storms = args.storms or DEFAULT_STORMS
    return _run_storms(storms, seed, args.time_budget)


if __name__ == "__main__":
    sys.exit(main())
