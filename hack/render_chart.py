#!/usr/bin/env python3
"""Render the deploy/chart Helm chart without helm.

A deliberate *subset* of Helm's template language — enough for this
chart, no more: ``{{ .Values.* }}`` / ``.Release.*`` / ``.Chart.*``
paths, ``if`` / ``else if`` / ``else`` / ``end``, ``define`` /
``include``, and the pipeline functions ``quote squote lower upper
default toYaml nindent indent trim printf eq ne and or not int``.
The chart's templates are written to stay inside this subset, so the
same sources render identically under real ``helm template`` (use that
in clusters where helm is available) and under this script (CI here has
no helm binary; tests render through this and assert the fleet's
cross-invariants on the parsed output).

Usage:
    python hack/render_chart.py deploy/chart [--set a.b.c=value ...] \
        [--release NAME] [--namespace NS]

Prints a multi-document YAML stream, like ``helm template``.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

try:
    import yaml
except ImportError as exc:  # pragma: no cover
    raise SystemExit("render_chart.py needs PyYAML") from exc

_TOKEN = re.compile(r"\{\{-?\s*(.*?)\s*-?\}\}", re.DOTALL)


# --- template parsing ------------------------------------------------------


class Node:
    pass


class Text(Node):
    def __init__(self, text: str) -> None:
        self.text = text


class Action(Node):
    def __init__(self, expr: str) -> None:
        self.expr = expr


class If(Node):
    def __init__(self) -> None:
        # [(condition-expr or None for else, body nodes)]
        self.branches: List[Tuple[Optional[str], List[Node]]] = []


class Define(Node):
    def __init__(self, name: str, body: List[Node]) -> None:
        self.name = name
        self.body = body


def _lex(source: str) -> List[Tuple[str, str]]:
    """Split into ('text', s) and ('action', expr) tokens, applying
    {{- / -}} whitespace trimming to the neighboring text."""
    tokens: List[Tuple[str, str]] = []
    pos = 0
    for match in _TOKEN.finditer(source):
        text = source[pos : match.start()]
        raw = match.group(0)
        if raw.startswith("{{-"):
            text = text.rstrip(" \t")
            if text.endswith("\n"):
                text = text[:-1]
        tokens.append(("text", text))
        tokens.append(("action", match.group(1).strip()))
        pos = match.end()
        if raw.endswith("-}}"):
            rest = source[pos:]
            stripped = rest.lstrip(" \t")
            if stripped.startswith("\n"):
                pos += len(rest) - len(stripped) + 1
            else:
                pos += len(rest) - len(stripped)
    tokens.append(("text", source[pos:]))
    return tokens


def _parse(tokens: List[Tuple[str, str]]) -> List[Node]:
    root: List[Node] = []
    # stack of (list-to-append-to, open If node or Define marker)
    stack: List[Tuple[List[Node], Optional[Node]]] = [(root, None)]

    for kind, value in tokens:
        target = stack[-1][0]
        if kind == "text":
            if value:
                target.append(Text(value))
            continue
        expr = value
        if expr.startswith("/*") or expr.startswith("#"):
            continue  # comment
        if expr.startswith("define "):
            name = expr[len("define ") :].strip().strip('"')
            body: List[Node] = []
            node = Define(name, body)
            stack[-1][0].append(node)
            stack.append((body, node))
        elif expr.startswith("if "):
            node = If()
            body = []
            node.branches.append((expr[3:].strip(), body))
            stack[-1][0].append(node)
            stack.append((body, node))
        elif expr.startswith("else if "):
            body = []
            _, open_node = stack.pop()
            if not isinstance(open_node, If):
                raise ValueError("'else if' outside if")
            open_node.branches.append((expr[len("else if ") :].strip(), body))
            stack.append((body, open_node))
        elif expr == "else":
            body = []
            _, open_node = stack.pop()
            if not isinstance(open_node, If):
                raise ValueError("'else' outside if")
            open_node.branches.append((None, body))
            stack.append((body, open_node))
        elif expr == "end":
            stack.pop()
            if not stack:
                raise ValueError("unbalanced 'end'")
        else:
            target.append(Action(expr))
    if len(stack) != 1:
        raise ValueError("unclosed block in template")
    return root


# --- expression evaluation -------------------------------------------------

_SPLIT_ARGS = re.compile(r'"(?:[^"\\]|\\.)*"|\S+')


def _truthy(value: Any) -> bool:
    if value is None or value is False:
        return False
    if isinstance(value, (int, float)) and value == 0 and value is not True:
        return False
    if isinstance(value, (str, list, dict, tuple)) and len(value) == 0:
        return False
    return True


def _to_yaml(value: Any) -> str:
    if value is None:
        return ""
    out = yaml.safe_dump(value, default_flow_style=False, sort_keys=False)
    return out.rstrip("\n")


class Renderer:
    def __init__(self, context: Dict[str, Any]) -> None:
        self.context = context
        self.defines: Dict[str, List[Node]] = {}

    # -- value resolution --

    def _resolve_path(self, path: str) -> Any:
        node: Any = self.context
        for part in path.lstrip(".").split("."):
            if not part:
                continue
            if isinstance(node, dict):
                node = node.get(part)
            else:
                node = getattr(node, part, None)
            if node is None:
                return None
        return node

    def _atom(self, token: str) -> Any:
        if token.startswith('"'):
            return token[1:-1].encode().decode("unicode_escape")
        if token == ".":
            return self.context
        if token.startswith("."):
            return self._resolve_path(token)
        if token in ("true", "false"):
            return token == "true"
        if token in ("nil", "null"):
            return None
        try:
            return int(token)
        except ValueError:
            pass
        try:
            return float(token)
        except ValueError:
            pass
        raise ValueError(f"cannot evaluate template atom: {token!r}")

    def _call(self, name: str, args: List[Any]) -> Any:
        if name == "quote":
            return '"' + str(args[0]).replace('"', '\\"') + '"'
        if name == "squote":
            return "'" + str(args[0]) + "'"
        if name == "lower":
            return str(args[0]).lower()
        if name == "upper":
            return str(args[0]).upper()
        if name == "trim":
            return str(args[0]).strip()
        if name == "int":
            return int(float(args[0]))
        if name == "default":
            return args[1] if _truthy(args[1]) else args[0]
        if name == "toYaml":
            return _to_yaml(args[0])
        if name == "indent":
            pad = " " * int(args[0])
            return "\n".join(pad + line for line in str(args[1]).split("\n"))
        if name == "nindent":
            return "\n" + self._call("indent", args)
        if name == "printf":
            fmt = str(args[0]).replace("%v", "%s").replace("%d", "%s")
            return fmt % tuple(str(a) for a in args[1:])
        if name == "eq":
            return all(a == args[0] for a in args[1:])
        if name == "ne":
            return args[0] != args[1]
        if name == "gt":
            return args[0] > args[1]
        if name == "ge":
            return args[0] >= args[1]
        if name == "lt":
            return args[0] < args[1]
        if name == "le":
            return args[0] <= args[1]
        if name == "and":
            result: Any = True
            for arg in args:
                result = arg
                if not _truthy(arg):
                    return arg
            return result
        if name == "or":
            for arg in args:
                if _truthy(arg):
                    return arg
            return args[-1] if args else None
        if name == "not":
            return not _truthy(args[0])
        if name == "fail":
            raise ValueError(f"chart validation failed: {args[0]}")
        if name == "include":
            body = self.defines.get(str(args[0]))
            if body is None:
                raise ValueError(f"include of unknown define {args[0]!r}")
            return self.render_nodes(body)
        raise ValueError(f"unsupported template function: {name}")

    _FUNCTIONS = {
        "quote", "squote", "lower", "upper", "trim", "int", "default",
        "toYaml", "indent", "nindent", "printf", "eq", "ne", "gt",
        "ge", "lt", "le", "and", "or", "not", "include", "fail",
    }

    def _command(self, tokens: List[str], piped: Optional[Any]) -> Any:
        head = tokens[0]
        if head in self._FUNCTIONS:
            args = [self._atom(t) for t in tokens[1:]]
            if piped is not None or (not args and head != "include"):
                args.append(piped)
            return self._call(head, args)
        if len(tokens) != 1 or piped is not None:
            raise ValueError(f"cannot evaluate: {' '.join(tokens)}")
        return self._atom(head)

    def evaluate(self, expr: str) -> Any:
        piped: Optional[Any] = None
        for i, segment in enumerate(expr.split("|")):
            tokens = _SPLIT_ARGS.findall(segment.strip())
            if not tokens:
                raise ValueError(f"empty pipeline segment in {expr!r}")
            piped = self._command(tokens, piped if i > 0 else None)
        return piped

    # -- rendering --

    def collect_defines(self, nodes: List[Node]) -> None:
        for node in nodes:
            if isinstance(node, Define):
                self.defines[node.name] = node.body

    def render_nodes(self, nodes: List[Node]) -> str:
        out: List[str] = []
        for node in nodes:
            if isinstance(node, Text):
                out.append(node.text)
            elif isinstance(node, Define):
                continue
            elif isinstance(node, If):
                for condition, body in node.branches:
                    if condition is None or _truthy(
                        self.evaluate(condition)
                    ):
                        out.append(self.render_nodes(body))
                        break
            elif isinstance(node, Action):
                value = self.evaluate(node.expr)
                if value is True:
                    out.append("true")
                elif value is False:
                    out.append("false")
                elif value is not None:
                    out.append(str(value))
        return "".join(out)


# --- chart assembly --------------------------------------------------------


def _set_path(values: dict, dotted: str, raw: str) -> None:
    node = values
    parts = dotted.split(".")
    for part in parts[:-1]:
        node = node.setdefault(part, {})
    try:
        parsed = yaml.safe_load(raw)
    except yaml.YAMLError:
        parsed = raw
    node[parts[-1]] = parsed


def render_chart(
    chart_dir: str,
    release_name: str = "kvtpu",
    namespace: Optional[str] = None,
    set_values: Optional[Dict[str, str]] = None,
) -> str:
    """Render every template in the chart; returns one multi-doc YAML
    string (empty documents dropped, like ``helm template``)."""
    with open(os.path.join(chart_dir, "Chart.yaml")) as f:
        chart_meta = yaml.safe_load(f)
    with open(os.path.join(chart_dir, "values.yaml")) as f:
        values = yaml.safe_load(f) or {}
    for dotted, raw in (set_values or {}).items():
        _set_path(values, dotted, raw)

    context = {
        "Values": values,
        "Release": {
            # Same default as real helm without -n, so both renderers
            # produce identical namespaces from the same sources.
            "Name": release_name,
            "Namespace": namespace or "default",
            "Service": "Helm",
        },
        "Chart": {
            "Name": chart_meta.get("name", "chart"),
            "Version": chart_meta.get("version", "0"),
            "AppVersion": chart_meta.get("appVersion", ""),
        },
    }
    renderer = Renderer(context)

    template_dir = os.path.join(chart_dir, "templates")
    names = sorted(os.listdir(template_dir))
    for name in names:  # defines first, from every file
        if name.endswith((".tpl", ".yaml")):
            with open(os.path.join(template_dir, name)) as f:
                renderer.collect_defines(_parse(_lex(f.read())))

    documents: List[str] = []
    for name in names:
        if not name.endswith(".yaml"):
            continue
        with open(os.path.join(template_dir, name)) as f:
            rendered = renderer.render_nodes(_parse(_lex(f.read())))
        for doc in rendered.split("\n---"):
            if yaml.safe_load(doc) is not None:
                documents.append(doc.strip("\n"))
    return "\n---\n".join(documents) + "\n"


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("chart_dir")
    parser.add_argument("--release", default="kvtpu")
    parser.add_argument("--namespace", default=None)
    parser.add_argument(
        "--set",
        dest="sets",
        action="append",
        default=[],
        metavar="a.b.c=value",
    )
    args = parser.parse_args()
    set_values = {}
    for item in args.sets:
        key, _, value = item.partition("=")
        set_values[key] = value
    sys.stdout.write(
        render_chart(
            args.chart_dir,
            release_name=args.release,
            namespace=args.namespace,
            set_values=set_values,
        )
    )


if __name__ == "__main__":
    main()
