"""CI smoke gate for the incident capture & replay plane (ISSUE 15).

Boots the service stack in-process — indexer + kvevents pool with the
input flight recorder attached, SLO engine with the incident bundler
subscribed, HTTP service — and asserts the whole loop closes:

* **Capture under traffic**: event-plane messages and scored requests
  land in the recorder (ring occupancy visible at
  ``GET /debug/incidents`` and ``/healthz``), and ``kvtpu_build_info``
  + the capture families are on ``/metrics``.
* **SLO-triggered bundle**: forcing a registered SLI past its
  declared bound flips the envelope healthy→violated and the
  transition listener writes one incident bundle containing
  ``capture.cbor`` + traces + profile + timeline + slo + the config
  fingerprint, listed at ``/debug/incidents``.
* **Replay to bit-identical**: the bundle's capture replays through a
  FRESH stack (``obs/replay.py``) with ZERO divergence — every
  recorded score reproduced exactly, seq classifications match, and
  the final index state equals the recorded canonical state.
* **Replay to divergence**: a deliberately mutated capture (one score
  bit-flipped) reports a first-divergence point naming the record.
* **Manual trigger**: ``POST /admin/incident`` forces a second bundle
  past the rate limit.

Run: ``python hack/replay_smoke.py`` (CI step "Replay smoke",
``make replay-smoke``).  Prints "replay smoke completed successfully"
on success; any assertion exits non-zero.
"""

import copy
import json
import os
import shutil
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TOKENIZERS_PARALLELISM", "false")

from llm_d_kv_cache_manager_tpu.api.http_service import serve  # noqa: E402
from llm_d_kv_cache_manager_tpu.kvcache.indexer import (  # noqa: E402
    Indexer,
    IndexerConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (  # noqa: E402,E501
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (  # noqa: E402
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (  # noqa: E402
    Message,
    Pool,
    PoolConfig,
)
from llm_d_kv_cache_manager_tpu.obs.capture import (  # noqa: E402
    CaptureConfig,
    IncidentManager,
    InputCaptureRecorder,
    set_build_info_metric,
)
from llm_d_kv_cache_manager_tpu.obs.replay import (  # noqa: E402
    load_capture,
    replay_capture,
)
from llm_d_kv_cache_manager_tpu.obs.slo import (  # noqa: E402
    SloEngine,
    SloSpec,
)
from llm_d_kv_cache_manager_tpu.obs.trace import TRACER  # noqa: E402
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (  # noqa: E402
    Encoding,
)

MODEL = "replay-model"
BLOCK_SIZE = 4


class WordTokenizer:
    def type(self):
        return "smoke-word"

    def encode(self, prompt, model_name, add_special_tokens):
        tokens, offsets, pos = [], [], 0
        for word in prompt.split(" "):
            tokens.append(int(word[1:]) if word.startswith("t") else 0)
            offsets.append((pos, pos + len(word)))
            pos += len(word) + 1
        return Encoding(tokens=tokens, offsets=offsets)


def post_json(base, path, payload, headers=None):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=10) as response:
        return dict(response.headers), json.loads(response.read())


def get_json(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as response:
        return json.loads(response.read())


def get_text(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as response:
        return response.read().decode()


def main() -> None:
    incident_dir = tempfile.mkdtemp(prefix="kvtpu-replay-smoke-")
    set_build_info_metric()
    capture = InputCaptureRecorder(
        CaptureConfig(window_s=3600.0, max_bytes=64 << 20),
        meta={"block_size": BLOCK_SIZE, "hash_seed": "", "model": MODEL},
    )
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            cache_stats=False,
        ),
        tokenizer=WordTokenizer(),
        capture_recorder=capture,
    )
    indexer.run()
    event_pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(concurrency=2),
        capture=capture,
    )
    event_pool.start()

    # A controllable SLI: pressure 0 = healthy, past 2 = violated.
    pressure = {"value": 0.0}
    slo = SloEngine(window_fast_s=5.0, window_slow_s=30.0)
    slo.register(
        SloSpec(
            "smoke_pressure",
            kind="gauge",
            objective=1.0,
            degraded_bound=2.0,
            description="replay-smoke controllable pressure",
        ),
        lambda: (pressure["value"], 0.0),
    )
    incidents = IncidentManager(
        incident_dir,
        capture=capture,
        sources={
            "traces": lambda: {
                "stats": TRACER.stats(),
                "errored": [
                    t.to_dict() for t in TRACER.recorder.errored(10)
                ],
                "slow": [t.to_dict() for t in TRACER.recorder.slow(10)],
            },
            "profile": lambda: {"disabled": True},
            "timeline": lambda: {"disabled": True},
            "slo": lambda: slo.last_payload() or {"no_data": True},
        },
        index=indexer.kv_block_index,
        min_interval_s=60.0,
    )
    slo.add_listener(incidents.slo_listener())
    server = serve(
        indexer,
        host="127.0.0.1",
        port=0,
        slo=slo,
        capture=capture,
        incidents=incidents,
    )
    base = f"http://127.0.0.1:{server.server_address[1]}"

    try:
        # -- traffic: 3 pods claim chained prefixes; multi-turn scores.
        # Per-pod seqs are contiguous, as a real publisher's are — the
        # replay harness re-checks gap classification against them.
        prompts = []
        seqs = {}
        for p in range(8):
            tokens = [p * 1000 + i + 1 for i in range(BLOCK_SIZE * 24)]
            prompts.append(" ".join(f"t{t}" for t in tokens))
            for pod_i in range(1 + p % 3):
                claimed = 24 - pod_i
                batch = EventBatch(
                    ts=1.0,
                    events=[
                        BlockStored(
                            block_hashes=[
                                70_000 + p * 100 + pod_i * 40 + b
                                for b in range(claimed)
                            ],
                            parent_block_hash=None,
                            token_ids=tokens[: claimed * BLOCK_SIZE],
                            block_size=BLOCK_SIZE,
                            medium="hbm",
                        )
                    ],
                )
                pod = f"pod-{pod_i}"
                seqs[pod] = seqs.get(pod, 0) + 1
                event_pool.add_task(
                    Message(
                        topic=f"kv@{pod}@{MODEL}",
                        payload=batch.encode(),
                        pod_identifier=pod,
                        model_name=MODEL,
                        seq=seqs[pod],
                    )
                )
            event_pool.drain()
            for _ in range(2):  # second pass rides the score memo
                _, scores = post_json(
                    base,
                    "/score_completions",
                    {"prompt": prompts[-1], "model": MODEL},
                )
                assert scores, f"no pod scored prompt {p}"
        # One explained request so the trace reservoirs have content.
        post_json(
            base,
            "/score_completions?explain=1",
            {"prompt": prompts[0], "model": MODEL},
        )

        # -- capture status surfaces.
        status = get_json(base, "/debug/incidents")
        sources = status["capture"]["sources"]
        assert sources["kvevents"]["records"] > 0, sources
        assert sources["scores"]["records"] > 0, sources
        assert not sources["kvevents"]["truncated"], sources
        health = get_json(base, "/healthz")
        assert health["fingerprint"]["fingerprint"], health
        assert health["capture"]["records"] > 0, health
        index_page = get_json(base, "/debug/")
        incident_rows = [
            s
            for s in index_page["surfaces"]
            if s["path"] == "/debug/incidents"
        ]
        assert incident_rows and incident_rows[0]["enabled"], index_page
        metrics_text = get_text(base, "/metrics")
        for family in (
            "kvtpu_build_info",
            "kvtpu_capture_ring_bytes",
            "kvtpu_capture_records_total",
        ):
            assert family in metrics_text, family

        # -- force the SLO violation: healthy -> violated bundles.
        slo.sample()
        slo.evaluate()
        assert slo.last_payload()["state"] == "healthy"
        pressure["value"] = 5.0
        slo.sample()
        payload = slo.evaluate()
        assert payload["state"] == "violated", payload["state"]
        listing = get_json(base, "/debug/incidents")
        assert listing["bundles"] == 1, listing
        manifest = listing["incidents"][0]
        assert manifest["reason"].startswith("slo:"), manifest
        assert "capture.cbor" in manifest["files"], manifest
        for expected in ("traces.json", "profile.json", "timeline.json",
                         "slo.json"):
            assert expected in manifest["files"], manifest
        assert manifest["fingerprint"]["fingerprint"], manifest
        bundle_dir = os.path.join(incident_dir, manifest["id"])
        slo_payload = json.load(
            open(os.path.join(bundle_dir, "slo.json"))
        )
        assert slo_payload["state"] == "violated", slo_payload

        # -- replay the bundle's capture: bit-identical, zero divergence.
        art = load_capture(os.path.join(bundle_dir, "capture.cbor"))
        report = replay_capture(art, mode="single")
        assert report.ok, report.to_dict()
        assert report.scores_compared >= 17, report.to_dict()
        assert report.state_compared, report.to_dict()

        # -- mutated capture reports a first divergence.
        mutated = copy.deepcopy(art)
        flipped = None
        for record in mutated["records"]:
            if record[0] == 1 and record[6]:
                raw = bytearray(record[6][0][1])
                raw[-1] ^= 0x01
                record[6][0][1] = bytes(raw)
                flipped = record[1]
                break
        assert flipped is not None, "no score record to mutate"
        bad = replay_capture(mutated, mode="single")
        assert not bad.ok, "mutated capture must diverge"
        assert bad.divergence["kind"] == "score", bad.divergence
        assert bad.divergence["at_seq"] == flipped, bad.divergence

        # -- manual trigger bypasses the rate limit.
        _, manual = post_json(
            base, "/admin/incident", {"reason": "smoke"}
        )
        assert manual["reason"] == "admin:smoke", manual
        listing = get_json(base, "/debug/incidents")
        assert listing["bundles"] == 2, listing
        assert listing["last_incident"] == manual["id"], listing
    finally:
        server.shutdown()
        event_pool.shutdown()
        indexer.shutdown()
        slo.close()
        shutil.rmtree(incident_dir, ignore_errors=True)
    print("replay smoke completed successfully")


if __name__ == "__main__":
    main()
