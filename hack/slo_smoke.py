"""CI smoke gate for the fleet observability plane (ISSUE 13).

Boots THREE in-process replicas (strict-wire codec, so every RPC pays
the real CBOR round trip) behind a router HTTP service with the SLO
engine attached, then asserts the two fleet-observability loops close:

* **Cross-replica trace stitching**: a scored request carrying a W3C
  ``traceparent`` resolves at ``GET /debug/traces/<id>`` as ONE trace
  whose ``cluster.rpc`` spans cover every owner RPC, with replica-side
  ``replica.lookup`` sub-spans piggybacked off the wire, and top-level
  stage durations summing to the end-to-end latency (±5%);
  ``?explain=1`` carries the per-replica ``cluster_rpcs`` rollup.
* **Degradation envelopes**: ``GET /debug/slo`` reports ``healthy``
  under steady traffic; a replica killed mid-traffic flips the
  ``replicas_dead`` / ``failovers`` SLIs to ``degraded`` (never
  ``violated`` — the published envelope stays inside its declared
  bounds, checked by ``envelope_violations``), with the failure's
  kind/last-error context visible in ``/debug/cluster`` and the
  ``kvtpu_slo_*`` / ``kvtpu_cluster_rpc_*`` families on ``/metrics``.

Run: ``python hack/slo_smoke.py`` (CI step "SLO smoke",
``make slo-smoke``).  Prints "slo smoke completed successfully" on
success; any assertion exits non-zero.
"""

import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TOKENIZERS_PARALLELISM", "false")

from llm_d_kv_cache_manager_tpu.api.http_service import serve  # noqa: E402
from llm_d_kv_cache_manager_tpu.cluster import LocalCluster  # noqa: E402
from llm_d_kv_cache_manager_tpu.kvcache.indexer import (  # noqa: E402
    Indexer,
    IndexerConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (  # noqa: E402,E501
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (  # noqa: E402
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (  # noqa: E402
    Message,
    Pool,
    PoolConfig,
)
from llm_d_kv_cache_manager_tpu.obs.slo import (  # noqa: E402
    default_fleet_slos,
    envelope_violations,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (  # noqa: E402
    Encoding,
)

MODEL = "slo-model"
BLOCK_SIZE = 4
TRACE_ID = "d3d3d3d3d3d3d3d3d3d3d3d3d3d3d3d3"
TRACEPARENT = f"00-{TRACE_ID}-e4e4e4e4e4e4e4e4-01"


class WordTokenizer:
    def type(self):
        return "smoke-word"

    def encode(self, prompt, model_name, add_special_tokens):
        tokens, offsets, pos = [], [], 0
        for word in prompt.split(" "):
            tokens.append(int(word[1:]) if word.startswith("t") else 0)
            offsets.append((pos, pos + len(word)))
            pos += len(word) + 1
        return Encoding(tokens=tokens, offsets=offsets)


def post_json(base, path, payload, headers=None):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=10) as response:
        return dict(response.headers), json.loads(response.read())


def get_json(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as response:
        return json.loads(response.read())


def get_text(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as response:
        return response.read().decode()


def main() -> None:
    cluster = LocalCluster(strict_wire=True, heartbeat_interval_s=0.2)
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            cache_stats=False,
        ),
        tokenizer=WordTokenizer(),
        kv_block_index=cluster.remote_index,
    )
    indexer.run()
    event_pool = Pool(
        cluster.remote_index,
        indexer.token_processor,
        PoolConfig(concurrency=2),
    )
    event_pool.start()
    # Tight windows so a smoke-scale run exercises real window math.
    slo = default_fleet_slos(
        window_fast_s=5.0,
        window_slow_s=30.0,
        score_latency_s=2.0,
        membership=cluster.membership,
        pool=event_pool,
    )
    server = serve(
        indexer,
        host="127.0.0.1",
        port=0,
        cluster_status=cluster.status,
        slo=slo,
    )
    base = f"http://127.0.0.1:{server.server_address[1]}"

    # Traffic: 3 pods claim chained prefixes of 8 prompts through the
    # real event plane, keys landing on every replica slice.  Prompts
    # are 32 blocks long so the per-request fixed bookkeeping is small
    # next to the staged work (the ±5% stage-sum pin below).
    blocks_per_prompt = 32
    prompts = []
    for p in range(8):
        tokens = [
            p * 1000 + i + 1
            for i in range(BLOCK_SIZE * blocks_per_prompt)
        ]
        prompts.append(" ".join(f"t{t}" for t in tokens))
        for pod_i in range(1 + p % 3):
            claimed = blocks_per_prompt - pod_i
            batch = EventBatch(
                ts=1.0,
                events=[
                    BlockStored(
                        block_hashes=[
                            40_000 + p * 100 + pod_i * 40 + b
                            for b in range(claimed)
                        ],
                        parent_block_hash=None,
                        token_ids=tokens[: claimed * BLOCK_SIZE],
                        block_size=BLOCK_SIZE,
                        medium="hbm",
                    )
                ],
            )
            event_pool.add_task(
                Message(
                    topic=f"kv@pod-{pod_i}@{MODEL}",
                    payload=batch.encode(),
                    pod_identifier=f"pod-{pod_i}",
                    model_name=MODEL,
                    seq=p,
                )
            )
    event_pool.drain()

    # 1. Stitched cross-replica trace, retrievable by id.
    headers, scores = post_json(
        base,
        "/score_completions",
        {"prompt": prompts[0], "model": MODEL},
        headers={"traceparent": TRACEPARENT},
    )
    assert scores, f"no pod scored: {scores}"
    assert headers.get("traceparent", "").split("-")[1] == TRACE_ID

    full = get_json(base, f"/debug/traces/{TRACE_ID}")
    spans = full["spans"]
    rpc_spans = [
        s
        for s in spans
        if s["name"] == "cluster.rpc"
        and s["attributes"].get("method") == "lookup"
    ]
    assert rpc_spans, [s["name"] for s in spans]
    owners = {s["attributes"]["replica"] for s in rpc_spans}
    assert len(owners) >= 2, f"expected a multi-owner fan-out: {owners}"
    server_spans = [s for s in spans if s["name"] == "replica.lookup"]
    assert server_spans, "replica-side spans must ride the reply"
    assert {s["attributes"]["replica"] for s in server_spans} <= set(
        cluster.replicas
    )
    assert all(s["parent"] == "cluster.rpc" for s in server_spans)

    # Stage sums consistent with end-to-end latency (±5%): top-level
    # stages are the request's sequential breakdown; stitched children
    # must not perturb it.  Best-of-3 traced requests — a single
    # scheduler hiccup between stages must not flake the gate.
    def stage_gap(view) -> float:
        stage_sum = sum(s["duration_ms"] for s in view["stages"])
        return abs(stage_sum - view["duration_ms"]) / view["duration_ms"]

    gaps = [stage_gap(full)]
    attempt = 0
    while min(gaps) > 0.05 and attempt < 2:
        attempt += 1
        retry_id = TRACE_ID[:-1] + str(attempt)
        post_json(
            base,
            "/score_completions",
            {"prompt": prompts[0], "model": MODEL},
            headers={
                "traceparent": f"00-{retry_id}-e4e4e4e4e4e4e4e4-01"
            },
        )
        gaps.append(stage_gap(get_json(base, f"/debug/traces/{retry_id}")))
    assert min(gaps) <= 0.05, (gaps, full["stages"])

    # explain=1 carries the per-owner rollup.
    _, body = post_json(
        base,
        "/score_completions?explain=1",
        {"prompt": prompts[0], "model": MODEL},
    )
    rollup = body["explain"].get("cluster_rpcs")
    assert rollup, body["explain"].keys()
    assert sum(v["rpcs"] for v in rollup.values()) >= len(rpc_spans)

    # 2. Healthy envelope under steady traffic.
    for _ in range(3):
        for prompt in prompts:
            post_json(
                base,
                "/score_completions",
                {"prompt": prompt, "model": MODEL},
            )
        slo.sample()
        time.sleep(0.05)
    payload = get_json(base, "/debug/slo")
    assert payload["state"] == "healthy", payload
    assert envelope_violations(payload) == [], payload
    health = get_json(base, "/healthz")
    assert health["slo"]["state"] == "healthy", health["slo"]

    # 3. Chaos: kill a replica mid-traffic -> the staleness SLIs burn
    # into DEGRADED (bounded), asserted via the published envelope
    # rather than ad-hoc numeric pins.
    victim = sorted(cluster.replicas)[0]
    cluster.kill(victim)
    for prompt in prompts:  # scores keep flowing over the survivors
        post_json(
            base, "/score_completions", {"prompt": prompt, "model": MODEL}
        )
    slo.sample()
    payload = get_json(base, "/debug/slo")
    assert payload["state"] == "degraded", payload["state"]
    assert payload["slis"]["replicas_dead"]["state"] == "degraded"
    assert payload["slis"]["failovers"]["state"] == "degraded"
    assert envelope_violations(payload) == [], envelope_violations(
        payload
    )
    health = get_json(base, "/healthz")
    assert "replicas_dead" in health["slo"].get("degraded", []), health

    # 4. Attribution surfaces: per-replica rpc panel + last-error
    # context + the new metric families.
    status = get_json(base, "/debug/cluster")
    assert status["rpc"]["replicas"], status["rpc"]
    assert status["rpc"]["critical_path"]["owner_rpcs"] >= 1
    assert victim in status["membership"]["last_errors"], status[
        "membership"
    ]["last_errors"]
    metrics_text = get_text(base, "/metrics")
    for family in (
        "kvtpu_slo_state",
        "kvtpu_slo_burn_rate",
        "kvtpu_cluster_rpc_latency_seconds",
        "kvtpu_score_latency_seconds",
    ):
        assert family in metrics_text, family

    server.shutdown()
    event_pool.shutdown()
    indexer.shutdown()
    cluster.close()
    print("slo smoke completed successfully")


if __name__ == "__main__":
    main()
