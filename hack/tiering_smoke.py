"""CI smoke gate for the predictive tiering plane.

Boots the HTTP scoring service with a tiering PolicyEngine attached,
then asserts the whole policy loop closes:

* scored traffic teaches the PolicyFeed (families mapped, snapshot
  refreshed) — visible in ``GET /debug/tiering``;
* a forced demotion (hbm -> host through the DemotionWorker, events
  riding the REAL kvevents pool) is observed in ``/debug/tiering``,
  in ``kvtpu_tiering_demotions_total`` on ``/metrics``, AND in the
  actual score (1.0/block -> 0.8/block through the live endpoint);
* the compute-or-load advice FLIPS when the RTT estimator is
  inflated: cheap readback -> load/hybrid, catastrophic readback ->
  recompute, and ``?explain=1`` carries the advice;
* ``/healthz`` carries the tiering block.

Run: ``python hack/tiering_smoke.py`` (CI step "Tiering smoke",
``make tiering-smoke``).  Prints "tiering smoke completed
successfully" on success; any assertion exits non-zero.
"""

import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TOKENIZERS_PARALLELISM", "false")
# Deterministic smoke: record every request, tier detail on all.
os.environ.setdefault("CACHESTATS_SAMPLE_RATE", "1")
os.environ.setdefault("CACHESTATS_TIER_SAMPLE", "1")
os.environ.setdefault("TIERING_REFRESH_S", "0")

from llm_d_kv_cache_manager_tpu.api.http_service import serve  # noqa: E402
from llm_d_kv_cache_manager_tpu.kvcache.indexer import (  # noqa: E402
    Indexer,
    IndexerConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (  # noqa: E402,E501
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (  # noqa: E402
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (  # noqa: E402
    Message,
    Pool,
    PoolConfig,
)
from llm_d_kv_cache_manager_tpu.tiering import (  # noqa: E402
    DemotionConfig,
    PodTierState,
    PolicyEngine,
    pool_event_sink,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (  # noqa: E402
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (  # noqa: E402
    Encoding,
)

MODEL = "test-model"
BLOCK_SIZE = 4


class WordTokenizer:
    """Deterministic whitespace tokenizer: 'tN' -> N."""

    def type(self) -> str:
        return "word"

    def encode(self, prompt, model_name, add_special_tokens=True):
        tokens, offsets, pos = [], [], 0
        for word in prompt.split(" "):
            tokens.append(int(word[1:]))
            offsets.append((pos, pos + len(word)))
            pos += len(word) + 1
        return Encoding(tokens, offsets)


def post(base, path, obj):
    request = urllib.request.Request(
        base + path,
        data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(request, timeout=30) as response:
        return json.load(response)


def get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as response:
        return json.load(response)


def get_text(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as response:
        return response.read().decode()


def main() -> None:
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=2, model_name=MODEL
            ),
        ),
        tokenizer=WordTokenizer(),
    )
    assert indexer.cache_stats is not None, "ledger must default on"
    indexer.run()
    engine = PolicyEngine(ledger=indexer.cache_stats)
    indexer.set_policy_engine(engine)
    event_pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(concurrency=2),
    )
    event_pool.start()

    tokens = list(range(1, 33))  # 8 blocks of 4
    n_blocks = len(tokens) // BLOCK_SIZE
    prompt = " ".join(f"t{t}" for t in tokens)
    engine_hashes = [0x300 + i for i in range(n_blocks)]

    # Seed the chain on pod-1 at hbm through the pool.
    batch = EventBatch(
        ts=1.0,
        events=[
            BlockStored(
                block_hashes=list(engine_hashes),
                parent_block_hash=None,
                token_ids=tokens,
                block_size=BLOCK_SIZE,
                medium="hbm",
            )
        ],
    )
    event_pool.add_task(
        Message(
            topic=f"kv@pod-1@{MODEL}",
            payload=batch.encode(),
            pod_identifier="pod-1",
            model_name=MODEL,
        )
    )
    event_pool.drain()

    server = serve(indexer, host="127.0.0.1", port=0, tiering=engine)
    base = f"http://127.0.0.1:{server.server_address[1]}"

    # 1. Traffic teaches the feed (repeat the prompt so the family
    # develops a reuse rhythm).
    for _ in range(4):
        scores = post(
            base, "/score_completions", {"prompt": prompt, "model": MODEL}
        )
        time.sleep(0.02)
    assert scores.get("pod-1") == n_blocks, scores

    status = get(base, "/debug/tiering")
    assert status["feed"]["observed_chains"] >= 4, status["feed"]
    assert status["feed"]["keys_mapped"] >= n_blocks, status["feed"]
    assert status["feed"]["refreshes"] >= 1, status["feed"]

    # 2. Forced demotion: the worker moves the (now idle-backdated)
    # group hbm -> host; its events ride the same kvevents pool.
    family = engine.feed.snapshot().family_of(
        indexer.token_processor.tokens_to_kv_block_keys(
            0, tokens, MODEL
        )[-1]
    )
    state = PodTierState(
        capacity_bytes=10_000,
        event_sink=pool_event_sink(event_pool, "pod-1", MODEL),
        feed=engine.feed,
    )
    state.register_group(
        0xFACE,
        engine_hashes=engine_hashes,
        token_ids=tokens,
        nbytes=4096,
        block_size=BLOCK_SIZE,
        family=family,
        now=time.monotonic() - 600,
    )
    worker = engine.start_demotion(
        state,
        DemotionConfig(demote_host_idle_s=0.0, require_prediction=False),
        start=False,
    )
    moves = worker.run_cycle()
    assert moves == 1, f"expected 1 demotion, got {moves}"
    event_pool.drain()

    # Observed in /debug/tiering...
    status = get(base, "/debug/tiering")
    demotion = status["demotion"][0]
    assert demotion["moves"] == 1, demotion
    assert demotion["recent"][0]["transition"] == "hbm_to_host", demotion

    # ...in /metrics...
    text = get_text(base, "/metrics")
    assert (
        'kvtpu_tiering_demotions_total{transition="hbm_to_host"} 1.0'
        in text
    ), "demotion counter missing from exposition"
    assert "kvtpu_tiering_demotion_bytes_total" in text

    # ...and in the actual score: host weighs 0.8 per block.
    scores = post(
        base, "/score_completions", {"prompt": prompt, "model": MODEL}
    )
    assert abs(scores["pod-1"] - 0.8 * n_blocks) < 1e-9, scores

    # 3. Compute-or-load advice flips when the RTT estimator inflates.
    advisor = engine.advisor
    advisor.config.bytes_per_block = 4096
    advisor.observe_prefill(8192, 0.5)
    advisor.observe_load(1 << 20, 0.001)  # cheap readback
    fast = advisor.advise(64)
    assert fast.action in ("load", "hybrid"), fast.to_dict()
    for _ in range(20):
        advisor.observe_load(1 << 20, 30.0)  # catastrophic readback
    slow = advisor.advise(64)
    assert slow.action == "recompute", slow.to_dict()

    # The explain surface carries the advice.
    explained = post(
        base,
        "/score_completions?explain=1",
        {"prompt": prompt, "model": MODEL},
    )
    advice = explained["explain"].get("tiering")
    assert advice is not None, explained["explain"].keys()
    assert advice["pod"] == "pod-1", advice
    assert advice["action"] == "recompute", advice

    text = get_text(base, "/metrics")
    assert 'kvtpu_tiering_advice_total{action="recompute"}' in text

    # 4. /healthz tiering block.
    health = get(base, "/healthz")
    tiering_block = health.get("tiering", {})
    assert "advice_counts" in tiering_block, health
    assert tiering_block["demotion_workers"] == 1, tiering_block

    server.shutdown()
    engine.close()
    event_pool.shutdown()
    indexer.shutdown()
    print("tiering smoke completed successfully")


if __name__ == "__main__":
    main()
