"""CI smoke gate for the KV-transfer planning plane.

Boots the HTTP scoring service with a TransferEngine attached, then
asserts the whole transfer loop closes over real wire surfaces:

* scored traffic (``plan: true`` + ``pod_loads``) yields a transfer
  directive pricing pod-to-pod movement against recompute, and the
  same request teaches the hot-family catalog;
* executing the planned directive publishes REAL KVEvents through the
  kvevents pool — the target pod's score rises through the ordinary
  index path (0 -> full chain via the live endpoint);
* a cold pod registering for instant-warm scale-out gets the hot
  family bulk-planned and drained by the warm-up worker, visible in
  ``GET /debug/transfer``, in ``kvtpu_transfer_warmup_moves_total``
  on ``/metrics``, AND in the cold pod's actual score;
* ``/healthz`` carries the transfer block.

Run: ``python hack/transfer_smoke.py`` (CI step "Transfer smoke",
``make transfer-smoke``).  Prints "transfer smoke completed
successfully" on success; any assertion exits non-zero.
"""

import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TOKENIZERS_PARALLELISM", "false")
# Deterministic smoke: record every request so the ledger ranks the
# family for warm-up, and keep tier detail on all provenance.
os.environ.setdefault("CACHESTATS_SAMPLE_RATE", "1")
os.environ.setdefault("CACHESTATS_TIER_SAMPLE", "1")

from llm_d_kv_cache_manager_tpu.api.http_service import serve  # noqa: E402
from llm_d_kv_cache_manager_tpu.kvcache.indexer import (  # noqa: E402
    Indexer,
    IndexerConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (  # noqa: E402,E501
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (  # noqa: E402
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (  # noqa: E402
    Message,
    Pool,
    PoolConfig,
)
from llm_d_kv_cache_manager_tpu.tiering.advisor import (  # noqa: E402
    AdvisorConfig,
    ComputeOrLoadAdvisor,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (  # noqa: E402
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (  # noqa: E402
    Encoding,
)
from llm_d_kv_cache_manager_tpu.transfer import (  # noqa: E402
    TransferConfig,
    TransferEngine,
)

MODEL = "test-model"
BLOCK_SIZE = 4


class WordTokenizer:
    """Deterministic whitespace tokenizer: 'tN' -> N."""

    def type(self) -> str:
        return "word"

    def encode(self, prompt, model_name, add_special_tokens=True):
        tokens, offsets, pos = [], [], 0
        for word in prompt.split(" "):
            tokens.append(int(word[1:]))
            offsets.append((pos, pos + len(word)))
            pos += len(word) + 1
        return Encoding(tokens, offsets)


def post(base, path, obj):
    request = urllib.request.Request(
        base + path,
        data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(request, timeout=30) as response:
        return json.load(response)


def get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as response:
        return json.load(response)


def get_text(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as response:
        return response.read().decode()


def main() -> None:
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=2, model_name=MODEL
            ),
        ),
        tokenizer=WordTokenizer(),
    )
    assert indexer.cache_stats is not None, "ledger must default on"
    indexer.run()

    # Advisor with both RTT models fed so transfers price cheap
    # against a deliberately slow prefill rate.
    advisor = ComputeOrLoadAdvisor(
        AdvisorConfig(
            bytes_per_block=1024,
            block_tokens=BLOCK_SIZE,
            prefill_tokens_per_s=50.0,
        )
    )
    advisor.observe_load(4096, 0.001)
    advisor.observe_store(4096, 0.0005)

    engine = TransferEngine(
        advisor=advisor,
        config=TransferConfig(load_threshold=2.0, min_blocks=2),
    )
    indexer.set_transfer_engine(engine)
    assert engine.ledger is indexer.cache_stats, "ledger must bind"

    event_pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(concurrency=2),
    )
    event_pool.start()
    # Directive channel's write side: executed transfers publish real
    # KVEvents through this pool.  The smoke pumps warm-up cycles by
    # hand, so the drain thread stays off.
    engine.attach_executor(
        indexer.kv_block_index, event_pool, MODEL, start_warmup=False
    )

    tokens = list(range(1, 33))  # 8 blocks of 4
    n_blocks = len(tokens) // BLOCK_SIZE
    prompt = " ".join(f"t{t}" for t in tokens)
    engine_hashes = [0x700 + i for i in range(n_blocks)]

    # Seed the chain on pod-1 at hbm through the pool.
    batch = EventBatch(
        ts=1.0,
        events=[
            BlockStored(
                block_hashes=list(engine_hashes),
                parent_block_hash=None,
                token_ids=tokens,
                block_size=BLOCK_SIZE,
                medium="hbm",
            )
        ],
    )
    event_pool.add_task(
        Message(
            topic=f"kv@pod-1@{MODEL}",
            payload=batch.encode(),
            pod_identifier="pod-1",
            model_name=MODEL,
        )
    )
    event_pool.drain()

    server = serve(indexer, host="127.0.0.1", port=0, transfer=engine)
    base = f"http://127.0.0.1:{server.server_address[1]}"

    # 1. Repeat traffic so the ledger develops a reuse rhythm for the
    # family (warm-up ranking feeds off reuse_predictions()).
    for _ in range(4):
        scores = post(
            base, "/score_completions", {"prompt": prompt, "model": MODEL}
        )
    assert scores.get("pod-1") == n_blocks, scores

    # 2. Planned scoring: pod-1 overloaded, pod-2 idle -> directive.
    reply = post(
        base,
        "/score_completions",
        {
            "prompt": prompt,
            "model": MODEL,
            "pods": ["pod-1", "pod-2"],
            "pod_loads": {"pod-1": 9.0, "pod-2": 0.0},
            "plan": True,
        },
    )
    directive = reply["transfer"]
    assert directive["planned"] is True, directive
    assert directive["source_pod"] == "pod-1", directive
    assert directive["target_pod"] == "pod-2", directive
    assert directive["blocks"] == n_blocks, directive

    # The explain surface carries the same directive.
    explained = post(
        base,
        "/score_completions?explain=1",
        {
            "prompt": prompt,
            "model": MODEL,
            "pod_loads": {"pod-1": 9.0, "pod-2": 0.0},
        },
    )
    assert "transfer" in explained["explain"], explained["explain"].keys()

    # 3. Execute the plan: real KVEvents flow, pod-2's score rises
    # through the ordinary index path.
    plan = engine.planner.get(directive["plan_id"])
    assert plan is not None, directive
    assert engine.executor.execute(plan) is True
    event_pool.drain()
    scores = post(
        base, "/score_completions", {"prompt": prompt, "model": MODEL}
    )
    assert scores.get("pod-2") == n_blocks, scores

    # 4. Instant-warm scale-out: cold pod-3 registers, the hot family
    # is bulk-planned and the worker drains the queue.
    queued = engine.register_cold_pod("pod-3")
    assert queued >= 1, "cold pod got no warm-up plans"
    status = get(base, "/debug/transfer")
    assert status["warmup"]["queued"] >= 1, status["warmup"]
    assert status["warmup"]["cold_pods"].get("pod-3", 0) >= 1, status[
        "warmup"
    ]
    while engine.run_warmup_cycle():
        pass
    event_pool.drain()
    scores = post(
        base, "/score_completions", {"prompt": prompt, "model": MODEL}
    )
    assert scores.get("pod-3") == n_blocks, scores

    # 5. The debug surface tells the whole story.
    status = get(base, "/debug/transfer")
    assert status["planner"]["outcomes"].get("planned", 0) >= 1, status[
        "planner"
    ]
    assert status["catalog"]["families"] >= 1, status["catalog"]
    assert status["executor"]["executed"] >= 2, status["executor"]
    assert status["warmup"]["queued"] == 0, status["warmup"]
    assert status["warmup"]["cold_pods"] == {}, status["warmup"]
    assert status["warmup"]["warmed_moves"].get("pod-3", 0) >= 1, status[
        "warmup"
    ]
    assert status["config"]["load_threshold"] == 2.0, status["config"]

    # 6. /metrics exposition.
    text = get_text(base, "/metrics")
    assert (
        'kvtpu_transfer_plans_total{outcome="planned"}' in text
    ), "plan counter missing from exposition"
    assert (
        'kvtpu_transfer_executions_total{outcome="copied"}' in text
    ), "execution counter missing from exposition"
    assert "kvtpu_transfer_bytes_total" in text
    assert "kvtpu_transfer_warmup_moves_total" in text
    assert "kvtpu_transfer_cold_pods 0.0" in text

    # 7. /healthz transfer block + debug index row.
    health = get(base, "/healthz")
    transfer_block = health.get("transfer", {})
    assert transfer_block.get("plans", 0) >= 1, health
    assert transfer_block.get("cold_pods") == 0, transfer_block
    debug_index = get(base, "/debug")
    surfaces = {
        row["path"]: row["enabled"] for row in debug_index["surfaces"]
    }
    assert surfaces["/debug/transfer"] is True, surfaces

    server.shutdown()
    engine.close()
    event_pool.shutdown()
    indexer.shutdown()
    print("transfer smoke completed successfully")


if __name__ == "__main__":
    main()
