#!/usr/bin/env bash
# Runs every example to completion (reference: hack/verify-examples.sh).
# Each demo asserts its own invariants and prints "... completed
# successfully"; any failure exits non-zero.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

for demo in offline_demo index_service_demo online_demo valkey_demo vllm_demo; do
  echo "=== examples/${demo}.py ==="
  python "examples/${demo}.py" 2>&1 | grep "completed successfully" \
    || { echo "FAIL: ${demo}"; exit 1; }
done
echo "all examples verified"
