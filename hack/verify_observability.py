"""CI smoke gate for the tracing debug surface.

Boots the HTTP scoring service against a tiny local tokenizer, makes a
scored request carrying a W3C ``traceparent`` header, and asserts the
whole observability loop closes:

* the response echoes a traceparent with the caller's trace id;
* ``GET /debug/traces`` lists the trace and ``GET /debug/traces/<id>``
  returns its spans (tokenize/hash_blocks/index_lookup/score);
* ``?explain=1`` returns the per-stage breakdown and per-pod score
  provenance (break index, tiers);
* ``/healthz`` carries the observability block.

Run: ``python hack/verify_observability.py`` (CI step "Observability
smoke").  Prints "observability smoke completed successfully" on
success; any assertion exits non-zero.
"""

import json
import os
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TOKENIZERS_PARALLELISM", "false")
# Before any package import: the tracer reads these at import time.
os.environ.setdefault("TRACE_SAMPLE_RATE", "1")
os.environ.setdefault("TRACE_RING_SIZE", "64")

from llm_d_kv_cache_manager_tpu.api.http_service import serve  # noqa: E402
from llm_d_kv_cache_manager_tpu.kvcache.indexer import (  # noqa: E402
    Indexer,
    IndexerConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (  # noqa: E402,E501
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (  # noqa: E402
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (  # noqa: E402
    Message,
    Pool,
    PoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (  # noqa: E402
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (  # noqa: E402
    LocalFastTokenizer,
)
from tests.helpers.tiny_tokenizer import save_tokenizer_json  # noqa: E402

MODEL = "test-model"
BLOCK_SIZE = 4
PROMPT = "the quick brown fox jumps over the lazy dog . " * 8
TRACE_ID = "c1c1c1c1c1c1c1c1c1c1c1c1c1c1c1c1"
TRACEPARENT = f"00-{TRACE_ID}-b2b2b2b2b2b2b2b2-01"


def post(base, path, obj, headers=None):
    request = urllib.request.Request(
        base + path,
        data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    with urllib.request.urlopen(request, timeout=30) as response:
        return dict(response.headers), json.load(response)


def get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as response:
        return json.load(response)


def main() -> None:
    tokenizer_dir = save_tokenizer_json(tempfile.mkdtemp(), MODEL)
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=2, model_name=MODEL
            ),
        ),
        tokenizer=LocalFastTokenizer(tokenizer_dir),
    )
    indexer.run()
    event_pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(concurrency=2),
    )
    event_pool.start()
    server = serve(indexer, host="127.0.0.1", port=0)
    base = f"http://127.0.0.1:{server.server_address[1]}"

    # Store half the prompt's blocks so explain has a chain break.
    tokens = indexer.tokenization_pool.tokenize(PROMPT, MODEL, None)
    n_blocks = len(tokens) // BLOCK_SIZE
    half_blocks = n_blocks // 2
    batch = EventBatch(
        ts=1.0,
        events=[
            BlockStored(
                block_hashes=list(range(0x100, 0x100 + half_blocks)),
                parent_block_hash=None,
                token_ids=tokens[: half_blocks * BLOCK_SIZE],
                block_size=BLOCK_SIZE,
                medium="hbm",
            )
        ],
    )
    event_pool.add_task(
        Message(
            topic=f"kv@pod-1@{MODEL}",
            payload=batch.encode(),
            pod_identifier="pod-1",
            model_name=MODEL,
        )
    )
    event_pool.drain()

    # 1. Scored request with a traceparent header: echo + retrieval.
    headers, scores = post(
        base,
        "/score_completions",
        {"prompt": PROMPT, "model": MODEL},
        headers={"traceparent": TRACEPARENT},
    )
    assert scores.get("pod-1") == half_blocks, scores
    echoed = headers.get("traceparent")
    assert echoed and echoed.split("-")[1] == TRACE_ID, headers

    listing = get(base, "/debug/traces?kind=recent")
    listed_ids = [t["trace_id"] for t in listing["traces"]]
    assert TRACE_ID in listed_ids, listed_ids

    full = get(base, f"/debug/traces/{TRACE_ID}")
    stage_names = {s["stage"] for s in full["stages"]}
    assert {
        "tokenize", "hash_blocks", "index_lookup", "score"
    } <= stage_names, stage_names

    # 2. explain=1: stage breakdown + per-pod chain-break provenance.
    _, body = post(
        base,
        "/score_completions?explain=1",
        {"prompt": PROMPT, "model": MODEL},
    )
    detail = body["explain"]["pods"]["pod-1"]
    assert detail["break_index"] == half_blocks, detail
    assert detail["tiers"] == {"hbm": half_blocks}, detail
    assert body["explain"]["stages"], body["explain"]

    # 3. /healthz observability block.
    health = get(base, "/healthz")
    obs = health.get("observability", {})
    assert obs.get("traces_sampled", 0) >= 2, obs
    assert obs.get("ring_occupancy", 0) >= 2, obs

    server.shutdown()
    event_pool.shutdown()
    indexer.shutdown()
    print("observability smoke completed successfully")


if __name__ == "__main__":
    main()
