"""CI smoke gate for the replay-driven what-if engine (ISSUE 18).

Closes the loop end to end:

* **Composition**: scales the pinned reference capture
  (``tests/testdata/whatif_reference.cbor``) 4x by pod fan-out into a
  valid artifact the loader accepts, then time-stretches it — the
  synthetic-storm path.
* **A/B canary**: runs the scaled storm through shards=1 vs shards=8
  — the deterministic counters MUST agree exactly (hit parity 1.0,
  equal digests): both arms apply identical writes, so any difference
  is a sharding bug.  A second A/B pits a flow-control-starved arm
  (tiny queue depth, finite drain rate) against a default arm and
  must measure real sheds, differing digests, and a first
  SLO-divergence checkpoint.
* **Service surfaces**: boots the HTTP service in-process, forces an
  incident bundle (``POST /admin/incident``), reads its detail page
  (``GET /debug/incidents/<id>``), replays the bundle through
  ``POST /admin/whatif`` by id, and checks ``GET /debug/whatif`` +
  the ``kvtpu_whatif_*`` metric families.
* **Perf-trend gate**: ``hack/perf_trend.py`` must pass on the honest
  checked-in trajectory (the live reference A/B equals
  ``WHATIF_r01.json`` exactly — the headlines are deterministic) and
  must FAIL when the baseline artifact is doctored to claim a higher
  hit rate than the code can deliver.

Run: ``python hack/whatif_smoke.py`` (CI step "What-if smoke",
``make whatif-smoke``).  Prints "whatif smoke completed successfully"
on success; any assertion exits non-zero.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TOKENIZERS_PARALLELISM", "false")

from llm_d_kv_cache_manager_tpu.api.http_service import serve  # noqa: E402
from llm_d_kv_cache_manager_tpu.kvcache.indexer import (  # noqa: E402
    Indexer,
    IndexerConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (  # noqa: E402,E501
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (  # noqa: E402
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (  # noqa: E402
    Message,
    Pool,
    PoolConfig,
)
from llm_d_kv_cache_manager_tpu.obs import whatif  # noqa: E402
from llm_d_kv_cache_manager_tpu.obs.capture import (  # noqa: E402
    CaptureConfig,
    IncidentManager,
    InputCaptureRecorder,
    set_build_info_metric,
)
from llm_d_kv_cache_manager_tpu.obs.replay import (  # noqa: E402
    _ReplayTokenizer,
    load_capture,
)
from llm_d_kv_cache_manager_tpu.obs.slo import (  # noqa: E402
    SloEngine,
    SloSpec,
)
from llm_d_kv_cache_manager_tpu.obs.trace import TRACER  # noqa: E402

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
REFERENCE = os.path.join(
    REPO, "tests", "testdata", "whatif_reference.cbor"
)
MODEL = "whatif-ref"
BLOCK_SIZE = 4


def post_json(base, path, payload, headers=None):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=60) as response:
        return json.loads(response.read())


def get_json(base, path):
    with urllib.request.urlopen(base + path, timeout=60) as response:
        return json.loads(response.read())


def get_text(base, path):
    with urllib.request.urlopen(base + path, timeout=60) as response:
        return response.read().decode()


def check_composition(workdir):
    reference = load_capture(REFERENCE, allow_mismatch=True)
    base_events = sum(1 for r in reference["records"] if r[0] == 0)
    base_scores = sum(1 for r in reference["records"] if r[0] == 1)

    storm = whatif.scale_pods(reference, 4)
    assert storm["meta"]["composed"] == "1", storm["meta"]
    assert (
        sum(1 for r in storm["records"] if r[0] == 0) == base_events * 4
    ), "scale:4 must quadruple the event streams"
    assert (
        sum(1 for r in storm["records"] if r[0] == 1) == base_scores
    ), "scale:4 must keep every recorded score"
    stretched = whatif.stretch(storm, 0.5)
    storm_path = os.path.join(workdir, "storm.cbor")
    with open(storm_path, "wb") as handle:
        handle.write(whatif.capture_to_bytes(stretched))
    # Round trip through the standard loader — a composed artifact is
    # a REAL capture, not a private in-memory shape.
    loaded = load_capture(storm_path, allow_mismatch=True)
    assert len(loaded["records"]) == len(stretched["records"])
    print(
        f"whatif-smoke: composed 4x storm ok "
        f"({len(loaded['records'])} records at {storm_path})"
    )
    return loaded


def check_ab(storm):
    cfg = whatif.WhatIfConfig(speed=6.0)
    # Sharding parity: identical deterministic measurements or the
    # index has a shard-dependent bug.  pod_cache is raised so the 12
    # fanned-out pods per key fit without eviction in BOTH arms.
    ab = whatif.run_ab(
        storm,
        whatif.StackConfig.parse("shards=1,pod_cache=16", name="s1"),
        whatif.StackConfig.parse("shards=8,pod_cache=16", name="s8"),
        cfg,
        register=False,
    )
    delta = ab["delta"]
    assert delta["digest_equal"], (
        "shards=1 vs shards=8 diverged deterministically: "
        f"{json.dumps(delta, default=str)[:600]}"
    )
    assert delta["hit_parity"] == 1.0, delta["hit_parity"]
    assert delta["slo"]["first_divergence"] is None
    assert 0.0 < delta["hit_rate"]["a"] <= 1.0
    for key in (
        "hit_rate",
        "shed",
        "applied",
        "latency_p50_ms",
        "latency_p99_ms",
        "wall_scores_per_sec",
    ):
        assert {"a", "b"} <= set(delta[key]), (key, delta[key])
    print(
        "whatif-smoke: shards A/B parity ok "
        f"(hit_rate {delta['hit_rate']['a']:.4f})"
    )

    # Flow-control A/B: a starved arm must measurably shed and push
    # its SLO envelope off the healthy arm's trajectory.
    ab2 = whatif.run_ab(
        storm,
        whatif.StackConfig.parse(
            "depth=4,drain_rate=120,pod_cache=16", name="starved"
        ),
        whatif.StackConfig.parse(
            "drain_rate=120,pod_cache=16", name="roomy"
        ),
        whatif.WhatIfConfig(speed=10.0),
        register=False,
    )
    d2 = ab2["delta"]
    assert d2["shed"]["a"] > 0 and d2["shed"]["b"] == 0, d2["shed"]
    assert not d2["digest_equal"]
    divergence = d2["slo"]["first_divergence"]
    assert divergence is not None, "starved arm never diverged on SLO"
    assert "whatif.event_shed" in divergence["slis"], divergence
    print(
        "whatif-smoke: flow-control A/B ok "
        f"(shed {d2['shed']['a']}, first divergence at virtual "
        f"{divergence['virtual_s']}s)"
    )


def check_service(workdir):
    incident_dir = os.path.join(workdir, "incidents")
    os.makedirs(incident_dir)
    set_build_info_metric()
    capture = InputCaptureRecorder(
        CaptureConfig(window_s=3600.0, max_bytes=32 << 20),
        meta={"block_size": BLOCK_SIZE, "hash_seed": "", "model": MODEL},
    )
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            cache_stats=False,
        ),
        tokenizer=_ReplayTokenizer(),
        capture_recorder=capture,
    )
    indexer.run()
    event_pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(concurrency=2),
        capture=capture,
    )
    event_pool.start()
    slo = SloEngine(window_fast_s=5.0, window_slow_s=30.0)
    slo.register(
        SloSpec(
            "smoke_pressure",
            kind="gauge",
            objective=1.0,
            degraded_bound=2.0,
            description="whatif-smoke controllable pressure",
        ),
        lambda: (0.0, 0.0),
    )
    incidents = IncidentManager(
        incident_dir,
        capture=capture,
        sources={
            "traces": lambda: {"stats": TRACER.stats()},
            "slo": lambda: slo.last_payload() or {"no_data": True},
        },
        index=indexer.kv_block_index,
        min_interval_s=60.0,
    )
    server = serve(
        indexer,
        host="127.0.0.1",
        port=0,
        slo=slo,
        capture=capture,
        incidents=incidents,
    )
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        # Enough traffic that the bundle's capture is worth replaying.
        seqs = {}
        for p in range(6):
            tokens = [p * 1000 + i + 1 for i in range(BLOCK_SIZE * 12)]
            for pod_i in range(1 + p % 3):
                pod = f"pod-{pod_i}"
                seqs[pod] = seqs.get(pod, 0) + 1
                event_pool.add_task(
                    Message(
                        topic=f"kv@{pod}@{MODEL}",
                        payload=EventBatch(
                            ts=1.0,
                            events=[
                                BlockStored(
                                    block_hashes=[
                                        50_000 + p * 100 + pod_i * 40 + b
                                        for b in range(12)
                                    ],
                                    parent_block_hash=None,
                                    token_ids=tokens[: 12 * BLOCK_SIZE],
                                    block_size=BLOCK_SIZE,
                                    medium="hbm",
                                )
                            ],
                        ).encode(),
                        pod_identifier=pod,
                        model_name=MODEL,
                        seq=seqs[pod],
                    )
                )
            event_pool.drain()
            indexer.get_pod_scores(
                " ".join(f"t{t}" for t in tokens), MODEL, None
            )

        surfaces = get_json(base, "/debug/")["surfaces"]
        whatif_row = [
            row for row in surfaces if row["path"] == "/debug/whatif"
        ]
        assert whatif_row and whatif_row[0]["enabled"], surfaces

        manifest = post_json(base, "/admin/incident", {"reason": "smoke"})
        incident_id = manifest["id"]
        detail = get_json(base, f"/debug/incidents/{incident_id}")
        assert detail["id"] == incident_id
        assert detail["manifest"]["reason"] == "admin:smoke"
        inventory = {row["file"]: row["bytes"] for row in detail["inventory"]}
        assert "capture.cbor" in inventory and inventory["capture.cbor"] > 0
        assert "manifest.json" in inventory
        bad = urllib.request.Request(
            base + "/debug/incidents/inc-nope", method="GET"
        )
        try:
            urllib.request.urlopen(bad, timeout=10)
            raise AssertionError("unknown incident id must 404")
        except urllib.error.HTTPError as exc:
            assert exc.code == 404, exc.code
        print(
            f"whatif-smoke: incident detail ok ({incident_id}, "
            f"{len(inventory)} files)"
        )

        verdict = post_json(
            base,
            "/admin/whatif",
            {"bundle": incident_id, "kind": "ab", "speed": 6},
        )
        assert verdict["summary"]["kind"] == "ab"
        assert verdict["summary"]["digest_equal"] is True
        run_verdict = post_json(
            base,
            "/admin/whatif",
            {"bundle": incident_id, "kind": "run", "arm": "mode=cluster"},
        )
        assert run_verdict["summary"]["slo_final"] in (
            "healthy",
            "degraded",
            "violated",
        )
        ring = get_json(base, "/debug/whatif")
        assert ring["results"] >= 2, ring
        assert ring["results_list"][0]["kind"] == "run"
        metrics_text = get_text(base, "/metrics")
        for family in (
            "kvtpu_whatif_runs_total",
            "kvtpu_whatif_events_total",
            "kvtpu_whatif_hit_rate",
        ):
            assert family in metrics_text, f"missing metric {family}"
        print(
            "whatif-smoke: service surfaces ok (/debug/whatif ring "
            f"holds {ring['results']} results)"
        )
    finally:
        server.shutdown()
        event_pool.shutdown()
        indexer.shutdown()


def check_perf_trend_gate(workdir):
    env = dict(os.environ)
    trend = os.path.join(REPO, "hack", "perf_trend.py")
    honest = subprocess.run(
        [sys.executable, trend],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert honest.returncode == 0, (
        f"perf-trend failed on the honest trajectory:\n{honest.stdout}"
        f"\n{honest.stderr}"
    )
    assert "live reference A/B" in honest.stdout, honest.stdout

    planted_dir = os.path.join(workdir, "planted")
    os.makedirs(planted_dir)
    with open(os.path.join(REPO, "WHATIF_r01.json")) as handle:
        artifact = json.load(handle)
    live_hit = artifact["headlines"]["whatif.hit_rate"]
    artifact["headlines"]["whatif.hit_rate"] = min(1.0, live_hit * 1.5)
    with open(
        os.path.join(planted_dir, "WHATIF_r01.json"), "w"
    ) as handle:
        json.dump(artifact, handle)
    planted = subprocess.run(
        [sys.executable, trend, "--dir", planted_dir],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert planted.returncode != 0, (
        "perf-trend must fail on a planted capacity regression:\n"
        f"{planted.stdout}"
    )
    assert "whatif.hit_rate (live)" in planted.stdout, planted.stdout
    print(
        "whatif-smoke: perf-trend gate ok (honest pass, planted "
        "regression fail)"
    )

    # The recorded baseline IS the live measurement — the headlines
    # are deterministic, so an inequality here means the engine's
    # behavior changed without regenerating the artifacts.
    ab = whatif.reference_ab()
    live = whatif.gate_headlines(ab)
    with open(os.path.join(REPO, "WHATIF_r01.json")) as handle:
        recorded = json.load(handle)["headlines"]
    assert live == recorded, (
        "deterministic headlines drifted from WHATIF_r01.json: "
        f"live {live} vs recorded {recorded} — regenerate the "
        "artifact (see hack/make_reference_capture.py docstring)"
    )
    print("whatif-smoke: recorded baseline matches live bit-for-bit")


def main() -> None:
    assert os.path.isfile(REFERENCE), (
        f"missing {REFERENCE}; run python hack/make_reference_capture.py"
    )
    workdir = tempfile.mkdtemp(prefix="kvtpu-whatif-smoke-")
    try:
        storm = check_composition(workdir)
        check_ab(storm)
        check_service(workdir)
        check_perf_trend_gate(workdir)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    print("whatif smoke completed successfully")


if __name__ == "__main__":
    main()
