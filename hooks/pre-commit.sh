#!/usr/bin/env bash
# Developer pre-commit gate (reference: hooks/pre-commit.sh — lint then
# tests).  Install with:
#   ln -s ../../hooks/pre-commit.sh .git/hooks/pre-commit
set -e

cd "$(git rev-parse --show-toplevel)"

echo "-> lint"
make lint

echo "-> kvlint (project invariants)"
make kvlint

echo "-> tests"
make test

echo "ok: all checks passed"
