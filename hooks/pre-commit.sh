#!/usr/bin/env bash
# Developer pre-commit gate (reference: hooks/pre-commit.sh — lint then
# tests).  Install with:
#   ln -s ../../hooks/pre-commit.sh .git/hooks/pre-commit
set -e

cd "$(git rev-parse --show-toplevel)"

echo "-> lint"
make lint

echo "-> raceguard manifest (regenerate if annotations changed)"
if ! python -m hack.kvlint llm_d_kv_cache_manager_tpu --check-manifest \
    >/dev/null 2>&1; then
  python -m hack.kvlint llm_d_kv_cache_manager_tpu --emit-manifest
  git add hack/kvlint/raceguard_manifest.json
fi

echo "-> kvlint (project invariants)"
make kvlint

echo "-> tests"
make test

echo "ok: all checks passed"
