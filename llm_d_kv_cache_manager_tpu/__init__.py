"""llm-d KV-cache manager, TPU-native.

A TPU-first re-design of the llm-d KV-cache indexing / routing stack
(reference: sagiahrac/llm-d-kv-cache-manager).  Two stacks:

* **Indexer stack** (`kvcache`, `kvevents`, `tokenization`, `preprocessing`,
  `metrics`, `api`): a fleet of vLLM-TPU pods emits KVEvents whenever KV
  blocks are stored/evicted; a central Indexer ingests them into a global
  block-hash -> {pod, tier} index and scores pods by longest resident
  prefix for KV-cache-aware routing.

* **Offload stack** (`offload`, `native`, `models`, `ops`, `parallel`): a
  TPU-native KV-offload connector paging KV blocks between TPU HBM and
  host/shared-storage via XLA host-offload, plus a paged-attention serving
  model used to exercise it end-to-end.

Import as ``import llm_d_kv_cache_manager_tpu as kvtpu``.
"""

__version__ = "0.1.0"

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (  # noqa: F401
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)

# Guarded-by runtime enforcement (KVTPU_RACEGUARD=1): instrument every
# manifest class at import time so all later constructions are covered.
# Unarmed this is a single env check — no manifest read, no descriptors.
import os as _os  # noqa: E402

if _os.environ.get("KVTPU_RACEGUARD", "") in ("1", "true", "yes"):
    from llm_d_kv_cache_manager_tpu.utils import raceguard as _raceguard

    _raceguard.install_from_env()
