"""Cache-efficiency analytics: hit-attribution ledger + index-truth
auditor (docs/observability.md).

The auditor names are lazy (PEP 562): they pull the kvevents stack
(and transitively zmq) for the ``InventorySource`` contract, which the
ledger — constructed by every ``Indexer`` — must not drag onto the
scoring path's import graph.
"""

from llm_d_kv_cache_manager_tpu.analytics.ledger import (
    CacheStatsLedger,
    LedgerConfig,
)
from llm_d_kv_cache_manager_tpu.analytics.windows import (
    Frame,
    WindowRing,
    standard_windows,
)

_AUDITOR_EXPORTS = ("AuditorConfig", "AuditReport", "IndexAuditor")

__all__ = [
    "CacheStatsLedger",
    "LedgerConfig",
    "Frame",
    "WindowRing",
    "standard_windows",
    *_AUDITOR_EXPORTS,
]


def __getattr__(name):
    if name in _AUDITOR_EXPORTS:
        from llm_d_kv_cache_manager_tpu.analytics import auditor

        return getattr(auditor, name)
    raise AttributeError(name)
