"""Index-truth auditor: measured staleness instead of inferred.

The index is advisory — continuously rebuilt from engine events — so
its claims drift from pod reality whenever events are lost, reordered,
or late.  PR 6 made *detected* gaps repair themselves (resync); this
auditor closes the remaining blind spot: divergence with **no gap on
the wire** (a pod that silently restarted, an inventory surface that
disagrees, an eviction burst the budget shed).  It periodically pulls a
pod's block inventory through the same pluggable
:class:`~llm_d_kv_cache_manager_tpu.kvevents.resync.InventorySource`
the resync path uses and diffs it against the index's view of that
pod, emitting per-pod divergence as a first-class, alertable quantity:

* **phantom** — the index claims a block the pod no longer holds
  (stale hits mis-route traffic toward it);
* **missing** — the pod holds a block the index never learned
  (lost hit rate: traffic routes away from a warm pod);
* **wrong_tier** — both agree the block exists but disagree on the
  memory tier (scores shift by the tier-weight delta).

``divergence_ratio = (phantom + missing + wrong_tier) / |union|`` per
pod lands in ``kvtpu_index_divergence_ratio{pod=...}``; audit outcomes
count in ``kvtpu_index_audits_total{outcome=...}`` and divergent
blocks in ``kvtpu_index_audit_blocks_total{kind=...}``.  Every audit
also appends to a bounded in-memory **audit log** (the flight
recorder's retention style: a ring of recent audits plus a reservoir
of the divergent ones), surfaced via ``GET /debug/cachestats``.

Inventory blocks carry *engine* hashes + token ids, exactly like
``BlockStored`` events; the auditor recomputes request keys with the
indexer's own token processor (parents resolved inside the inventory
first, then through the dumped engine map), so per-engine hash schemes
cannot fake divergence.  The index view comes from ``dump_entries()``
— O(index size), same class of administrative operation as
``purge_pod``; the audit interval (env ``AUDIT_INTERVAL_S``) bounds
the amortized cost.  Durable backends whose ``dump_entries`` is a
documented no-op (Redis) surface no pods to audit, so cycles there are
empty rather than fake-clean.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Set, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import Index
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    EMPTY_BLOCK_HASH,
    engine_hash_to_uint64,
)
from llm_d_kv_cache_manager_tpu.kvevents.resync import (
    InventorySource,
    PodInventory,
)
from llm_d_kv_cache_manager_tpu.metrics.collector import (
    METRICS,
    safe_label,
)
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("analytics.auditor")

DEFAULT_AUDIT_INTERVAL_S = 0.0  # disabled until explicitly enabled
DEFAULT_LOG_KEEP = 64
DEFAULT_DIVERGENT_KEEP = 32
DEFAULT_TIER = "hbm"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("invalid %s=%r; using %s", name, raw, default)
        return default


@dataclass
class AuditorConfig:
    # Seconds between audit cycles; <= 0 means the background worker
    # never runs (audits only via explicit run_cycle()/audit_pod()).
    interval_s: float = DEFAULT_AUDIT_INTERVAL_S
    # Pods audited per cycle (round-robin across cycles); 0 = all.
    pods_per_cycle: int = 0
    # Default tier when inventory blocks omit medium (must match the
    # event pool's default_device_tier or tier diffs are noise).
    default_tier: str = DEFAULT_TIER
    # Audit-log retention (ring of recent + reservoir of divergent).
    log_keep: int = DEFAULT_LOG_KEEP
    divergent_keep: int = DEFAULT_DIVERGENT_KEEP

    @classmethod
    def from_env(cls) -> "AuditorConfig":
        return cls(
            interval_s=_env_float(
                "AUDIT_INTERVAL_S", DEFAULT_AUDIT_INTERVAL_S
            )
        )


@dataclass
class AuditReport:
    """One pod audit: the diff and its provenance."""

    pod: str
    outcome: str  # clean | divergent | failed | unsupported
    ts_unix: float = 0.0
    duration_s: float = 0.0
    index_claims: int = 0
    inventory_blocks: int = 0
    phantom: int = 0
    missing: int = 0
    wrong_tier: int = 0
    unresolvable: int = 0
    divergence_ratio: float = 0.0
    detail: str = ""
    # Small samples of divergent request keys, for operator drill-down.
    phantom_sample: List[str] = field(default_factory=list)
    missing_sample: List[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "pod": self.pod,
            "outcome": self.outcome,
            "ts_unix": self.ts_unix,
            "duration_ms": round(self.duration_s * 1e3, 2),
            "index_claims": self.index_claims,
            "inventory_blocks": self.inventory_blocks,
            "phantom": self.phantom,
            "missing": self.missing,
            "wrong_tier": self.wrong_tier,
            "unresolvable": self.unresolvable,
            "divergence_ratio": round(self.divergence_ratio, 4),
            "detail": self.detail,
            "phantom_sample": self.phantom_sample,
            "missing_sample": self.missing_sample,
        }


_SAMPLE_KEYS = 8


class IndexAuditor:
    """Background index-truth sampler over one index + inventory source."""

    def __init__(
        self,
        index: Index,
        token_processor,
        source: InventorySource,
        config: Optional[AuditorConfig] = None,
    ) -> None:
        self._index = index
        self._token_processor = token_processor
        self._source = source
        self.config = config or AuditorConfig.from_env()
        # Leaf lock + wake channel (the ResyncManager shape).  Nothing
        # else is acquired under it: audits run with it released.
        self._lock = lockorder.tracked(
            threading.Condition(), "IndexAuditor._lock"
        )
        self._log: Deque[AuditReport] = deque(
            maxlen=max(1, self.config.log_keep)
        )  # guarded-by: _lock
        self._divergent: Deque[AuditReport] = deque(
            maxlen=max(1, self.config.divergent_keep)
        )  # guarded-by: _lock
        self._cycles = 0  # guarded-by: _lock
        self._audits = 0  # guarded-by: _lock
        self._last_cycle_unix: Optional[float] = None  # guarded-by: _lock
        self._ratio_by_pod: Dict[str, float] = {}  # guarded-by: _lock
        self._rr_cursor = 0  # guarded-by: _lock
        self._stopping = False  # guarded-by: _lock
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        """Start the periodic worker (no-op when interval_s <= 0)."""
        if self._thread is not None or self.config.interval_s <= 0:
            return
        with self._lock:
            self._stopping = False
        # gil-atomic: lifecycle ref; start/close are control-plane
        self._thread = threading.Thread(
            target=self._run, name="kvtpu-index-auditor", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        with self._lock:
            self._stopping = True
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            # gil-atomic: lifecycle ref; start/close are control-plane
            self._thread = None

    def _run(self) -> None:
        while True:
            with self._lock:
                if self._stopping:
                    return
                self._lock.wait(self.config.interval_s)
                if self._stopping:
                    return
            try:
                self.run_cycle()
            except Exception:  # noqa: BLE001 — worker must survive
                logger.exception("audit cycle failed")

    # -- the audit -------------------------------------------------------

    def _index_view(
        self,
    ) -> Tuple[Dict[str, Dict[int, Set[str]]], Dict[int, int]]:
        """Per-pod index claims and the engine->request map, from one
        dump: ``claims[pod][request_key] = {tiers}``."""
        block_entries, engine_map = self._index.dump_entries()
        claims: Dict[str, Dict[int, Set[str]]] = {}
        for request_key, pods in block_entries:
            for entry in pods:
                per_key = claims.setdefault(entry.pod_identifier, {})
                per_key.setdefault(request_key, set()).add(
                    entry.device_tier
                )
        return claims, dict(engine_map)

    def _inventory_view(
        self,
        inventory: PodInventory,
        engine_map: Dict[int, int],
    ) -> Tuple[Dict[int, Set[str]], int]:
        """Recompute the inventory's request keys with the indexer's
        own hash chain: ``{request_key: {tiers}}`` plus the count of
        blocks whose parent chain could not be resolved.  Tier SETS,
        mirroring the index side: a pod can hold one block on several
        tiers, and a single-string view would make the diff depend on
        inventory block ordering."""
        expected: Dict[int, Set[str]] = {}
        local_map: Dict[int, int] = {}
        unresolvable = 0
        for block in inventory.blocks:
            engine_keys = []
            try:
                for raw in block.block_hashes:
                    engine_keys.append(engine_hash_to_uint64(raw))
            except (TypeError, ValueError):
                unresolvable += len(block.block_hashes)
                continue
            parent_request = EMPTY_BLOCK_HASH
            if block.parent_block_hash is not None:
                try:
                    parent_engine = engine_hash_to_uint64(
                        block.parent_block_hash
                    )
                except (TypeError, ValueError):
                    unresolvable += len(engine_keys)
                    continue
                parent_request = local_map.get(parent_engine)
                if parent_request is None:
                    parent_request = engine_map.get(parent_engine)
                if parent_request is None:
                    try:
                        parent_request = self._index.get_request_key(
                            parent_engine
                        )
                    except KeyError:
                        unresolvable += len(engine_keys)
                        continue
            model = block.lora_name or inventory.model_name
            request_keys = self._token_processor.tokens_to_kv_block_keys(
                parent_request, block.token_ids, model
            )
            overlap = min(len(request_keys), len(engine_keys))
            if overlap < len(engine_keys):
                unresolvable += len(engine_keys) - overlap
            tier = (
                block.medium.lower()
                if block.medium
                else self.config.default_tier
            )
            for engine_key, request_key in zip(
                engine_keys[:overlap], request_keys[:overlap]
            ):
                local_map[engine_key] = request_key
                expected.setdefault(request_key, set()).add(tier)
        return expected, unresolvable

    def audit_pod(
        self,
        pod: str,
        claims: Optional[Dict[int, Set[str]]] = None,
        engine_map: Optional[Dict[int, int]] = None,
    ) -> AuditReport:
        """Audit one pod now; pass ``claims``/``engine_map`` from a
        shared dump when auditing many pods in one cycle."""
        started = time.perf_counter()
        if claims is None or engine_map is None:
            all_claims, engine_map = self._index_view()
            claims = all_claims.get(pod, {})
        report = AuditReport(pod=pod, outcome="clean", ts_unix=time.time())
        report.index_claims = len(claims)
        try:
            inventory = self._source.fetch_inventory(pod)
        except Exception as exc:  # noqa: BLE001 — source may do I/O
            inventory = None
            report.detail = f"inventory fetch raised: {exc!r}"
        if inventory is None:
            report.outcome = "failed"
            report.detail = report.detail or "inventory unavailable"
            report.duration_s = time.perf_counter() - started
            self._finish(report)
            return report

        expected, unresolvable = self._inventory_view(inventory, engine_map)
        report.inventory_blocks = len(expected)
        report.unresolvable = unresolvable

        phantom = [key for key in claims if key not in expected]
        missing = [key for key in expected if key not in claims]
        # Wrong tier only when NO tier agrees: a pod holding a block
        # on more tiers than the index knows is an under-claim, not a
        # mis-claim, and must not flip with inventory ordering.
        wrong_tier = [
            key
            for key, tiers in expected.items()
            if key in claims and tiers.isdisjoint(claims[key])
        ]
        union = len(claims.keys() | expected.keys())
        report.phantom = len(phantom)
        report.missing = len(missing)
        report.wrong_tier = len(wrong_tier)
        report.divergence_ratio = (
            (report.phantom + report.missing + report.wrong_tier) / union
            if union
            else 0.0
        )
        report.phantom_sample = [
            f"{key:016x}" for key in sorted(phantom)[:_SAMPLE_KEYS]
        ]
        report.missing_sample = [
            f"{key:016x}" for key in sorted(missing)[:_SAMPLE_KEYS]
        ]
        if report.divergence_ratio > 0.0:
            report.outcome = "divergent"
        report.duration_s = time.perf_counter() - started
        self._finish(report)
        return report

    def _finish(self, report: AuditReport) -> None:
        pod_label = safe_label(report.pod)
        with self._lock:
            self._audits += 1
            self._log.append(report)
            if report.outcome == "divergent":
                self._divergent.append(report)
            if report.outcome in ("clean", "divergent"):
                self._ratio_by_pod[report.pod] = report.divergence_ratio
        METRICS.index_audits.labels(outcome=report.outcome).inc()
        if report.outcome in ("clean", "divergent"):
            METRICS.index_divergence_ratio.labels(pod=pod_label).set(
                report.divergence_ratio
            )
            if report.phantom:
                METRICS.index_audit_blocks.labels(kind="phantom").inc(
                    report.phantom
                )
            if report.missing:
                METRICS.index_audit_blocks.labels(kind="missing").inc(
                    report.missing
                )
            if report.wrong_tier:
                METRICS.index_audit_blocks.labels(kind="wrong_tier").inc(
                    report.wrong_tier
                )
        if report.outcome == "divergent":
            logger.warning(
                "index divergence on pod %s: ratio %.4f "
                "(phantom=%d missing=%d wrong_tier=%d over %d claims / "
                "%d inventory blocks)",
                report.pod,
                report.divergence_ratio,
                report.phantom,
                report.missing,
                report.wrong_tier,
                report.index_claims,
                report.inventory_blocks,
            )

    def run_cycle(self) -> List[AuditReport]:
        """One audit cycle: dump the index once, audit the selected
        pods (round-robin slice when ``pods_per_cycle`` bounds it)."""
        claims_by_pod, engine_map = self._index_view()
        pods = sorted(claims_by_pod)
        if not pods:
            with self._lock:
                departed = list(self._ratio_by_pod)
                self._ratio_by_pod.clear()
                self._cycles += 1
                self._last_cycle_unix = time.time()
            for pod in departed:
                try:
                    METRICS.index_divergence_ratio.remove(safe_label(pod))
                except KeyError:
                    pass
            return []
        per_cycle = self.config.pods_per_cycle
        if per_cycle and per_cycle < len(pods):
            with self._lock:
                start = self._rr_cursor % len(pods)
                self._rr_cursor = start + per_cycle
            selected = [
                pods[(start + i) % len(pods)] for i in range(per_cycle)
            ]
        else:
            selected = pods
        reports = [
            self.audit_pod(
                pod, claims=claims_by_pod.get(pod, {}), engine_map=engine_map
            )
            for pod in selected
        ]
        # Pods that left the index (decommissioned, purged) must not
        # keep a stale divergence reading alive forever — in a churning
        # fleet the per-pod map and the gauge's label series would
        # otherwise grow monotonically and /healthz would alert on
        # pods that no longer exist.
        current = set(pods)
        with self._lock:
            departed = [
                pod for pod in self._ratio_by_pod if pod not in current
            ]
            for pod in departed:
                # The earlier read is in the mutually-exclusive
                # empty-index early-return branch; this block derives
                # `departed` under its own acquisition.
                del self._ratio_by_pod[pod]  # kvlint: atomic-ok
            self._cycles += 1
            self._last_cycle_unix = time.time()
        for pod in departed:
            try:
                METRICS.index_divergence_ratio.remove(safe_label(pod))
            except KeyError:
                pass  # label series never created (audit never scored it)
        return reports

    # -- read surface ----------------------------------------------------

    def status(self) -> dict:
        """The /healthz analytics block's audit half."""
        with self._lock:
            divergent = {
                pod: round(ratio, 4)
                for pod, ratio in sorted(self._ratio_by_pod.items())
                if ratio > 0.0
            }
            return {
                "interval_s": self.config.interval_s,
                "running": self._thread is not None,
                "cycles": self._cycles,
                "audits": self._audits,
                "last_cycle_unix": self._last_cycle_unix,
                "pods_tracked": len(self._ratio_by_pod),
                "divergent_pods": divergent,
            }

    def recent(self, limit: int = 50) -> List[dict]:
        """Newest-first audit log (the flight-recorder-style ring)."""
        with self._lock:
            return [r.to_dict() for r in list(self._log)[::-1][:limit]]

    def divergent(self, limit: int = 50) -> List[dict]:
        """Newest-first reservoir of divergent audits."""
        with self._lock:
            return [r.to_dict() for r in list(self._divergent)[::-1][:limit]]
