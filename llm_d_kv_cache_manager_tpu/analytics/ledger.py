"""Hit-attribution ledger: the fleet-level cache-efficiency aggregator.

The scoring read path answers one request at a time; this ledger turns
that stream into the fleet-level questions PR 3's per-request explain
cannot: *what fraction of scored prefixes actually hit, per prefix
family and per tier, and how quickly do families come back?*

One :meth:`record` per scored request, keyed by **prefix family** —
the chained block key at block ``family_blocks-1``.  Block keys are
chained hashes, so that single key already commits to the whole first-k
token prefix: two prompts share a family iff they share their first
``family_blocks`` blocks, without the ledger storing any token text
(the HashEvict observation from PAPERS.md — cheap structural identity
from hashes the read path already computed).

Per family the ledger keeps rolling hit/partial/miss counts, block
match totals, per-tier hit splits, a reuse **inter-arrival EWMA** (the
predictive-eviction signal ROADMAP item 4 needs), and last-seen
bookkeeping; globally it keeps the same counts windowed (1m/10m/1h
rings of CBOR-serializable frames, ``windows.py``) plus a
**reuse-distance histogram** (distinct scored requests between
re-encounters of a family — the classic working-set signal).

Hot-path contract (the tentpole's constraint):

* ``record`` is called by the indexer AFTER scoring completes, outside
  every index shard lock;
* the family table is **lock-striped** (``stripes`` locks, key-masked)
  and LRU-bounded (``max_families``), so memory is bounded and
  concurrent scoring threads rarely share a stripe lock;
* the aggregate windows take one short leaf lock per record;
* ``sample_rate`` (env ``CACHESTATS_SAMPLE_RATE``) gates everything —
  an unsampled request costs one RNG draw, exactly the tracer's
  pattern.  At rates < 1 the ledger is an unbiased sample, not a total
  count (same caveat as ``kvtpu_stage_latency_seconds``).

Classification: a request **hit** when its best pod's consecutive
matched blocks reached ``hit_ratio`` of the prompt's full block chain
(default 1.0: the whole chain), **partial** when anything matched,
**miss** otherwise.  The bench's ``cache_analytics`` regime validates
the reported hit rate against engine-side ground truth (±2%).
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from llm_d_kv_cache_manager_tpu.analytics.windows import (
    Frame,
    standard_windows,
)
from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("analytics.ledger")

DEFAULT_SAMPLE_RATE = 1.0
DEFAULT_FAMILY_BLOCKS = 4
DEFAULT_MAX_FAMILIES = 4096
DEFAULT_STRIPES = 8
# Per-tier attribution walks every scored block, the one analytics
# cost that scales with prompt length; by default every 4th sampled
# request pays it (the split is an unbiased sample, like
# kvtpu_stage_latency_seconds).  1 = every sampled request.
DEFAULT_TIER_SAMPLE = 4

# Inter-arrival EWMA smoothing: ~the last 6-7 arrivals dominate.
EWMA_ALPHA = 0.3

# Prometheus-side flush cadence: record() accumulates outcome/tier/
# reuse deltas in plain ints and drains them to the registry every
# this-many records (and on every snapshot/stats read), so the hot
# path never pays a labels() resolution or histogram observe.  The
# exposition lags the ledger by at most one batch.
METRICS_FLUSH_EVERY = 32

# Reuse-distance histogram bucket upper bounds (requests), power-of-two
# ladder; the last bucket is open-ended.
REUSE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096, 16384)

# Stripe locks are leaves acquired one at a time (never nested with
# each other, the aggregate lock, or anything else — the family table
# is a plain dict, no inner lock); the ascending rank arms the
# watchdog in case that ever changes.
# kvlint: lock-order: CacheStatsLedger._stripe_lock ascending
lockorder.declare_ascending("CacheStatsLedger._stripe_lock")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("invalid %s=%r; using %s", name, raw, default)
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        value = int(raw)
        if value <= 0:
            raise ValueError(raw)
        return value
    except ValueError:
        logger.warning("invalid %s=%r; using %s", name, raw, default)
        return default


@dataclass
class LedgerConfig:
    # Fraction of scored requests recorded (0 disables recording).
    sample_rate: float = DEFAULT_SAMPLE_RATE
    # Prefix-family identity: the chained key at this block index - 1
    # (shorter prompts use their last key).
    family_blocks: int = DEFAULT_FAMILY_BLOCKS
    # LRU bound on tracked families (total across stripes).
    max_families: int = DEFAULT_MAX_FAMILIES
    # Lock stripes for the family table (rounded up to a power of two).
    stripes: int = DEFAULT_STRIPES
    # Track the per-tier hit split on every Nth sampled request (the
    # only analytics cost proportional to prompt length); 1 = always.
    tier_sample: int = DEFAULT_TIER_SAMPLE
    # A request "hit" when best matched blocks >= hit_ratio * total
    # blocks; 1.0 = the full chain.
    hit_ratio: float = 1.0
    # Absolute override: when set, a request "hit" when best matched
    # blocks >= hit_blocks regardless of the prompt's total (workloads
    # with a known shared-prefix length, e.g. the bench's churn regime
    # where the engine's own hit criterion is the 512-block prefix).
    hit_blocks: Optional[int] = None

    @classmethod
    def from_env(cls) -> "LedgerConfig":
        sample_rate = _env_float(
            "CACHESTATS_SAMPLE_RATE", DEFAULT_SAMPLE_RATE
        )
        if not 0.0 <= sample_rate <= 1.0:
            # Env knobs warn-and-default, never crash the Indexer
            # construction path (the ledger is default-on there).
            logger.warning(
                "CACHESTATS_SAMPLE_RATE=%s outside [0, 1]; using %s",
                sample_rate,
                DEFAULT_SAMPLE_RATE,
            )
            sample_rate = DEFAULT_SAMPLE_RATE
        return cls(
            sample_rate=sample_rate,
            family_blocks=_env_int(
                "CACHESTATS_FAMILY_BLOCKS", DEFAULT_FAMILY_BLOCKS
            ),
            max_families=_env_int(
                "CACHESTATS_MAX_FAMILIES", DEFAULT_MAX_FAMILIES
            ),
            tier_sample=_env_int(
                "CACHESTATS_TIER_SAMPLE", DEFAULT_TIER_SAMPLE
            ),
        )


class FamilyStats:
    """Rolling per-prefix-family counters."""

    __slots__ = (
        "requests",
        "hits",
        "partials",
        "misses",
        "blocks_matched",
        "blocks_total",
        "tiers",
        "first_seen",
        "last_seen",
        "last_seq",
        "ewma_interarrival_s",
        "model",
    )

    def __init__(self, now: float, seq: int, model: str) -> None:
        self.requests = 0
        self.hits = 0
        self.partials = 0
        self.misses = 0
        self.blocks_matched = 0
        self.blocks_total = 0
        self.tiers: Dict[str, int] = {}
        self.first_seen = now
        self.last_seen = now
        self.last_seq = seq
        self.ewma_interarrival_s: Optional[float] = None
        self.model = model

    def to_dict(self, now: float) -> dict:
        requests = self.requests
        return {
            "requests": requests,
            "hits": self.hits,
            "partials": self.partials,
            "misses": self.misses,
            "hit_rate": round(self.hits / requests, 4) if requests else None,
            "blocks_matched": self.blocks_matched,
            "blocks_total": self.blocks_total,
            "block_hit_rate": (
                round(self.blocks_matched / self.blocks_total, 4)
                if self.blocks_total
                else None
            ),
            "tiers": dict(self.tiers),
            "ewma_interarrival_s": (
                round(self.ewma_interarrival_s, 4)
                if self.ewma_interarrival_s is not None
                else None
            ),
            "idle_s": round(now - self.last_seen, 3),
            "model": self.model,
        }


class CacheStatsLedger:
    """Lock-striped online aggregator over the scoring stream."""

    def __init__(self, config: Optional[LedgerConfig] = None) -> None:
        self.config = config or LedgerConfig.from_env()
        if not 0.0 <= self.config.sample_rate <= 1.0:
            raise ValueError("sample_rate must be in [0, 1]")
        if self.config.family_blocks <= 0:
            raise ValueError("family_blocks must be positive")
        n = 1
        while n < max(1, self.config.stripes):
            n <<= 1
        self._mask = n - 1
        self._per_stripe = max(1, -(-self.config.max_families // n))
        # Plain insertion-ordered dicts with move-to-end on repeat:
        # true LRU semantics at two dict ops per touch instead of a
        # full LRUCache (whose internal lock would be redundant under
        # the stripe lock — and measurable on the scoring path).
        self._stripes: List[Dict[int, FamilyStats]] = [
            {} for _ in range(n)
        ]
        self._stripe_locks = [
            lockorder.tracked(
                threading.Lock(), "CacheStatsLedger._stripe_lock", rank=i
            )
            for i in range(n)
        ]
        # Aggregate state: windows, totals, the request sequence that
        # reuse distance is measured in, and the reuse histogram.  One
        # leaf lock, never nested with stripe locks (record() releases
        # the stripe before touching the aggregate side).
        self._agg_lock = lockorder.tracked(
            threading.Lock(), "CacheStatsLedger._agg_lock"
        )
        self._windows = standard_windows()  # guarded-by: _agg_lock
        # 1-second accumulator: record() lands counts here (one Frame
        # update) and the completed second is absorbed into all three
        # rings on roll-over — three ring walks per second, not per
        # record.  Slot -1 = empty sentinel (folded lazily).
        self._acc = Frame(-1)  # guarded-by: _agg_lock
        self._seq = 0  # guarded-by: _agg_lock
        self._recorded = 0  # guarded-by: _agg_lock
        self._hits = 0  # guarded-by: _agg_lock
        self._partials = 0  # guarded-by: _agg_lock
        self._misses = 0  # guarded-by: _agg_lock
        self._blocks_matched = 0  # guarded-by: _agg_lock
        self._blocks_total = 0  # guarded-by: _agg_lock
        self._tiers: Dict[str, int] = {}  # guarded-by: _agg_lock
        self._tier_untracked = 0  # guarded-by: _agg_lock
        self._reuse_hist = [0] * (len(REUSE_BUCKETS) + 1)  # guarded-by: _agg_lock
        self._families_evicted = 0  # guarded-by: _agg_lock
        # Prometheus deltas pending flush (see METRICS_FLUSH_EVERY).
        self._pending_outcomes = {
            "hit": 0, "partial": 0, "miss": 0
        }  # guarded-by: _agg_lock
        self._pending_tiers: Dict[str, int] = {}  # guarded-by: _agg_lock
        # Reuse-distance deltas pending flush, per bucket (+ the sum of
        # distances, for the histogram's _sum series).
        self._pending_reuse = [0] * (len(REUSE_BUCKETS) + 1)  # guarded-by: _agg_lock
        self._pending_reuse_sum = 0  # guarded-by: _agg_lock
        self._since_flush = 0  # guarded-by: _agg_lock
        # Pre-resolved metric children: labels() resolution costs more
        # than the increment itself, so the flush path resolves each
        # child once.
        self._outcome_children = {
            outcome: METRICS.cachestats_requests.labels(outcome=outcome)
            for outcome in ("hit", "partial", "miss")
        }
        self._tier_children: Dict[str, object] = {}
        # Config reads hoisted off the per-record path.
        self._hit_blocks = (
            max(1, self.config.hit_blocks)
            if self.config.hit_blocks is not None
            else None
        )
        self._hit_ratio = self.config.hit_ratio
        self._tier_tick = 0  # lock-free by design (see tier_detail_due)
        # Written once (False -> True) under _agg_lock by close();
        # deliberately read lock-free on the record path — the flag
        # only ever advances, and the stripe section re-checks it
        # inside the stripe lock, which close()'s sweep also takes, so
        # a post-sweep insert can never slip through.
        self._closed = False

    # -- hot-path surface ------------------------------------------------

    def should_sample(self) -> bool:
        """The indexer's cheap per-request gate: when False, the
        request contributes nothing (and pays nothing beyond this RNG
        draw)."""
        rate = self.config.sample_rate
        if rate >= 1.0:
            return True
        if rate <= 0.0:
            return False
        return random.random() < rate

    def family_key(self, chain_keys, total_blocks: int) -> Optional[int]:
        """Prefix-family id for a request's chained block keys: the key
        at ``family_blocks - 1`` (chained, so it commits to the whole
        first-k prefix), clamped to the chain actually available."""
        if not chain_keys:
            return None
        index = min(self.config.family_blocks, total_blocks, len(chain_keys))
        return chain_keys[index - 1]

    def classify(self, matched_blocks: int, total_blocks: int) -> str:
        if total_blocks <= 0:
            return "miss"
        threshold = self._hit_blocks
        if threshold is None:
            # Round-half-up in int math (the hot path calls this per
            # request; round() costs a surprising amount here).
            threshold = int(self._hit_ratio * total_blocks + 0.5) or 1
        if matched_blocks >= threshold:
            return "hit"
        if matched_blocks > 0:
            return "partial"
        return "miss"

    def record(
        self,
        family: Optional[int],
        model: str,
        total_blocks: int,
        matched_blocks: int,
        tiers: Optional[Dict[str, int]] = None,
        now: Optional[float] = None,
    ) -> str:
        """Fold one scored request into the ledger; returns the
        hit/partial/miss classification.  Called outside every index
        lock; takes the aggregate lock and one stripe lock
        sequentially (never nested)."""
        if now is None:
            now = time.monotonic()
        outcome = self.classify(matched_blocks, total_blocks)
        if self._closed:
            # Late record after close() (racing shutdown): classified
            # but not folded, so the returned-to-gauge family count
            # stays exact.  Cheap unlocked read; the stripe section
            # re-checks under its lock to close the race with the
            # sweep itself.
            return outcome

        # Aggregate side first: it owns the request sequence number the
        # reuse distance below is measured in.
        with self._agg_lock:
            self._seq += 1
            seq = self._seq
            self._recorded += 1
            if outcome == "hit":
                self._hits += 1
            elif outcome == "partial":
                self._partials += 1
            else:
                self._misses += 1
            self._blocks_matched += matched_blocks
            self._blocks_total += total_blocks
            self._pending_outcomes[outcome] += 1
            if tiers:
                agg = self._tiers
                pending = self._pending_tiers
                for tier, count in tiers.items():
                    agg[tier] = agg.get(tier, 0) + count
                    pending[tier] = pending.get(tier, 0) + count
            elif matched_blocks:
                self._tier_untracked += 1
            acc = self._acc
            slot = int(now)
            if acc.slot != slot:
                self._fold_acc_locked()
                acc = self._acc = Frame(slot)
            acc.record(outcome, matched_blocks, total_blocks, tiers)

        evicted = 0
        reuse_distance = None
        if family is not None:
            stripe_index = family & self._mask
            with self._stripe_locks[stripe_index]:
                stripe = self._stripes[stripe_index]
                # close() sets _closed BEFORE sweeping the stripes, so
                # an insert that would land after the sweep (leaking a
                # gauge increment forever) sees the flag here.
                if self._closed:
                    return outcome
                stats: Optional[FamilyStats] = stripe.get(family)
                if stats is None:
                    if len(stripe) >= self._per_stripe:
                        # Insertion order IS recency order (repeats
                        # re-insert below), so the first key is LRU.
                        del stripe[next(iter(stripe))]
                        evicted = 1
                    stats = FamilyStats(now, seq, model)
                    stripe[family] = stats
                    membership_changed = True
                else:
                    # Move-to-end: keeps insertion order == recency.
                    del stripe[family]
                    stripe[family] = stats
                    membership_changed = False
                    reuse_distance = max(1, seq - stats.last_seq)
                    interarrival = max(0.0, now - stats.last_seen)
                    stats.ewma_interarrival_s = (
                        interarrival
                        if stats.ewma_interarrival_s is None
                        else EWMA_ALPHA * interarrival
                        + (1.0 - EWMA_ALPHA) * stats.ewma_interarrival_s
                    )
                    stats.last_seen = now
                    stats.last_seq = seq
                stats.requests += 1
                if outcome == "hit":
                    stats.hits += 1
                elif outcome == "partial":
                    stats.partials += 1
                else:
                    stats.misses += 1
                stats.blocks_matched += matched_blocks
                stats.blocks_total += total_blocks
                if tiers:
                    mine = stats.tiers
                    for tier, count in tiers.items():
                        mine[tier] = mine.get(tier, 0) + count
        else:
            membership_changed = False

        flush = None
        with self._agg_lock:
            if reuse_distance is not None:
                bucket = self._observe_reuse_locked(reuse_distance)
                self._pending_reuse[bucket] += 1
                self._pending_reuse_sum += reuse_distance
            if evicted:
                self._families_evicted += evicted
            self._since_flush += 1
            if self._since_flush >= METRICS_FLUSH_EVERY:
                flush = self._drain_pending_locked()
        if flush is not None:
            self._apply_flush(flush)
        if membership_changed and not evicted:
            # Delta, not set(): the gauge is process-global and several
            # ledgers may share it (one per Indexer) — deltas aggregate
            # to the true total where absolute writes would clobber
            # last-writer-wins.  Insert-with-evict nets to zero; the
            # ledger's close() gives the families back.
            METRICS.cachestats_families.inc()
        return outcome

    def close(self) -> None:
        """Retire this ledger: flush pending metric deltas and return
        its tracked families to the process-global gauge (deltas would
        otherwise overstate forever after an Indexer teardown).
        Idempotent; called by ``Indexer.shutdown()``."""
        with self._agg_lock:
            if self._closed:
                return
            self._closed = True
            flush = self._drain_pending_locked()
        self._apply_flush(flush)
        tracked = 0
        for stripe_index, stripe in enumerate(self._stripes):
            with self._stripe_locks[stripe_index]:
                tracked += len(stripe)
                stripe.clear()
        if tracked:
            METRICS.cachestats_families.dec(tracked)

    def _observe_reuse_locked(self, distance: int) -> int:
        for i, bound in enumerate(REUSE_BUCKETS):
            if distance <= bound:
                self._reuse_hist[i] += 1
                return i
        self._reuse_hist[-1] += 1
        return len(REUSE_BUCKETS)

    def _fold_acc_locked(self) -> None:
        """Absorb the accumulator into every ring and reset it (same
        slot, so a mid-second read folds what exists and later records
        in that second merge into the same ring frames)."""
        acc = self._acc
        if acc.slot < 0 or not acc.requests:
            return
        at = float(acc.slot)
        for _, ring in self._windows:
            ring.absorb(at, acc)
        self._acc = Frame(acc.slot)

    # -- Prometheus flush ------------------------------------------------

    def _drain_pending_locked(self):
        """Swap out the pending Prometheus deltas (caller applies them
        outside the lock)."""
        self._since_flush = 0
        pending = (
            dict(self._pending_outcomes),
            self._pending_tiers,
            self._pending_reuse,
            self._pending_reuse_sum,
        )
        for outcome in self._pending_outcomes:
            self._pending_outcomes[outcome] = 0
        self._pending_tiers = {}
        self._pending_reuse = [0] * (len(REUSE_BUCKETS) + 1)
        self._pending_reuse_sum = 0
        return pending

    def _apply_flush(self, flush) -> None:
        outcomes, tiers, reuse, reuse_sum = flush
        for outcome, count in outcomes.items():
            if count:
                self._outcome_children[outcome].inc(count)
        for tier, count in tiers.items():
            child = self._tier_children.get(tier)
            if child is None:
                child = METRICS.cachestats_tier_hits.labels(tier=tier)
                # gil-atomic: idempotent memo; racing put re-derives the same value
                self._tier_children[tier] = child
            child.inc(count)
        if any(reuse):
            self._flush_reuse(reuse, reuse_sum)

    def _flush_reuse(self, per_bucket, total) -> None:
        """Batch-apply reuse-distance deltas.

        The public Histogram API only offers per-value ``observe`` —
        at one observe per repeat request that was the single biggest
        analytics cost — so the flush increments the bucket values
        directly (our bucket ladder is the histogram's, asserted at
        construction below).  Exposition parity with observe() is
        pinned by tests/test_cache_analytics.py; if the private layout
        ever changes, the fallback is the plain observe loop.
        """
        hist = METRICS.cachestats_reuse_distance
        buckets = getattr(hist, "_buckets", None)
        hist_sum = getattr(hist, "_sum", None)
        if buckets is None or hist_sum is None or len(buckets) != len(
            per_bucket
        ):
            observe = hist.observe
            for i, count in enumerate(per_bucket[:-1]):
                for _ in range(count):
                    observe(REUSE_BUCKETS[i])
            for _ in range(per_bucket[-1]):
                observe(REUSE_BUCKETS[-1] + 1)
            return
        # prometheus_client stores non-cumulative per-bucket counts and
        # accumulates at collect(); our ladder (+inf tail) aligns 1:1.
        for i, count in enumerate(per_bucket):
            if count:
                buckets[i].inc(count)
        hist_sum.inc(total)

    def flush_metrics(self) -> None:
        """Drain pending Prometheus deltas now (scrape consistency for
        tests and snapshot readers; record() flushes every
        METRICS_FLUSH_EVERY records on its own)."""
        with self._agg_lock:
            flush = self._drain_pending_locked()
        self._apply_flush(flush)

    # -- read surface ----------------------------------------------------

    def families_tracked(self) -> int:
        return sum(len(stripe) for stripe in self._stripes)

    def predicted_interarrival_s(self, family: int) -> Optional[float]:
        """The reuse signal for future eviction/admission policy
        (ROADMAP item 4): this family's EWMA of inter-arrival times, or
        None when it has been seen at most once (or was evicted)."""
        stripe_index = family & self._mask
        with self._stripe_locks[stripe_index]:
            stats = self._stripes[stripe_index].get(family)
            return stats.ewma_interarrival_s if stats is not None else None

    def predicted_matched_blocks(self, family: int) -> Optional[float]:
        """Average matched blocks per request for a tracked family —
        the read path's chain-speculation depth signal: a multi-turn
        family that historically matched deep justifies dispatching
        the next chunk's lookups before the current chunk resolves
        (docs/replication.md "Pipelined read path").  None when the
        family is untracked."""
        stripe_index = family & self._mask
        with self._stripe_locks[stripe_index]:
            stats = self._stripes[stripe_index].get(family)
            if stats is None or not stats.requests:
                return None
            return stats.blocks_matched / stats.requests

    def reuse_predictions(self):
        """Bulk export of the reuse signal: ``(family,
        ewma_interarrival_s, last_seen, requests)`` for every tracked
        family seen at least twice — the PolicyFeed's snapshot input
        (tiering/policy_feed.py).  One stripe lock at a time, never
        nested; O(families tracked), for periodic refreshes rather
        than per-request calls."""
        out = []
        for stripe_index, stripe in enumerate(self._stripes):
            with self._stripe_locks[stripe_index]:
                for family, stats in stripe.items():
                    ewma = stats.ewma_interarrival_s
                    if ewma is not None:
                        out.append(
                            (family, ewma, stats.last_seen, stats.requests)
                        )
        return out

    def tier_detail_due(self) -> bool:
        """Cheap modulo gate for per-tier attribution (every Nth
        sampled request pays the per-block tier walk; see
        ``LedgerConfig.tier_sample``).  Deliberately lock-free: a racy
        tick merely shifts which request carries the detail."""
        sample = self.config.tier_sample
        if sample <= 1:
            return True
        tick = self._tier_tick + 1
        if tick >= sample:
            # gil-atomic: sampling tick; a lost update only shifts the sampled request
            self._tier_tick = 0
            return True
        # gil-atomic: sampling tick; a lost update only shifts the sampled request
        self._tier_tick = tick
        return False

    def stats_summary(self) -> dict:
        """Compact totals for /healthz."""
        self.flush_metrics()
        with self._agg_lock:
            recorded = self._recorded
            hits = self._hits
            summary = {
                "sample_rate": self.config.sample_rate,
                "recorded": recorded,
                "hit_rate": round(hits / recorded, 4) if recorded else None,
                "block_hit_rate": (
                    round(self._blocks_matched / self._blocks_total, 4)
                    if self._blocks_total
                    else None
                ),
            }
        summary["families_tracked"] = self.families_tracked()
        return summary

    def snapshot(self, now: Optional[float] = None, top: int = 20) -> dict:
        """The /debug/cachestats payload: totals, windows, reuse
        distances, and the top families by request count."""
        if now is None:
            now = time.monotonic()
        self.flush_metrics()
        with self._agg_lock:
            self._fold_acc_locked()
            out = {
                "config": {
                    "sample_rate": self.config.sample_rate,
                    "family_blocks": self.config.family_blocks,
                    "max_families": self.config.max_families,
                    "hit_ratio": self.config.hit_ratio,
                    "hit_blocks": self.config.hit_blocks,
                },
                "totals": {
                    "recorded": self._recorded,
                    "hits": self._hits,
                    "partials": self._partials,
                    "misses": self._misses,
                    "hit_rate": (
                        round(self._hits / self._recorded, 4)
                        if self._recorded
                        else None
                    ),
                    "blocks_matched": self._blocks_matched,
                    "blocks_total": self._blocks_total,
                    "block_hit_rate": (
                        round(self._blocks_matched / self._blocks_total, 4)
                        if self._blocks_total
                        else None
                    ),
                    "tiers": dict(self._tiers),
                    "tier_untracked": self._tier_untracked,
                    "families_evicted": self._families_evicted,
                },
                "windows": {
                    name: ring.totals(now) for name, ring in self._windows
                },
                "reuse_distance": self._reuse_view_locked(),
            }
        out["families_tracked"] = self.families_tracked()
        out["top_families"] = self.top_families(now, top)
        return out

    def _reuse_view_locked(self) -> dict:
        view = {}
        for i, bound in enumerate(REUSE_BUCKETS):
            if self._reuse_hist[i]:
                view[f"le_{bound}"] = self._reuse_hist[i]
        if self._reuse_hist[-1]:
            view["inf"] = self._reuse_hist[-1]
        return view

    def top_families(self, now: Optional[float] = None, top: int = 20) -> list:
        """Most-requested families, for the drill-down listing."""
        if now is None:
            now = time.monotonic()
        entries = []
        for stripe_index, stripe in enumerate(self._stripes):
            with self._stripe_locks[stripe_index]:
                for family, stats in stripe.items():
                    entries.append((stats.requests, family, stats.to_dict(now)))
        entries.sort(key=lambda item: (-item[0], item[1]))
        return [
            dict(detail, family=f"{family:016x}")
            for _, family, detail in entries[: max(0, top)]
        ]

    def family_detail(self, family: int, now: Optional[float] = None) -> Optional[dict]:
        """One family's stats (the ?family=<hex> drill-down), or None."""
        if now is None:
            now = time.monotonic()
        stripe_index = family & self._mask
        with self._stripe_locks[stripe_index]:
            stats = self._stripes[stripe_index].get(family)
            if stats is None:
                return None
            detail = stats.to_dict(now)
        detail["family"] = f"{family:016x}"
        return detail

    def window_frames_cbor(self, now: Optional[float] = None) -> Dict[str, bytes]:
        """Canonical-CBOR frame snapshots per window (the snapshottable
        artifact future eviction policy consumes)."""
        if now is None:
            now = time.monotonic()
        with self._agg_lock:
            self._fold_acc_locked()
            return {name: ring.to_cbor(now) for name, ring in self._windows}

    def window_totals(self, name: str, now: Optional[float] = None) -> Optional[dict]:
        if now is None:
            now = time.monotonic()
        with self._agg_lock:
            self._fold_acc_locked()
            for window_name, ring in self._windows:
                if window_name == name:
                    return ring.totals(now)
        return None
