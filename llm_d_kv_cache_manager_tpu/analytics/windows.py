"""Windowed aggregation frames for the cache-efficiency ledger.

A :class:`WindowRing` is a bounded ring of fixed-span frames: recording
lands in the frame covering "now", and reading aggregates only the
frames still inside the window.  Three standard rings (1m / 10m / 1h)
give the ledger a scrapeable short view and a snapshottable long view
without unbounded memory — the ring holds ``frames`` frames, ever.

Frames are CBOR-serializable through the project's canonical encoder
(``kvcache/kvblock/cbor_canonical.py``) so a snapshot is deterministic
bytes: the same counts always encode identically (the persistence
subsystem's rule, applied here so future eviction-policy training can
diff snapshots byte-wise).  The canonical encoder supports no maps, so
a frame encodes as a fixed-shape list (see :meth:`Frame.to_wire`).

Time is injected (``now`` parameters) rather than read, so tests drive
rotation deterministically; callers pass ``time.monotonic()``.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cbor_canonical import (
    encode_canonical,
)

# Wire-format version of the frame list shape below.
FRAME_WIRE_VERSION = 1

OUTCOMES = ("hit", "partial", "miss")


class Frame:
    """Counts for one fixed time slot."""

    __slots__ = (
        "slot",
        "requests",
        "hits",
        "partials",
        "misses",
        "blocks_matched",
        "blocks_total",
        "tiers",
    )

    def __init__(self, slot: int) -> None:
        self.slot = slot
        self.requests = 0
        self.hits = 0
        self.partials = 0
        self.misses = 0
        self.blocks_matched = 0
        self.blocks_total = 0
        self.tiers: Dict[str, int] = {}

    def record(
        self,
        outcome: str,
        matched_blocks: int,
        total_blocks: int,
        tiers: Optional[Dict[str, int]],
    ) -> None:
        self.requests += 1
        if outcome == "hit":
            self.hits += 1
        elif outcome == "partial":
            self.partials += 1
        else:
            self.misses += 1
        self.blocks_matched += matched_blocks
        self.blocks_total += total_blocks
        if tiers:
            mine = self.tiers
            for tier, count in tiers.items():
                mine[tier] = mine.get(tier, 0) + count

    def merge(self, other: "Frame") -> None:
        """Fold another frame's counts into this one (the ledger's
        1-second accumulator absorbs into each ring once per slot roll
        instead of updating three rings per record)."""
        self.requests += other.requests
        self.hits += other.hits
        self.partials += other.partials
        self.misses += other.misses
        self.blocks_matched += other.blocks_matched
        self.blocks_total += other.blocks_total
        if other.tiers:
            mine = self.tiers
            for tier, count in other.tiers.items():
                mine[tier] = mine.get(tier, 0) + count

    def to_wire(self) -> list:
        """Fixed-shape list for canonical CBOR (no maps there): tiers
        become a name-sorted ``[name, count]`` list so equal counts
        always encode to equal bytes."""
        return [
            self.slot,
            self.requests,
            self.hits,
            self.partials,
            self.misses,
            self.blocks_matched,
            self.blocks_total,
            [[name, self.tiers[name]] for name in sorted(self.tiers)],
        ]

    def to_dict(self) -> dict:
        return {
            "slot": self.slot,
            "requests": self.requests,
            "hits": self.hits,
            "partials": self.partials,
            "misses": self.misses,
            "blocks_matched": self.blocks_matched,
            "blocks_total": self.blocks_total,
            "tiers": dict(self.tiers),
        }


class WindowRing:
    """Ring of ``frames`` frames, each spanning ``span_s`` seconds.

    Unlocked by design: the owning ledger serializes access (its
    aggregate lock), keeping this class a plain data structure.
    """

    def __init__(self, span_s: float, frames: int) -> None:
        if span_s <= 0 or frames <= 0:
            raise ValueError("span_s and frames must be positive")
        self.span_s = float(span_s)
        self.frames = frames
        self._ring: Deque[Frame] = deque()

    @property
    def window_s(self) -> float:
        return self.span_s * self.frames

    def _slot(self, now: float) -> int:
        return int(now // self.span_s)

    def _advance(self, now: float) -> None:
        """Drop frames that rotated out of the window."""
        floor = self._slot(now) - self.frames + 1
        ring = self._ring
        while ring and ring[0].slot < floor:
            ring.popleft()

    def record(
        self,
        now: float,
        outcome: str,
        matched_blocks: int,
        total_blocks: int,
        tiers: Optional[Dict[str, int]] = None,
    ) -> None:
        self._advance(now)
        slot = self._slot(now)
        ring = self._ring
        if not ring or ring[-1].slot != slot:
            # Slots between the last frame and now simply never existed
            # (no traffic there); the ring stores only non-empty frames.
            ring.append(Frame(slot))
        ring[-1].record(outcome, matched_blocks, total_blocks, tiers)

    def absorb(self, at: float, frame: Frame) -> None:
        """Fold pre-aggregated counts (a completed accumulator frame)
        into the ring frame covering time ``at``."""
        self._advance(at)
        slot = self._slot(at)
        ring = self._ring
        if not ring or ring[-1].slot != slot:
            ring.append(Frame(slot))
        ring[-1].merge(frame)

    def live_frames(self, now: float) -> List[Frame]:
        self._advance(now)
        return list(self._ring)

    def totals(self, now: float) -> dict:
        """Aggregate over the live frames, plus derived hit rate."""
        frames = self.live_frames(now)
        out = {
            "window_s": self.window_s,
            "frames": len(frames),
            "requests": 0,
            "hits": 0,
            "partials": 0,
            "misses": 0,
            "blocks_matched": 0,
            "blocks_total": 0,
            "tiers": {},
        }
        tiers: Dict[str, int] = out["tiers"]
        for frame in frames:
            out["requests"] += frame.requests
            out["hits"] += frame.hits
            out["partials"] += frame.partials
            out["misses"] += frame.misses
            out["blocks_matched"] += frame.blocks_matched
            out["blocks_total"] += frame.blocks_total
            for tier, count in frame.tiers.items():
                tiers[tier] = tiers.get(tier, 0) + count
        requests = out["requests"]
        out["hit_rate"] = (
            round(out["hits"] / requests, 4) if requests else None
        )
        out["block_hit_rate"] = (
            round(out["blocks_matched"] / out["blocks_total"], 4)
            if out["blocks_total"]
            else None
        )
        return out

    def to_cbor(self, now: float) -> bytes:
        """Canonical CBOR snapshot of the live frames."""
        frames = self.live_frames(now)
        payload = [
            FRAME_WIRE_VERSION,
            # span in milliseconds: the canonical encoder is int-only.
            int(self.span_s * 1000),
            self.frames,
            [frame.to_wire() for frame in frames],
        ]
        return encode_canonical(payload)


def standard_windows() -> List[Tuple[str, WindowRing]]:
    """The ledger's three standard windows: scrape-friendly 1m, trend
    10m, snapshot 1h."""
    return [
        ("1m", WindowRing(span_s=5.0, frames=12)),
        ("10m", WindowRing(span_s=30.0, frames=20)),
        ("1h", WindowRing(span_s=300.0, frames=12)),
    ]
