"""gRPC API layer (reference: api/indexerpb, api/tokenizerpb).

Message classes are protoc-generated (``*_pb2.py``, checked in — the
image ships ``protoc`` but not ``grpc_tools``); the service stubs and
servicer registration in ``grpc_services.py`` are hand-written over
grpcio's generic-handler API, which produces the same wire behavior as
plugin-generated code.
"""

from llm_d_kv_cache_manager_tpu.api import indexer_pb2, tokenizer_pb2

__all__ = ["indexer_pb2", "tokenizer_pb2"]
