"""gRPC stubs + servicer registration for the two framework services.

Equivalent to the plugin-generated ``*_pb2_grpc.py`` modules of the
reference (api/indexerpb, api/tokenizerpb): same fully-qualified method
paths, so clients/servers interoperate with the reference's generated Go
and Python code.
"""

from __future__ import annotations

import os
from typing import Optional

import grpc

from llm_d_kv_cache_manager_tpu.api import indexer_pb2, tokenizer_pb2

INDEXER_SERVICE = "indexer.v1.IndexerService"
TOKENIZATION_SERVICE = "tokenization.TokenizationService"


class IndexerServiceStub:
    """Client stub for IndexerService (reference: indexer.proto:24-27)."""

    def __init__(self, channel: grpc.Channel) -> None:
        self.channel = channel  # retained so owners can close() it
        self.GetPodScores = channel.unary_unary(
            f"/{INDEXER_SERVICE}/GetPodScores",
            request_serializer=(
                indexer_pb2.GetPodScoresRequest.SerializeToString
            ),
            response_deserializer=(
                indexer_pb2.GetPodScoresResponse.FromString
            ),
        )


class IndexerServiceServicer:
    def GetPodScores(self, request, context):  # pragma: no cover - abstract
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError()


def add_indexer_servicer(servicer: IndexerServiceServicer, server) -> None:
    handlers = {
        "GetPodScores": grpc.unary_unary_rpc_method_handler(
            servicer.GetPodScores,
            request_deserializer=indexer_pb2.GetPodScoresRequest.FromString,
            response_serializer=(
                indexer_pb2.GetPodScoresResponse.SerializeToString
            ),
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(INDEXER_SERVICE, handlers),)
    )


class TokenizationServiceStub:
    """Client stub for TokenizationService (tokenizer.proto:113-123)."""

    def __init__(self, channel: grpc.Channel) -> None:
        self.channel = channel  # retained so owners can close() it
        self.Tokenize = channel.unary_unary(
            f"/{TOKENIZATION_SERVICE}/Tokenize",
            request_serializer=tokenizer_pb2.TokenizeRequest.SerializeToString,
            response_deserializer=tokenizer_pb2.TokenizeResponse.FromString,
        )
        self.RenderChatTemplate = channel.unary_unary(
            f"/{TOKENIZATION_SERVICE}/RenderChatTemplate",
            request_serializer=(
                tokenizer_pb2.ChatTemplateRequest.SerializeToString
            ),
            response_deserializer=(
                tokenizer_pb2.ChatTemplateResponse.FromString
            ),
        )
        self.InitializeTokenizer = channel.unary_unary(
            f"/{TOKENIZATION_SERVICE}/InitializeTokenizer",
            request_serializer=(
                tokenizer_pb2.InitializeTokenizerRequest.SerializeToString
            ),
            response_deserializer=(
                tokenizer_pb2.InitializeTokenizerResponse.FromString
            ),
        )


class TokenizationServiceServicer:
    def Tokenize(self, request, context):  # pragma: no cover - abstract
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError()

    def RenderChatTemplate(self, request, context):  # pragma: no cover
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError()

    def InitializeTokenizer(self, request, context):  # pragma: no cover
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError()


def add_tokenization_servicer(
    servicer: TokenizationServiceServicer, server
) -> None:
    handlers = {
        "Tokenize": grpc.unary_unary_rpc_method_handler(
            servicer.Tokenize,
            request_deserializer=tokenizer_pb2.TokenizeRequest.FromString,
            response_serializer=tokenizer_pb2.TokenizeResponse.SerializeToString,
        ),
        "RenderChatTemplate": grpc.unary_unary_rpc_method_handler(
            servicer.RenderChatTemplate,
            request_deserializer=tokenizer_pb2.ChatTemplateRequest.FromString,
            response_serializer=(
                tokenizer_pb2.ChatTemplateResponse.SerializeToString
            ),
        ),
        "InitializeTokenizer": grpc.unary_unary_rpc_method_handler(
            servicer.InitializeTokenizer,
            request_deserializer=(
                tokenizer_pb2.InitializeTokenizerRequest.FromString
            ),
            response_serializer=(
                tokenizer_pb2.InitializeTokenizerResponse.SerializeToString
            ),
        ),
    }
    server.add_generic_rpc_handlers(
        (
            grpc.method_handlers_generic_handler(
                TOKENIZATION_SERVICE, handlers
            ),
        )
    )


# --- Value <-> python conversion (tokenizer.proto:72-91 kwargs encoding) ---


def value_to_python(value: tokenizer_pb2.Value):
    kind = value.WhichOneof("value")
    if kind == "string_value":
        return value.string_value
    if kind == "number_value":
        # Always a float: ints travel as int_value, so 2.0 stays 2.0 and
        # sidecar rendering agrees with the in-process path.  Version
        # skew note: upgrade decode sides (servers) before encode sides —
        # an old server's pb2 lacks int_value and would null-out integer
        # kwargs sent by a new client.
        return value.number_value
    if kind == "int_value":
        return value.int_value
    if kind == "bool_value":
        return value.bool_value
    if kind == "list_value":
        return [value_to_python(item) for item in value.list_value.values]
    if kind == "struct_value":
        return {
            key: value_to_python(item)
            for key, item in value.struct_value.fields.items()
        }
    return None


# int_value (field 6) is an extension over the reference proto, whose
# Value oneof stops at number_value (api/tokenizerpb/tokenizer.proto).
# A reference Go sidecar receiving int_value leaves the oneof unset and
# the kwarg silently becomes null — so when talking to a peer that may
# run the reference implementation, disable the extension and fall back
# to the reference's lossy-float encoding.  Env toggle for deployments;
# per-call override for tests.
USE_INT_VALUE = os.environ.get("KVTPU_PROTO_INT_VALUE", "1") != "0"


def python_to_value(
    obj, use_int_value: Optional[bool] = None
) -> tokenizer_pb2.Value:
    if use_int_value is None:
        use_int_value = USE_INT_VALUE
    value = tokenizer_pb2.Value()
    if isinstance(obj, bool):
        value.bool_value = obj
    elif isinstance(obj, str):
        value.string_value = obj
    elif isinstance(obj, int):
        if use_int_value and -(2**63) <= obj < 2**63:
            value.int_value = obj
        else:  # beyond sint64 (or reference-compat mode): lossy float
            value.number_value = float(obj)
    elif isinstance(obj, float):
        value.number_value = obj
    elif isinstance(obj, (list, tuple)):
        value.list_value.values.extend(
            python_to_value(item, use_int_value) for item in obj
        )
    elif isinstance(obj, dict):
        value.struct_value.SetInParent()
        for key, item in obj.items():
            value.struct_value.fields[str(key)].CopyFrom(
                python_to_value(item, use_int_value)
            )
    elif obj is None:
        pass  # unset oneof round-trips as None in value_to_python
    else:
        raise TypeError(f"cannot encode {type(obj).__name__} as Value")
    return value


def struct_map_to_dict(fields) -> dict:
    return {key: value_to_python(item) for key, item in fields.items()}


def dict_to_struct_map(obj: dict, fields) -> None:
    for key, item in obj.items():
        fields[str(key)].CopyFrom(python_to_value(item))
