"""HTTP scoring service: the online-demo surface as a real module.

Counterpart of the reference's online example service
(examples/kv_events/online/main.go:273-385): ``POST /score_completions``
and ``POST /score_chat_completions`` against the live indexer,
``GET /metrics`` (Prometheus exposition), ``GET /healthz``.  Stdlib
``http.server`` — threaded, no framework dependency.

Observability surface (docs/observability.md): the scoring endpoints
ingest and echo W3C ``traceparent`` (a sampled flag forces tracing),
accept ``?explain=1`` for a per-stage latency breakdown plus per-pod
score provenance, and the read-only flight-recorder endpoints
``GET /debug/traces`` (``?kind=recent|slow|errored``) and
``GET /debug/traces/<id>`` expose recent sampled traces.

Run standalone (env-configured like the reference's example):

    PYTHONHASHSEED=42 BLOCK_SIZE=16 ZMQ_ENDPOINT=tcp://*:5557 \
    MODEL_NAME=meta-llama/Llama-3.1-8B-Instruct \
    python -m llm_d_kv_cache_manager_tpu.api.http_service
"""

from __future__ import annotations

import http.server
import json
import os
import socket
import threading
import time
import urllib.parse
from typing import Dict, Optional

from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer
from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.obs.trace import TRACER, use_trace
from llm_d_kv_cache_manager_tpu.preprocessing.chat_templating import (
    ApplyChatTemplateRequest,
)
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("api.http_service")

# Generous for scoring payloads (a 100k-token chat conversation is well
# under 2 MiB of JSON) while bounding per-request buffering.
MAX_BODY_BYTES = 16 * 1024 * 1024


def _make_handler(
    indexer: Indexer,
    admin_token: Optional[str] = None,
    persistence=None,
    recovery_report=None,
    event_plane_status=None,
    auditor=None,
    tiering=None,
    transfer=None,
    replica=None,
    cluster_status=None,
    slo=None,
    profiler=None,
    timeline=None,
    capture=None,
    incidents=None,
):
    class Handler(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # Socket timeout (StreamRequestHandler applies it in setup()):
        # without one, a client that declares a Content-Length and goes
        # silent wedges a handler thread forever in rfile.read — a few
        # dozen such connections exhaust the ThreadingHTTPServer.
        timeout = 60

        def log_message(self, *args):  # route through our logger
            logger.debug("http: " + args[0], *args[1:])

        def _reply(
            self,
            status: int,
            body: bytes,
            content_type: str,
            extra_headers: Optional[Dict[str, str]] = None,
        ):
            # Centralized desync guard: replying while a declared
            # request body sits unconsumed (404 route, 403 admin gate,
            # any future early-reply path) leaves those bytes to be
            # parsed as the next request line on keep-alive.  Close —
            # and TELL the client (without the Connection: close
            # header a keep-alive pool marks the connection reusable
            # and its next non-idempotent POST dies with ECONNRESET).
            if not getattr(
                self, "_body_consumed", True
            ) and self._declares_body():
                self.close_connection = True
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for name, value in (extra_headers or {}).items():
                self.send_header(name, value)
            if self.close_connection:
                self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(body)

        def _reply_json(
            self,
            status: int,
            obj,
            extra_headers: Optional[Dict[str, str]] = None,
        ) -> None:
            self._reply(
                status,
                json.dumps(obj).encode(),
                "application/json",
                extra_headers,
            )

        def _error(self, status: int, message: str) -> None:
            self._reply(status, message.encode() + b"\n", "text/plain")

        def _read_body(self) -> Optional[bytes]:
            # A chunked body is never decoded here, so its framing bytes
            # would sit in the buffer and be parsed as the next request
            # line — the keep-alive desync the paths below guard
            # against.  Reject the encoding outright.
            if self.headers.get("Transfer-Encoding"):
                self.close_connection = True
                self._error(501, "Transfer-Encoding not supported")
                return None
            # Duplicate Content-Length headers are a request-smuggling
            # primitive: .get() would silently honor the first value and
            # leave the rest of the body buffered for the next request
            # line.  Reject conflicting duplicates outright.
            all_lengths = self.headers.get_all("Content-Length") or ["0"]
            if len(set(all_lengths)) > 1:
                self.close_connection = True
                self._error(400, "conflicting Content-Length headers")
                return None
            # Strict digit grammar, same policy as RespClient._parse_int:
            # Python's int() accepts ' 10 ', '+10' and '1_0', which are
            # corrupted headers, not lengths.  ASCII digits only also
            # rules out negatives (read-to-EOF wedge) by construction.
            raw_length = str(all_lengths[0])
            # The digit-count bound precedes int(): CPython (>=3.11)
            # raises ValueError past ~4300 digits of str->int, which
            # would escape the handler; anything longer than
            # len(str(MAX_BODY_BYTES)) digits is oversized regardless.
            if (
                not raw_length.isascii()
                or not raw_length.isdigit()
                or len(raw_length) > len(str(MAX_BODY_BYTES))
            ):
                # Rejecting without consuming the body desyncs HTTP/1.1
                # keep-alive (leftover bytes parse as the next request
                # line); drop the connection instead.
                self.close_connection = True
                self._error(400, "invalid Content-Length")
                return None
            length = int(raw_length)
            if length > MAX_BODY_BYTES:
                self.close_connection = True
                self._error(413, "request body too large")
                return None
            try:
                body = self.rfile.read(length)
            except (TimeoutError, socket.timeout, OSError):
                # Stalled client (declared length, stopped sending):
                # the socket timeout fired mid-read.  The connection is
                # in an unknown framing state — drop it.
                self.close_connection = True
                return None
            self._body_consumed = True
            return body

        def _read_json(self) -> Optional[dict]:
            body = self._read_body()
            if body is None:
                return None
            try:
                obj = json.loads(body)
            except (ValueError, json.JSONDecodeError):
                self._error(400, "invalid JSON body")
                return None
            if not isinstance(obj, dict):
                # `null`/arrays/scalars are valid JSON: without this an
                # object-assuming handler would send NO response (client
                # hang) or crash the connection mid-request.
                self._error(400, "JSON object body required")
                return None
            return obj

        @staticmethod
        def _split_path(raw_path: str):
            """(path, {query}) with single-valued query params."""
            parsed = urllib.parse.urlsplit(raw_path)
            query = {
                key: values[-1]
                for key, values in urllib.parse.parse_qs(
                    parsed.query
                ).items()
            }
            return parsed.path, query

        def _do_get(self):
            path, query = self._split_path(self.path)
            if path == "/metrics":
                self._reply(
                    200,
                    METRICS.exposition(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            elif path == "/healthz":
                health = {"status": "ok"}
                # Sampling health without a scrape: ring occupancy and
                # sampled/unsampled counters tell an operator whether
                # the flight recorder is alive.
                health["observability"] = TRACER.stats()
                if recovery_report is not None:
                    health["recovery"] = recovery_report.to_dict()
                if persistence is not None:
                    health["persistence"] = persistence.status()
                if event_plane_status is not None:
                    # Live view: poller fan-in, suspect pods, resync
                    # outcomes (docs/event-plane.md).
                    try:
                        health["event_plane"] = event_plane_status()
                    except Exception:  # noqa: BLE001 — health must answer
                        logger.exception("event-plane status failed")
                        health["event_plane"] = {"error": "unavailable"}
                analytics = {}
                try:
                    if indexer.cache_stats is not None:
                        analytics["cachestats"] = (
                            indexer.cache_stats.stats_summary()
                        )
                    if auditor is not None:
                        analytics["audit"] = auditor.status()
                except Exception:  # noqa: BLE001 — health must answer
                    logger.exception("analytics status failed")
                    analytics = {"error": "unavailable"}
                if analytics:
                    health["analytics"] = analytics
                if tiering is not None:
                    # Compact: full engine status lives at
                    # /debug/tiering; health carries the liveness bits.
                    try:
                        status = tiering.status()
                        health["tiering"] = {
                            "feed": status["feed"]["snapshot"],
                            "advice_counts": status["advisor"][
                                "advice_counts"
                            ],
                            "demotion_workers": len(status["demotion"]),
                        }
                    except Exception:  # noqa: BLE001 — health must answer
                        logger.exception("tiering status failed")
                        health["tiering"] = {"error": "unavailable"}
                if transfer is not None:
                    # Compact: full engine status lives at
                    # /debug/transfer; health carries the liveness bits.
                    try:
                        status = transfer.status()
                        health["transfer"] = {
                            "plans": status["planner"]["plans"],
                            "outcomes": status["planner"]["outcomes"],
                            "cold_pods": (
                                len(status["warmup"]["cold_pods"])
                                if status["warmup"]
                                else 0
                            ),
                        }
                    except Exception:  # noqa: BLE001 — health must answer
                        logger.exception("transfer status failed")
                        health["transfer"] = {"error": "unavailable"}
                if slo is not None:
                    # Compact degradation envelope; the full per-SLI
                    # payload lives at /debug/slo.
                    try:
                        health["slo"] = slo.healthz_block()
                    except Exception:  # noqa: BLE001 — health must answer
                        logger.exception("slo status failed")
                        health["slo"] = {"error": "unavailable"}
                # Config fingerprint + capture/incident liveness: the
                # same fingerprint stamped into capture headers and
                # incident bundles, so "would a replay of that bundle
                # match THIS process" is one healthz read away
                # (docs/observability.md "Incident capture & replay").
                try:
                    from llm_d_kv_cache_manager_tpu.obs.capture import (
                        fingerprint_status,
                    )

                    health["fingerprint"] = fingerprint_status()
                    if capture is not None:
                        health["capture"] = capture.status()
                    if incidents is not None:
                        health["incidents"] = incidents.status()
                except Exception:  # noqa: BLE001 — health must answer
                    logger.exception("capture status failed")
                    health["capture"] = {"error": "unavailable"}
                self._reply_json(200, health)
            elif path in ("/debug", "/debug/"):
                self._debug_index()
            elif path == "/debug/traces":
                self._debug_traces(query)
            elif path.startswith("/debug/traces/"):
                self._debug_trace_by_id(path[len("/debug/traces/"):])
            elif path == "/debug/cachestats":
                self._debug_cachestats(query)
            elif path == "/debug/tiering":
                self._debug_tiering()
            elif path == "/debug/transfer":
                self._debug_transfer()
            elif path == "/debug/cluster":
                self._debug_cluster()
            elif path == "/debug/slo":
                self._debug_slo()
            elif path == "/debug/profile":
                self._debug_profile(query)
            elif path == "/debug/timeline":
                self._debug_timeline(query)
            elif path == "/debug/incidents":
                self._debug_incidents()
            elif path.startswith("/debug/incidents/"):
                self._debug_incident_detail(
                    path[len("/debug/incidents/"):]
                )
            elif path == "/debug/whatif":
                self._debug_whatif(query)
            else:
                self._error(404, "not found")

        def _debug_index(self):
            """The debug-surface directory: every registered surface,
            one line each, with its enabled state — replaces
            guess-the-path (docs/observability.md)."""
            surfaces = [
                {
                    "path": "/debug/traces",
                    "enabled": True,
                    "description": (
                        "flight recorder: recent/slow/errored sampled "
                        "traces (?kind=, ?limit=; /debug/traces/<id> "
                        "for full spans)"
                    ),
                },
                {
                    "path": "/debug/cachestats",
                    "enabled": indexer.cache_stats is not None,
                    "description": (
                        "hit-attribution ledger + index-truth audit "
                        "plane (?top=, ?family=<hex>)"
                    ),
                },
                {
                    "path": "/debug/tiering",
                    "enabled": tiering is not None,
                    "description": (
                        "predictive tiering: policy feed, advisor, "
                        "eviction and demotion state"
                    ),
                },
                {
                    "path": "/debug/transfer",
                    "enabled": transfer is not None,
                    "description": (
                        "KV-transfer planning plane: planner "
                        "outcomes, hot-family catalog, warm-up "
                        "queue, executor counters"
                    ),
                },
                {
                    "path": "/debug/cluster",
                    "enabled": cluster_status is not None,
                    "description": (
                        "replicated index: membership, ring, "
                        "per-replica rpc fan-out attribution"
                    ),
                },
                {
                    "path": "/debug/slo",
                    "enabled": slo is not None,
                    "description": (
                        "SLO engine: per-SLI burn rates and the "
                        "degradation envelope"
                    ),
                },
                {
                    # Enabled means the SAMPLER is live-able (wired
                    # AND PROFILE_HZ > 0) — a wired-but-off profiler
                    # must read disabled or the index lies exactly
                    # when the plane is off.  ?kind=locks stays
                    # served regardless (contention timing is armed
                    # by LOCK_CONTENTION_SAMPLE, not the sampler).
                    "path": "/debug/profile",
                    "enabled": (
                        profiler is not None and profiler.config.hz > 0
                    ),
                    "description": (
                        "continuous sampling profiler: top self-time "
                        "table (?kind=top), collapsed flamegraph "
                        "stacks (?kind=stacks), lock contention "
                        "(?kind=locks — served even with the sampler "
                        "off)"
                    ),
                },
                {
                    "path": "/debug/timeline",
                    "enabled": (
                        timeline is not None and timeline.window_s > 0
                    ),
                    "description": (
                        "1s-resolution gauge history rings "
                        "(?last=<seconds>, ?series=<name>)"
                    ),
                },
                {
                    "path": "/debug/incidents",
                    "enabled": incidents is not None,
                    "description": (
                        "incident capture plane: input flight-recorder "
                        "occupancy + SLO-triggered replayable bundles "
                        "(POST /admin/incident forces one)"
                    ),
                    "status": (
                        {
                            "capture": (
                                capture.status()["sources"]
                                if capture is not None
                                else None
                            ),
                            "last_incident": (
                                incidents.status()["last_incident"]
                                if incidents is not None
                                else None
                            ),
                        }
                        if capture is not None or incidents is not None
                        else None
                    ),
                },
                {
                    "path": "/debug/whatif",
                    # The engine is in-process library code with a
                    # module-level results ring — always answerable.
                    "enabled": True,
                    "description": (
                        "what-if engine verdicts: time-compressed "
                        "replays and A/B config canaries, newest "
                        "first (?full=1; POST /admin/whatif runs one "
                        "against a capture or incident bundle)"
                    ),
                },
            ]
            self._reply_json(
                200,
                {
                    "surfaces": surfaces,
                    "also": ["/metrics", "/healthz"],
                },
            )

        def _debug_profile(self, query):
            """Read-only continuous-profiling plane: the sampling
            profiler's top/collapsed views and the lock-contention
            table (docs/observability.md "Continuous profiling")."""
            kind = query.get("kind", "top")
            if kind == "locks":
                # The contention table is module-global lockorder
                # state, armed by LOCK_CONTENTION_SAMPLE — it answers
                # regardless of the sampler (or a profiler being
                # wired at all).
                from llm_d_kv_cache_manager_tpu.utils import lockorder

                self._reply_json(
                    200,
                    {
                        "sample": lockorder.contention_sample(),
                        "locks": lockorder.contention_stats(),
                    },
                )
                return
            if profiler is None or profiler.config.hz <= 0:
                self._error(
                    404, "profiler disabled (set PROFILE_HZ > 0)"
                )
                return
            if kind == "stacks":
                # The standard collapsed/folded format — pipe into
                # flamegraph.pl or paste into speedscope.
                self._reply(
                    200,
                    profiler.collapsed().encode(),
                    "text/plain; charset=utf-8",
                )
                return
            if kind != "top":
                self._error(400, "kind must be one of top|stacks|locks")
                return
            try:
                top = max(1, min(int(query.get("top", "30")), 500))
            except ValueError:
                self._error(400, "invalid 'top'")
                return
            self._reply_json(200, profiler.status(top=top))

        def _debug_timeline(self, query):
            """Read-only gauge timelines: the 1s ring history that
            walks a burn-rate alert back to the minutes before it
            fired (docs/observability.md "Gauge timelines")."""
            if timeline is None or timeline.window_s <= 0:
                self._error(
                    404, "timeline disabled (set TIMELINE_WINDOW_S > 0)"
                )
                return
            last_s = None
            raw_last = query.get("last")
            if raw_last is not None:
                try:
                    last_s = max(0.0, float(raw_last))
                except ValueError:
                    self._error(400, "invalid 'last'")
                    return
            self._reply_json(
                200,
                timeline.snapshot(
                    last_s=last_s, series=query.get("series")
                ),
            )

        def _debug_incidents(self):
            """Read-only incident capture plane: flight-recorder ring
            occupancy (bytes, records, truncation) and every retained
            incident bundle's manifest, newest first
            (docs/observability.md "Incident response runbook")."""
            if capture is None and incidents is None:
                self._error(404, "capture disabled (CAPTURE=0)")
                return
            try:
                payload = {
                    "capture": (
                        capture.status() if capture is not None else None
                    ),
                }
                if incidents is not None:
                    payload.update(incidents.status())
                    payload["incidents"] = incidents.list()
                self._reply_json(200, payload)
            except Exception as exc:  # noqa: BLE001 — debug must answer
                logger.exception("incident status failed")
                self._error(500, f"error: {exc}")

        def _debug_incident_detail(self, incident_id):
            """One retained incident bundle's manifest + source-file
            inventory (byte sizes) — what an operator pulls before
            running the bundle through replay or the what-if engine
            (docs/observability.md "Incident response runbook")."""
            if incidents is None:
                self._error(404, "capture disabled (CAPTURE=0)")
                return
            try:
                detail = incidents.detail(incident_id)
            except Exception as exc:  # noqa: BLE001 — debug must answer
                logger.exception("incident detail failed")
                self._error(500, f"error: {exc}")
                return
            if detail is None:
                self._error(404, f"no such incident: {incident_id}")
                return
            self._reply_json(200, detail)

        def _debug_whatif(self, query):
            """Read-only what-if engine results ring: recent replay /
            A/B verdicts, newest first (?full=1 for complete results;
            POST /admin/whatif runs one; docs/observability.md
            "What-if engine")."""
            from llm_d_kv_cache_manager_tpu.obs import whatif as whatif_mod

            try:
                full = query.get("full", "").lower() in (
                    "1",
                    "true",
                    "yes",
                )
                payload = whatif_mod.REGISTRY.status()
                payload["results_list"] = whatif_mod.REGISTRY.list(
                    full=full
                )
                self._reply_json(200, payload)
            except Exception as exc:  # noqa: BLE001 — debug must answer
                logger.exception("whatif status failed")
                self._error(500, f"error: {exc}")

        def _debug_slo(self):
            """Read-only degradation envelopes: per-SLI state, burn
            rates over both evaluation windows, and the declared
            bounds chaos cells assert against
            (docs/observability.md)."""
            if slo is None:
                self._error(404, "slo engine disabled (SLO_ENABLE=0)")
                return
            try:
                payload = slo.status()
            except Exception as exc:  # noqa: BLE001 — debug must answer
                logger.exception("slo status failed")
                self._error(500, f"error: {exc}")
                return
            self._reply_json(200, payload)

        def _debug_cluster(self):
            """Read-only cluster plane: membership + ring version +
            failovers on a router, replica identity + replication
            follower positions on a replica (docs/replication.md)."""
            if cluster_status is None:
                self._error(
                    404,
                    "cluster disabled (set CLUSTER_REPLICAS or "
                    "CLUSTER_SELF)",
                )
                return
            try:
                payload = cluster_status()
            except Exception as exc:  # noqa: BLE001 — debug must answer
                logger.exception("cluster status failed")
                self._error(500, f"error: {exc}")
                return
            self._reply_json(200, payload)

        def _debug_tiering(self):
            """Read-only tiering policy plane: feed/snapshot stats,
            compute-or-load advisor state, predictive-eviction
            counters, demotion worker status + recent transitions
            (docs/tiering.md)."""
            if tiering is None:
                self._error(404, "tiering disabled (set TIERING=1)")
                return
            try:
                payload = tiering.status()
            except Exception as exc:  # noqa: BLE001 — debug must answer
                logger.exception("tiering status failed")
                self._error(500, f"error: {exc}")
                return
            self._reply_json(200, payload)

        def _debug_transfer(self):
            """Read-only transfer planning plane: planner outcome
            counters + recent plans, the hot-family catalog, warm-up
            queue/cold-pod state, and executor counters
            (docs/transfer.md)."""
            if transfer is None:
                self._error(404, "transfer disabled (set TRANSFER=1)")
                return
            try:
                payload = transfer.status()
            except Exception as exc:  # noqa: BLE001 — debug must answer
                logger.exception("transfer status failed")
                self._error(500, f"error: {exc}")
                return
            self._reply_json(200, payload)

        def _debug_cachestats(self, query):
            """Read-only cache-efficiency analytics: ledger totals,
            windows, reuse distances, top families (?top=N), one
            family's drill-down (?family=<16-hex id from a listing>),
            and the index-truth audit plane (docs/observability.md)."""
            ledger = indexer.cache_stats
            if ledger is None:
                self._error(404, "cache analytics disabled (CACHESTATS=0)")
                return
            family_raw = query.get("family")
            if family_raw:
                try:
                    family = int(family_raw, 16)
                except ValueError:
                    self._error(400, "invalid 'family' (expect hex id)")
                    return
                detail = ledger.family_detail(family)
                if detail is None:
                    self._error(
                        404, "family not tracked (evicted or never seen)"
                    )
                    return
                self._reply_json(200, detail)
                return
            try:
                top = max(1, min(int(query.get("top", "20")), 500))
            except ValueError:
                self._error(400, "invalid 'top'")
                return
            payload = ledger.snapshot(top=top)
            if auditor is not None:
                payload["audit"] = auditor.status()
                payload["audit_log"] = auditor.recent(20)
                divergent = auditor.divergent(20)
                if divergent:
                    payload["audit_divergent"] = divergent
            self._reply_json(200, payload)

        def _debug_traces(self, query):
            """Read-only flight-recorder listing (span-free summaries;
            fetch /debug/traces/<id> for full spans)."""
            kind = query.get("kind", "recent")
            try:
                limit = max(1, min(int(query.get("limit", "50")), 1000))
            except ValueError:
                self._error(400, "invalid 'limit'")
                return
            recorder = TRACER.recorder
            if kind == "recent":
                traces = recorder.recent(limit)
            elif kind == "slow":
                traces = recorder.slow(limit)
            elif kind == "errored":
                traces = recorder.errored(limit)
            else:
                self._error(
                    400, "kind must be one of recent|slow|errored"
                )
                return
            self._reply_json(
                200,
                {
                    "kind": kind,
                    "count": len(traces),
                    "stats": TRACER.stats(),
                    "traces": [
                        t.to_dict(include_spans=False) for t in traces
                    ],
                },
            )

        def _debug_trace_by_id(self, trace_id: str):
            found = TRACER.recorder.get(trace_id)
            if found is None:
                self._error(404, "trace not found (evicted or never sampled)")
                return
            self._reply_json(200, found.to_dict())

        def _declares_body(self) -> bool:
            if self.headers.get("Transfer-Encoding"):
                return True
            # get_all: a conflicting duplicate pair like ('0', '100')
            # must count as declaring a body, or the smuggling guard
            # below is bypassed on paths that never reach _read_json.
            lengths = self.headers.get_all("Content-Length") or []
            return any(str(raw).strip() not in ("", "0") for raw in lengths)

        def do_POST(self):
            # Replies sent before the body is consumed (404 route, 403
            # admin gate, field validation) leave the body bytes
            # buffered to be parsed as the next request line on
            # keep-alive.  _read_json marks consumption; any exit
            # without it drops the connection.
            self._body_consumed = False
            try:
                path, query = self._split_path(self.path)
                if path == "/score_completions":
                    self._score_completions(query)
                elif path == "/score_chat_completions":
                    self._score_chat_completions(query)
                elif path == "/admin/purge_pod":
                    self._purge_pod()
                elif path == "/admin/snapshot":
                    self._snapshot()
                elif path == "/admin/incident":
                    self._incident()
                elif path == "/admin/whatif":
                    self._whatif()
                elif path == "/replica":
                    self._replica_call()
                else:
                    self._error(404, "not found")
            finally:
                if not self._body_consumed and self._declares_body():
                    self.close_connection = True

        def do_GET(self):
            # A GET that declares a body is pathological; its unread
            # bytes would desync keep-alive exactly like the POST case.
            # GET handlers never read a body, so marking it unconsumed
            # lets _reply's centralized guard close when one is declared.
            self._body_consumed = False
            self._do_get()

        def _admin_allowed(self) -> bool:
            """Scoring is read-only; /admin/* mutates, so it gets its
            own gate: a configured bearer token, or — when no token is
            set — loopback clients only (kubectl port-forward / exec),
            never the whole cluster network."""
            if admin_token:
                supplied = self.headers.get("Authorization", "")
                return supplied == f"Bearer {admin_token}"
            host = self.client_address[0]
            return host == "::1" or host.startswith("127.")

        def _replica_call(self):
            """Replica-serving RPC (docs/replication.md): one CBOR
            request per POST, dispatched through the cluster replica's
            method table (``ClusterReplica.handle_wire``).  Mutating
            like /admin/*, so it shares the admin gate — cluster
            deployments set ADMIN_TOKEN and give routers the same
            token; the tokenless default accepts loopback only."""
            if replica is None:
                self._error(
                    404, "not a cluster replica (set CLUSTER_SELF)"
                )
                return
            if not self._admin_allowed():
                self._error(
                    403, "replica endpoint: token or loopback only"
                )
                return
            body = self._read_body()
            if body is None:
                return
            self._reply(200, replica.handle_wire(body), "application/cbor")

        def _purge_pod(self):
            """Operator recovery: drop every index entry for one pod
            (Index.purge_pod) — e.g. after a pod dies or its event
            stream gapped badly.  O(index size), runs inline."""
            if not self._admin_allowed():
                self._error(403, "admin endpoint: token or loopback only")
                return
            request = self._read_json()
            if request is None:
                return
            pod = request.get("pod", "")
            if not pod:
                self._error(400, "field 'pod' required")
                return
            try:
                removed = indexer.kv_block_index.purge_pod(pod)
            except Exception as exc:
                logger.exception("purge_pod failed")
                self._error(500, f"error: {exc}")
                return
            reply = {"pod": pod, "removed": removed}
            if persistence is not None:
                # Journal the purge so recovery replays it in order —
                # without the record, replayed adds resurrect exactly
                # the entries this endpoint dropped.  The purge already
                # APPLIED: a journal failure (disk full) must not eat
                # the reply, but the operator needs to know recovery
                # would resurrect.
                try:
                    persistence.journal.record_purge(pod)
                    reply["journaled"] = True
                except Exception:  # noqa: BLE001 — purge applied; reply
                    logger.exception(
                        "purge applied but journaling failed: a "
                        "recovery would resurrect pod %s's entries",
                        pod,
                    )
                    reply["journaled"] = False
            self._reply_json(200, reply)

        def _snapshot(self):
            """Operator trigger: publish an index snapshot now (e.g.
            before a planned restart or rollout).  Admin-gated like
            purge_pod; 503 when the service runs without persistence.
            An empty body is allowed — the endpoint takes no fields."""
            if not self._admin_allowed():
                self._error(403, "admin endpoint: token or loopback only")
                return
            if self._declares_body():
                if self._read_json() is None:
                    return
            if persistence is None:
                self._error(503, "persistence not configured")
                return
            try:
                info = persistence.snapshot(indexer.kv_block_index)
            except Exception as exc:
                logger.exception("snapshot failed")
                self._error(500, f"error: {exc}")
                return
            self._reply_json(
                200,
                {
                    "path": info.path,
                    "bytes": info.size_bytes,
                    "block_keys": info.block_keys,
                    "engine_mappings": info.engine_mappings,
                },
            )

        def _incident(self):
            """Operator trigger: bundle the capture window + debug
            surfaces NOW (docs/observability.md "Incident response
            runbook") — e.g. to pin a live anomaly the SLO engine has
            not (yet) classified as violated.  Admin-gated like
            purge_pod; bypasses the SLO trigger's rate limit.  Body is
            optional: ``{"reason": "..."}``."""
            if not self._admin_allowed():
                self._error(403, "admin endpoint: token or loopback only")
                return
            reason = "admin"
            if self._declares_body():
                request = self._read_json()
                if request is None:
                    return
                reason = str(request.get("reason") or "admin")
            if incidents is None:
                self._error(503, "incident capture not configured")
                return
            try:
                manifest = incidents.trigger(
                    f"admin:{reason}", force=True
                )
            except Exception as exc:  # noqa: BLE001 — reply, don't wedge
                logger.exception("admin incident trigger failed")
                self._error(500, f"error: {exc}")
                return
            if manifest is None:
                self._error(500, "incident bundle failed (see logs)")
                return
            self._reply_json(200, manifest)

        def _whatif(self):
            """Operator what-if: replay a capture (or a retained
            incident bundle, by id) through candidate config arms
            IN-PROCESS and reply with the measured verdict.  Body:
            ``{"bundle": "inc-..."}`` or ``{"capture": "<path>"}``,
            plus optional ``"kind"`` ("run" | "ab", default "ab"),
            ``"arm"`` / ``"a"`` / ``"b"`` arm specs
            ("shards=8,mode=cluster"), and ``"speed"``.  Admin-gated:
            it reads operator-named filesystem paths and burns CPU for
            seconds.  The full result lands in the /debug/whatif ring;
            the reply carries the summary (docs/observability.md
            "What-if engine")."""
            if not self._admin_allowed():
                self._error(403, "admin endpoint: token or loopback only")
                return
            request = self._read_json() if self._declares_body() else {}
            if request is None:
                return
            from llm_d_kv_cache_manager_tpu.obs import whatif as whatif_mod

            source = None
            bundle = request.get("bundle")
            if bundle:
                if incidents is None:
                    self._error(503, "incident capture not configured")
                    return
                detail = incidents.detail(str(bundle))
                if detail is None:
                    self._error(404, f"no such incident: {bundle}")
                    return
                source = detail["directory"]
            elif request.get("capture"):
                source = str(request["capture"])
            else:
                self._error(400, "body needs 'bundle' or 'capture'")
                return
            try:
                config = whatif_mod.WhatIfConfig.from_env()
                if request.get("speed"):
                    config.speed = float(request["speed"])
                capture_doc = whatif_mod.load_capture(
                    whatif_mod.resolve_capture_source(source),
                    allow_mismatch=True,
                )
                kind = str(request.get("kind") or "ab")
                if kind == "run":
                    result = whatif_mod.run_whatif(
                        capture_doc,
                        whatif_mod.StackConfig.parse(
                            str(request.get("arm") or ""), name="a"
                        ),
                        config,
                    )
                elif kind == "ab":
                    result = whatif_mod.run_ab(
                        capture_doc,
                        whatif_mod.StackConfig.parse(
                            str(request.get("a") or "shards=1"),
                            name="a",
                        ),
                        whatif_mod.StackConfig.parse(
                            str(request.get("b") or "shards=8"),
                            name="b",
                        ),
                        config,
                    )
                else:
                    self._error(400, f"unknown kind {kind!r}")
                    return
            except (ValueError, FileNotFoundError, OSError) as exc:
                self._error(400, f"whatif failed: {exc}")
                return
            except Exception as exc:  # noqa: BLE001 — reply, don't wedge
                logger.exception("admin whatif failed")
                self._error(500, f"error: {exc}")
                return
            self._reply_json(
                200,
                {
                    "source": source,
                    "summary": whatif_mod._summarize(result),
                },
            )

        @staticmethod
        def _wants_explain(query) -> bool:
            return query.get("explain", "").lower() in ("1", "true", "yes")

        @staticmethod
        def _cluster_rpc_rollup(spans) -> Optional[Dict[str, dict]]:
            """Per-replica rollup of a trace's ``cluster.rpc`` spans —
            which owner dominated this score (docs/observability.md
            "Fleet tracing")."""
            rollup: Dict[str, dict] = {}
            for view in spans:
                if view["name"] != "cluster.rpc":
                    continue
                replica = str(
                    view["attributes"].get("replica", "unknown")
                )
                entry = rollup.setdefault(
                    replica, {"rpcs": 0, "total_ms": 0.0, "errors": 0}
                )
                entry["rpcs"] += 1
                entry["total_ms"] = round(
                    entry["total_ms"] + view["duration_ms"], 3
                )
                if view["status"] != "ok":
                    entry["errors"] += 1
            return rollup or None

        def _run_scored(self, name, query, score_kwargs, plan=False):
            """Shared scoring execution: trace lifecycle (traceparent
            ingest/echo, ``?explain=1`` forcing a sample), the explain
            response shape, and error accounting.  ``score_kwargs`` are
            handed to ``Indexer.get_pod_scores[_explained|_planned]``;
            ``plan`` opts the request into the transfer-directive
            channel (the planned scoring variant, docs/transfer.md)."""
            explain = self._wants_explain(query)
            req_trace = TRACER.start_trace(
                name,
                traceparent=self.headers.get("traceparent"),
                force=explain,
            )
            started = time.perf_counter()
            directive = None
            try:
                with use_trace(req_trace):
                    if explain:
                        scores, detail = (
                            indexer.get_pod_scores_explained(**score_kwargs)
                        )
                    elif plan:
                        scores, directive = (
                            indexer.get_pod_scores_planned(**score_kwargs)
                        )
                        detail = None
                    else:
                        scores, detail = (
                            indexer.get_pod_scores(**score_kwargs),
                            None,
                        )
            except Exception as exc:
                # The SLO feeds see FAILED requests too: a fully
                # failing service must burn the availability SLI, not
                # read as a no-data latency SLI (obs/slo.py).
                METRICS.score_latency.observe(
                    time.perf_counter() - started
                )
                METRICS.score_requests.labels(outcome="error").inc()
                if req_trace is not None:
                    req_trace.set_error(repr(exc))
                    req_trace.finish("error")
                logger.exception("%s failed", name)
                self._error(500, f"error: {exc}")
                return
            elapsed = time.perf_counter() - started
            headers: Dict[str, str] = {}
            if req_trace is not None:
                # Finish BEFORE replying so the trace is retrievable
                # from /debug/traces the moment the client sees the
                # echoed traceparent.
                req_trace.finish()
                headers["traceparent"] = req_trace.traceparent()
            # Every request feeds the SLO latency/availability SLIs —
            # unsampled, unlike the trace-fed stage histogram
            # (obs/slo.py); the observations sit outside the trace
            # window so they cannot widen the stage-sum gap the
            # acceptance tests pin.
            METRICS.score_latency.observe(elapsed)
            METRICS.score_requests.labels(outcome="ok").inc()
            if not explain:
                if plan:
                    # The directive rides the scoring response: the
                    # scheduler routes to the directive's target with a
                    # fetch instruction, or falls back to the scores.
                    self._reply_json(
                        200,
                        {"scores": scores, "transfer": directive},
                        headers,
                    )
                    return
                self._reply_json(200, scores, headers)
                return
            # explain forces sampling, so req_trace is always live here.
            trace_view = req_trace.to_dict(include_spans=True)
            detail = dict(detail)
            detail["trace_id"] = req_trace.trace_id
            detail["duration_ms"] = trace_view["duration_ms"]
            detail["stages"] = trace_view["stages"]
            cluster_rpcs = self._cluster_rpc_rollup(trace_view["spans"])
            if cluster_rpcs is not None:
                detail["cluster_rpcs"] = cluster_rpcs
            self._reply_json(
                200, {"scores": scores, "explain": detail}, headers
            )

        def _parse_pod_loads(self, request):
            """Optional ``pod_loads`` field: {pod: queue_depth}.
            Returns (ok, loads_or_None); replies 400 itself on a
            malformed field."""
            raw = request.get("pod_loads")
            if raw is None:
                return True, None
            if not isinstance(raw, dict):
                self._error(400, "field 'pod_loads' must be an object")
                return False, None
            loads = {}
            for pod, depth in raw.items():
                try:
                    loads[str(pod)] = float(depth)
                except (TypeError, ValueError):
                    self._error(
                        400, "field 'pod_loads' values must be numbers"
                    )
                    return False, None
            return True, loads

        def _score_completions(self, query):
            request = self._read_json()
            if request is None:
                return
            prompt = request.get("prompt", "")
            if not prompt:
                self._error(400, "field 'prompt' required")
                return
            ok, pod_loads = self._parse_pod_loads(request)
            if not ok:
                return
            self._run_scored(
                "http.score_completions",
                query,
                dict(
                    prompt=prompt,
                    model_name=request.get("model", ""),
                    pod_identifiers=request.get("pods"),
                    pod_loads=pod_loads,
                ),
                plan=bool(request.get("plan")),
            )

        def _score_chat_completions(self, query):
            request = self._read_json()
            if request is None:
                return
            messages = request.get("messages")
            if not messages:
                self._error(400, "field 'messages' required")
                return
            model = request.get("model", "")
            render_req = ApplyChatTemplateRequest(
                conversation=messages,
                tools=request.get("tools"),
                documents=request.get("documents"),
                chat_template=request.get("chat_template"),
                add_generation_prompt=request.get(
                    "add_generation_prompt", True
                ),
                continue_final_message=request.get(
                    "continue_final_message", False
                ),
                chat_template_kwargs=request.get("chat_template_kwargs"),
                model=model,
            )
            ok, pod_loads = self._parse_pod_loads(request)
            if not ok:
                return
            self._run_scored(
                "http.score_chat_completions",
                query,
                dict(
                    prompt="",
                    model_name=model,
                    pod_identifiers=request.get("pods"),
                    render_req=render_req,
                    pod_loads=pod_loads,
                ),
                plan=bool(request.get("plan")),
            )

    return Handler


class _NamedThreadingHTTPServer(http.server.ThreadingHTTPServer):
    """ThreadingHTTPServer whose per-connection handler threads carry
    the stable ``kvtpu-http-handler`` role name instead of the stock
    anonymous ``Thread-N`` — the profiler attributes request-handling
    wall time by it (docs/observability.md "Thread roles")."""

    def process_request_thread(self, request, client_address):
        threading.current_thread().name = "kvtpu-http-handler"
        super().process_request_thread(request, client_address)


def serve(
    indexer: Indexer,
    host: str = "0.0.0.0",
    port: int = 8080,
    admin_token: Optional[str] = None,
    persistence=None,
    recovery_report=None,
    event_plane_status=None,
    auditor=None,
    tiering=None,
    transfer=None,
    replica=None,
    cluster_status=None,
    slo=None,
    profiler=None,
    timeline=None,
    capture=None,
    incidents=None,
) -> http.server.ThreadingHTTPServer:
    """Start the HTTP service on a background thread; returns the server
    (call ``.shutdown()`` to stop).  ``admin_token`` (env:
    ``ADMIN_TOKEN``) gates ``/admin/*``; without one, admin calls are
    accepted from loopback only.  ``persistence`` (a
    ``PersistenceManager``) enables ``POST /admin/snapshot`` and the
    persistence block in ``/healthz``; ``recovery_report`` surfaces the
    startup recovery outcome there too; ``event_plane_status`` (a
    zero-arg callable) adds the event-plane block.  The indexer's
    hit-attribution ledger (``indexer.cache_stats``) backs
    ``GET /debug/cachestats`` and the ``/healthz`` analytics block;
    ``auditor`` (an ``analytics.IndexAuditor``) adds the index-truth
    audit plane to both; ``tiering`` (a ``tiering.PolicyEngine``)
    backs ``GET /debug/tiering`` and the ``/healthz`` tiering block;
    ``transfer`` (a ``transfer.TransferEngine``) backs
    ``GET /debug/transfer``, the ``/healthz`` transfer block, and the
    scoring requests' ``plan``/``pod_loads`` fields
    (docs/transfer.md); ``replica`` (a ``cluster.ClusterReplica``)
    serves the
    ``POST /replica`` RPC surface and ``cluster_status`` (a zero-arg
    callable) backs ``GET /debug/cluster`` (docs/replication.md);
    ``slo`` (an ``obs.slo.SloEngine``) backs ``GET /debug/slo`` and
    the ``/healthz`` degradation-envelope block; ``profiler`` (an
    ``obs.SamplingProfiler``) backs ``GET /debug/profile`` and
    ``timeline`` (an ``obs.GaugeTimeline``) ``GET /debug/timeline``;
    ``capture`` (an ``obs.InputCaptureRecorder``) and ``incidents``
    (an ``obs.IncidentManager``) back ``GET /debug/incidents``,
    ``POST /admin/incident`` and the ``/healthz`` capture block —
    ``GET /debug/`` indexes every surface (docs/observability.md)."""
    server = _NamedThreadingHTTPServer(
        (host, port),
        _make_handler(
            indexer,
            admin_token=admin_token,
            persistence=persistence,
            recovery_report=recovery_report,
            event_plane_status=event_plane_status,
            auditor=auditor,
            tiering=tiering,
            transfer=transfer,
            replica=replica,
            cluster_status=cluster_status,
            slo=slo,
            profiler=profiler,
            timeline=timeline,
            capture=capture,
            incidents=incidents,
        ),
    )
    thread = threading.Thread(
        target=server.serve_forever,
        name="kvtpu-http-service",
        daemon=True,
    )
    thread.start()
    logger.info("http scoring service listening on %s:%d", host, port)
    return server


def main() -> None:  # pragma: no cover - CLI entry
    """Env-configured standalone service: indexer + event subscription
    (the reference's online example, main.go:93-148)."""
    from llm_d_kv_cache_manager_tpu.kvcache.indexer import IndexerConfig
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
        IndexConfig,
        InMemoryIndexConfig,
        RedisIndexConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
        TokenProcessorConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvevents.pool import Pool, PoolConfig
    from llm_d_kv_cache_manager_tpu.kvevents.subscriber_manager import (
        SubscriberManager,
    )
    from llm_d_kv_cache_manager_tpu.metrics.collector import (
        start_metrics_logging,
    )
    from llm_d_kv_cache_manager_tpu.tokenization.pool import (
        TokenizationPoolConfig,
    )

    config = IndexerConfig(
        token_processor_config=TokenProcessorConfig(
            block_size=int(os.environ.get("BLOCK_SIZE", "16")),
            hash_seed=os.environ.get("PYTHONHASHSEED", ""),
        ),
        kvblock_index_config=IndexConfig(
            enable_metrics=os.environ.get("ENABLE_METRICS", "true").lower()
            != "false",
            # Lock stripes for the in-memory backend (ignored for
            # Redis); power of two, docs/performance.md.
            in_memory_config=InMemoryIndexConfig(
                shards=int(os.environ.get("INDEX_SHARDS", "8"))
            ),
            # e.g. INDEX_BACKEND=valkey://valkey:6379 selects the shared
            # distributed index; unset keeps the in-memory backend.
            redis_config=(
                RedisIndexConfig(
                    address=os.environ["INDEX_BACKEND"],
                    tls_ca_file=os.environ.get("INDEX_TLS_CA_FILE")
                    or None,
                    tls_insecure_skip_verify=os.environ.get(
                        "INDEX_TLS_INSECURE", ""
                    ).lower()
                    in ("1", "true", "yes"),
                )
                if os.environ.get("INDEX_BACKEND")
                else None
            ),
        ),
        tokenizers_pool_config=TokenizationPoolConfig(
            model_name=os.environ.get("MODEL_NAME", "")
        ),
        local_tokenizers_dir=os.environ.get("LOCAL_TOKENIZER_DIR") or None,
        uds_tokenizer_path=os.environ.get("UDS_TOKENIZER_PATH") or None,
        # read_path_fast_lane stays None here: the Indexer resolves the
        # READ_PATH_FAST_LANE env knob itself (docs/performance.md).
        lookup_chunk_size=int(
            os.environ.get("READ_PATH_LOOKUP_CHUNK", "32")
        ),
    )
    # CLUSTER_REPLICAS makes this process a cluster ROUTER: the local
    # backend selection is replaced by a RemoteIndex fanning out to the
    # configured replicas over HTTP (docs/replication.md).  The rest of
    # the stack — scoring, kvevents pool, analytics, tiering — works
    # unchanged against the remote backend.
    cluster_membership = None
    cluster_heartbeat = None
    cluster_remote_index = None
    injected_index = None
    if os.environ.get("CLUSTER_REPLICAS"):
        from llm_d_kv_cache_manager_tpu.cluster import (
            ClusterMembership,
            HeartbeatMonitor,
            RemoteIndex,
        )
        from llm_d_kv_cache_manager_tpu.cluster.replica import (
            HttpReplicaTransport,
        )

        transports = {}
        for pair in os.environ["CLUSTER_REPLICAS"].split(","):
            replica_id, _, url = pair.strip().partition("=")
            if not replica_id or not url:
                raise ValueError(
                    "CLUSTER_REPLICAS expects id=url[,id=url...]; got "
                    f"{pair!r}"
                )
            transports[replica_id] = HttpReplicaTransport(
                url, token=os.environ.get("ADMIN_TOKEN")
            )
        cluster_membership = ClusterMembership(transports)
        cluster_heartbeat = HeartbeatMonitor(
            cluster_membership,
            interval_s=float(os.environ.get("CLUSTER_HEARTBEAT_S", "2")),
            misses=int(os.environ.get("CLUSTER_HEARTBEAT_MISSES", "2")),
        )
        cluster_heartbeat.start()
        # Explicit env resolution for the fan-out knobs (the
        # RemoteIndex would resolve them itself; naming them here
        # keeps the router's tuning surface discoverable —
        # docs/configuration.md): CLUSTER_FANOUT_WORKERS (0 =
        # sequential parity oracle), CLUSTER_FANOUT_BUDGET_S (whole
        # fan-out deadline across re-routes), CLUSTER_VV_TTL_S
        # (version-vector staleness bound for the score memo),
        # CLUSTER_OVERLAP_MIN_RPC_S (adaptive-arming latency
        # threshold; 0 forces overlap always-on).
        from llm_d_kv_cache_manager_tpu.cluster.remote_index import (
            resolve_fanout_budget_env,
            resolve_fanout_workers_env,
            resolve_overlap_min_rpc_env,
            resolve_vv_ttl_env,
        )

        cluster_remote_index = RemoteIndex(
            cluster_membership,
            fanout_workers=resolve_fanout_workers_env(),
            fanout_budget_s=resolve_fanout_budget_env(),
            vv_ttl_s=resolve_vv_ttl_env(),
            overlap_min_rpc_s=resolve_overlap_min_rpc_env(),
        )
        injected_index = cluster_remote_index
        if config.kvblock_index_config.enable_metrics:
            from llm_d_kv_cache_manager_tpu.kvcache.kvblock.instrumented import (  # noqa: E501 - lazy: mirrors new_index's wrap
                InstrumentedIndex,
            )

            injected_index = InstrumentedIndex(injected_index)

    # CAPTURE (default on) wires the input flight recorder
    # (obs/capture.py): the kvevents pool and the indexer tap every
    # ingress message/scored request into bounded rings
    # (CAPTURE_WINDOW_S / CAPTURE_MAX_BYTES) that incident bundles
    # snapshot and obs/replay.py re-drives.  CAPTURE=0 is fully inert:
    # no recorder object, no ring, no thread — the taps see None.
    from llm_d_kv_cache_manager_tpu.obs.capture import (
        CaptureConfig,
        InputCaptureRecorder,
        capture_enabled_env,
        set_build_info_metric,
    )

    set_build_info_metric()
    capture = None
    if capture_enabled_env():
        capture = InputCaptureRecorder(
            CaptureConfig.from_env(),
            meta={
                "block_size": config.token_processor_config.block_size,
                "hash_seed": config.token_processor_config.hash_seed,
                "model": os.environ.get("MODEL_NAME", ""),
            },
        )

    indexer = Indexer(
        config, kv_block_index=injected_index, capture_recorder=capture
    )
    indexer.run()

    # CLUSTER_SELF makes this process a cluster REPLICA: the local
    # index (built from the normal backend config above) serves the
    # POST /replica RPC surface, journals applied ops for replication
    # (CLUSTER_JOURNAL_DIR), and tails its peers' journals for the
    # standby slice (CLUSTER_FOLLOW, filtered by CLUSTER_MEMBERS).
    cluster_replica = None
    cluster_followers = []
    if os.environ.get("CLUSTER_SELF"):
        from llm_d_kv_cache_manager_tpu.cluster import (
            ClusterReplica,
            ReplicationFollower,
            standby_record_filter,
        )
        from llm_d_kv_cache_manager_tpu.cluster.ring import HashRing
        from llm_d_kv_cache_manager_tpu.persistence.journal import Journal

        replica_journal = None
        if os.environ.get("CLUSTER_JOURNAL_DIR"):
            replica_journal = Journal(os.environ["CLUSTER_JOURNAL_DIR"])
        cluster_replica = ClusterReplica(
            os.environ["CLUSTER_SELF"],
            index=indexer.kv_block_index,
            journal=replica_journal,
            journal_retain_segments=int(
                os.environ.get("CLUSTER_JOURNAL_RETAIN", "64")
            ),
        )
        record_filter = None
        members_ring = None
        members_raw = os.environ.get("CLUSTER_MEMBERS", "")
        if members_raw:
            members_ring = HashRing(
                [m.strip() for m in members_raw.split(",") if m.strip()]
            )
            record_filter = standby_record_filter(
                members_ring, cluster_replica.replica_id
            )
        for pair in (os.environ.get("CLUSTER_FOLLOW") or "").split(","):
            if not pair.strip():
                continue
            peer, _, directory = pair.strip().partition("=")
            if not peer or not directory:
                raise ValueError(
                    "CLUSTER_FOLLOW expects peer=journal_dir[,...]; "
                    f"got {pair!r}"
                )
            follower = ReplicationFollower(
                peer,
                directory,
                indexer.kv_block_index,
                record_filter=record_filter,
                poll_interval_s=float(
                    os.environ.get("CLUSTER_FOLLOW_POLL_S", "0.2")
                ),
                # Scope the peer's purge replays to its primary slice
                # (needs the full member ring; unscoped otherwise).
                purge_scope=(
                    (
                        lambda key, peer=peer, ring=members_ring: (
                            ring.owner(key) == peer
                        )
                    )
                    if members_ring is not None
                    else None
                ),
            )
            follower.start()
            cluster_followers.append(follower)

    cluster_status = None
    if cluster_membership is not None or cluster_replica is not None:
        def cluster_status() -> dict:
            status = {
                "role": "router" if cluster_membership else "replica"
            }
            if cluster_membership is not None:
                status["membership"] = cluster_membership.status()
            if cluster_remote_index is not None:
                # Per-replica fan-out attribution + the sequential
                # critical-path breakdown (docs/observability.md).
                status["rpc"] = cluster_remote_index.rpc_stats()
            if cluster_replica is not None:
                status["replica"] = cluster_replica.replica_id
            if cluster_followers:
                status["replication"] = [
                    f.status() for f in cluster_followers
                ]
            return status

    # TIERING=1 attaches the predictive-tiering policy engine
    # (docs/tiering.md): the scoring stream feeds its PolicyFeed,
    # explain carries compute-or-load advice, and /debug/tiering
    # exposes the policy plane.  The demotion worker needs a pod-side
    # target, so the standalone indexer runs without one.
    policy_engine = None
    if os.environ.get("TIERING", "").lower() in ("1", "true", "yes"):
        from llm_d_kv_cache_manager_tpu.tiering import PolicyEngine

        policy_engine = PolicyEngine(ledger=indexer.cache_stats)
        indexer.set_policy_engine(policy_engine)

    # TRANSFER=1 attaches the KV-transfer planning plane
    # (docs/transfer.md): scoring requests carrying pod_loads/plan get
    # transfer directives, executed transfers publish real KVEvents
    # through the pool (attached below, after the pool exists), and
    # /debug/transfer exposes the plane.  Shares the tiering advisor
    # when TIERING=1 so both planes price from one RTT model.
    transfer_engine = None
    if os.environ.get("TRANSFER", "").lower() in ("1", "true", "yes"):
        from llm_d_kv_cache_manager_tpu.transfer import TransferEngine

        transfer_engine = TransferEngine(
            advisor=(
                policy_engine.advisor
                if policy_engine is not None
                else None
            ),
            ledger=indexer.cache_stats,
        )
        indexer.set_transfer_engine(transfer_engine)

    # PERSISTENCE_DIR enables warm restarts: recover the index from the
    # last snapshot + journal tail BEFORE the event pool starts, then
    # journal every applied event and snapshot periodically.
    persistence = None
    recovery_report = None
    stop_snapshots = None
    if os.environ.get("PERSISTENCE_DIR"):
        from llm_d_kv_cache_manager_tpu.persistence import (
            PersistenceConfig,
            PersistenceManager,
        )

        persistence = PersistenceManager(
            PersistenceConfig(
                directory=os.environ["PERSISTENCE_DIR"],
                journal_fsync=os.environ.get(
                    "PERSISTENCE_FSYNC", ""
                ).lower()
                in ("1", "true", "yes"),
            )
        )
        recovery_report = persistence.recover(indexer.kv_block_index)
        stop_snapshots = persistence.start_auto_snapshot(
            indexer.kv_block_index,
            float(os.environ.get("PERSISTENCE_SNAPSHOT_INTERVAL", "300")),
        )

    pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(
            concurrency=int(os.environ.get("POOL_CONCURRENCY", "4")),
            apply_batch_size=int(
                os.environ.get("KVEVENTS_APPLY_BATCH", "32")
            ),
            # Per-pod flow control (docs/event-plane.md): in-flight
            # budget per pod, fairness-aware shedding.  0/unset budget
            # -> whole-shard depth (budget engages only at overflow);
            # the 0 case must map to None here or PoolConfig would
            # clamp it to a 1-message budget.
            pod_budget=(
                int(os.environ.get("KVEVENTS_POD_BUDGET") or 0) or None
            ),
            per_pod_flow_control=os.environ.get(
                "KVEVENTS_POD_FLOW", "1"
            ).lower()
            not in ("0", "false", "no"),
        ),
        journal=persistence.journal if persistence else None,
        capture=capture,
    )
    pool.start()
    if transfer_engine is not None:
        # The directive channel's write side: executed transfers (and
        # cold-pod warm-up) publish through this pool, so every move
        # lands in the index/ledger/journal via the ordinary
        # decode/apply path.
        transfer_engine.attach_executor(
            indexer.kv_block_index,
            pool,
            os.environ.get("MODEL_NAME", ""),
        )
    # Gap-driven anti-entropy (docs/event-plane.md): a wire-level seq
    # gap marks the pod suspect and triggers purge + inventory
    # re-apply.  Without a fleet inventory surface the default "purge"
    # mode uses the empty source (purge-only repair); "off" disables.
    resync = None
    if os.environ.get("KVEVENTS_GAP_RESYNC", "purge").lower() not in (
        "off",
        "0",
        "false",
        "no",
    ):
        from llm_d_kv_cache_manager_tpu.kvevents.resync import (
            EmptyInventorySource,
            ResyncManager,
        )

        resync = ResyncManager(pool, EmptyInventorySource())
        resync.start()
    # Two event-ingestion modes (reference online example supports both):
    # - POD_DISCOVERY=true: watch the k8s API and dial out to each serving
    #   pod's ZMQ socket (needs the pod list/watch RBAC grant);
    # - default: bind one global SUB socket engines connect to.
    discover = os.environ.get("POD_DISCOVERY", "").lower() in (
        "1",
        "true",
        "yes",
    )
    manager = SubscriberManager(
        sink=pool.add_task,
        # Batched fast-lane delivery: each poller burst is one
        # enqueue + one lock-free pre-decode pass (event-plane.md).
        sink_batch=pool.add_tasks,
        bind=not discover,
        on_gap=resync.gap_listener if resync else None,
    )
    # CLUSTER_LOCAL_INGEST=1 (replica mode + discovery): this replica
    # subscribes to only its pod slice of the fleet — the event plane's
    # write throughput then scales with the replica count instead of
    # funneling through one process (docs/event-plane.md).  The
    # reconciler announces the whole fleet; the ingestor slices it over
    # the member ring and re-slices on ring changes.
    ingestor = None
    if os.environ.get("CLUSTER_LOCAL_INGEST", "").lower() in (
        "1",
        "true",
        "yes",
    ):
        members_raw = os.environ.get("CLUSTER_MEMBERS", "")
        self_id = os.environ.get("CLUSTER_SELF", "")
        if not (
            discover
            and members_raw
            and self_id
            and cluster_membership is not None
        ):
            # CLUSTER_REPLICAS (the router wiring) is load-bearing,
            # not optional: it injects the RemoteIndex the pool
            # applies through (pod-sliced subscriptions + KEY-sliced
            # applies compose only then — a local backend would strand
            # ~(N-1)/N of claims on the wrong replica) and provides
            # the membership whose ring bumps drive re-slicing.
            logger.warning(
                "CLUSTER_LOCAL_INGEST needs POD_DISCOVERY, "
                "CLUSTER_SELF, CLUSTER_MEMBERS and CLUSTER_REPLICAS "
                "(the RemoteIndex apply path + ring membership); "
                "ignoring"
            )
        else:
            from llm_d_kv_cache_manager_tpu.cluster.ingest import (
                ReplicaIngestor,
            )
            from llm_d_kv_cache_manager_tpu.cluster.ring import HashRing

            ingestor = ReplicaIngestor(
                self_id,
                manager,
                ring=HashRing(
                    [
                        m.strip()
                        for m in members_raw.split(",")
                        if m.strip()
                    ]
                ),
                membership=cluster_membership,
                resync=resync,
            )

    reconciler = None
    if discover:
        from llm_d_kv_cache_manager_tpu.kvevents.pod_reconciler import (
            DEFAULT_LABEL_SELECTOR,
            PodReconciler,
            PodReconcilerConfig,
        )

        reconciler = PodReconciler(
            ingestor if ingestor is not None else manager,
            PodReconcilerConfig(
                namespace=os.environ.get("POD_NAMESPACE") or None,
                label_selector=os.environ.get(
                    "POD_LABEL_SELECTOR", DEFAULT_LABEL_SELECTOR
                ),
                socket_port=int(os.environ.get("POD_SOCKET_PORT", "5557")),
                topic_filter=os.environ.get("ZMQ_TOPIC", "kv@"),
                # Out-of-cluster override (local runs / tests); in-cluster
                # the service-account environment is discovered.
                api_server=os.environ.get("POD_API_SERVER") or None,
                token=os.environ.get("POD_API_TOKEN") or None,
            ),
        )
        reconciler.start()
    else:
        endpoint = os.environ.get("ZMQ_ENDPOINT", "tcp://*:5557")
        manager.ensure_subscriber(
            "global",
            endpoint,
            topic_filter=os.environ.get("ZMQ_TOPIC", "kv@"),
        )

    stop_beat = start_metrics_logging(
        float(os.environ.get("METRICS_LOGGING_INTERVAL", "60"))
    )

    # Continuous profiling plane (docs/observability.md): the
    # always-on sampling profiler (PROFILE_HZ, 0 = fully inert), gc
    # pause accounting, and the 1s gauge timeline rings
    # (TIMELINE_WINDOW_S, 0 disables) feeding /debug/profile and
    # /debug/timeline.  Lock-contention timing arms itself from
    # LOCK_CONTENTION_SAMPLE at lock construction (utils/lockorder.py).
    from llm_d_kv_cache_manager_tpu.metrics.collector import (
        install_gc_metrics,
    )
    from llm_d_kv_cache_manager_tpu.obs.profiler import PROFILER
    from llm_d_kv_cache_manager_tpu.obs.timeline import (
        GaugeTimeline,
        register_default_series,
    )

    install_gc_metrics()
    PROFILER.start()
    timeline = GaugeTimeline()
    register_default_series(
        timeline,
        pool=pool,
        remote_index=cluster_remote_index,
        resync=resync,
    )
    timeline.start()

    # SLO_ENABLE (default on) attaches the degradation-envelope engine
    # (obs/slo.py): the stock fleet SLIs are fed from existing metric
    # surfaces, evaluated over a fast and a slow window, and published
    # at GET /debug/slo + the /healthz slo block.
    slo_engine = None
    if os.environ.get("SLO_ENABLE", "1").lower() not in (
        "0",
        "false",
        "off",
        "no",
    ):
        from llm_d_kv_cache_manager_tpu.obs.slo import default_fleet_slos

        slo_engine = default_fleet_slos(
            window_fast_s=float(
                os.environ.get("SLO_WINDOW_FAST_S", "300")
            ),
            window_slow_s=float(
                os.environ.get("SLO_WINDOW_SLOW_S", "3600")
            ),
            score_latency_s=(
                float(os.environ.get("SLO_SCORE_LATENCY_MS", "250"))
                / 1000.0
            ),
            hit_rate_objective=float(
                os.environ.get("SLO_HIT_RATE_OBJECTIVE", "0")
            ),
            membership=cluster_membership,
            pool=pool,
        )
        slo_engine.start(float(os.environ.get("SLO_POLL_S", "5")))

    # Incident bundler (obs/capture.py): subscribes to the SLO
    # engine's overall-state transitions — healthy→violated dumps the
    # capture window plus every other debug surface into one versioned
    # bundle under INCIDENT_DIR, rate-limited by
    # INCIDENT_MIN_INTERVAL_S and pruned to INCIDENT_KEEP;
    # POST /admin/incident forces one (docs/observability.md).
    incidents = None
    if capture is not None:
        from llm_d_kv_cache_manager_tpu.obs.capture import (
            IncidentManager,
        )
        from llm_d_kv_cache_manager_tpu.utils import lockorder

        incident_sources = {
            "traces": lambda: {
                "stats": TRACER.stats(),
                "slow": [
                    t.to_dict() for t in TRACER.recorder.slow(20)
                ],
                "errored": [
                    t.to_dict() for t in TRACER.recorder.errored(20)
                ],
                "recent": [
                    t.to_dict(include_spans=False)
                    for t in TRACER.recorder.recent(50)
                ],
            },
            "profile": lambda: {
                "profiler": (
                    PROFILER.status(top=30)
                    if PROFILER.config.hz > 0
                    else {"disabled": True}
                ),
                "locks": lockorder.contention_stats(),
            },
            "timeline": lambda: (
                timeline.snapshot()
                if timeline.window_s > 0
                else {"disabled": True}
            ),
        }
        if cluster_status is not None:
            incident_sources["cluster"] = cluster_status
        if slo_engine is not None:
            incident_sources["slo"] = (
                lambda: slo_engine.last_payload() or {"no_data": True}
            )
        incidents = IncidentManager(
            os.environ.get("INCIDENT_DIR", "incidents"),
            capture=capture,
            sources=incident_sources,
            index=indexer.kv_block_index,
            keep=int(os.environ.get("INCIDENT_KEEP", "8")),
            min_interval_s=float(
                os.environ.get("INCIDENT_MIN_INTERVAL_S", "60")
            ),
        )
        if slo_engine is not None:
            slo_engine.add_listener(incidents.slo_listener())

    def event_plane_status() -> dict:
        status = {
            "pollers": manager.poller_count(),
            "subscriptions": len(manager.active_pods()),
        }
        if resync is not None:
            status["resync"] = resync.stats()
        if ingestor is not None:
            status["local_ingest"] = ingestor.status()
        status["stages"] = pool.stage_stats()
        return status

    server = serve(
        indexer,
        port=int(os.environ.get("HTTP_PORT", "8080")),
        admin_token=os.environ.get("ADMIN_TOKEN"),
        persistence=persistence,
        recovery_report=recovery_report,
        event_plane_status=event_plane_status,
        tiering=policy_engine,
        transfer=transfer_engine,
        replica=cluster_replica,
        cluster_status=cluster_status,
        slo=slo_engine,
        profiler=PROFILER,
        timeline=timeline,
        capture=capture,
        incidents=incidents,
    )
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        stop_beat.set()
        timeline.close()
        PROFILER.close()
        if slo_engine is not None:
            slo_engine.close()
        if stop_snapshots is not None:
            stop_snapshots.set()
        server.shutdown()
        if reconciler is not None:
            reconciler.stop()
        manager.shutdown()
        if resync is not None:
            resync.close()
        pool.shutdown()
        if persistence is not None:
            # Parting snapshot: the next start recovers warm even if
            # the periodic beat never fired.
            try:
                persistence.snapshot(indexer.kv_block_index)
            except Exception:  # noqa: BLE001 - best-effort on the way out
                logger.exception("shutdown snapshot failed")
            persistence.close()
        if cluster_heartbeat is not None:
            cluster_heartbeat.close()
        if cluster_remote_index is not None:
            cluster_remote_index.close()
        for follower in cluster_followers:
            follower.close()
        if cluster_replica is not None:
            cluster_replica.close()
        if transfer_engine is not None:
            transfer_engine.close()
        if policy_engine is not None:
            policy_engine.close()
        indexer.shutdown()


if __name__ == "__main__":  # pragma: no cover
    main()
