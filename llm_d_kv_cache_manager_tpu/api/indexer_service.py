"""gRPC scoring service wrapping the Indexer.

TPU-native counterpart of the reference's index service
(examples/kv_cache_index_service/server/server.go:67-93): one RPC,
``GetPodScores``, delegating to ``Indexer.get_pod_scores``.  Serves TCP
or Unix-domain endpoints (``unix:///path.sock``).
"""

from __future__ import annotations

from concurrent import futures
from typing import Optional

import grpc

from llm_d_kv_cache_manager_tpu.api import indexer_pb2
from llm_d_kv_cache_manager_tpu.api.grpc_services import (
    IndexerServiceServicer,
    IndexerServiceStub,
    add_indexer_servicer,
)
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer
from llm_d_kv_cache_manager_tpu.obs.trace import TRACER, use_trace
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("api.indexer_service")


class IndexerGrpcService(IndexerServiceServicer):
    def __init__(self, indexer: Indexer) -> None:
        self.indexer = indexer

    def GetPodScores(self, request, context):
        # W3C traceparent rides gRPC metadata (same semantics as the
        # HTTP header): a sampled flag forces tracing, and the server's
        # own traceparent is echoed in the initial metadata.
        traceparent = None
        for key, value in context.invocation_metadata() or ():
            if key == "traceparent":
                traceparent = value
        req_trace = TRACER.start_trace(
            "grpc.get_pod_scores", traceparent=traceparent
        )
        try:
            with use_trace(req_trace):
                scores = self.indexer.get_pod_scores(
                    prompt=request.prompt,
                    model_name=request.model_name,
                    pod_identifiers=list(request.pod_identifiers) or None,
                )
        except Exception as exc:
            if req_trace is not None:
                req_trace.set_error(repr(exc))
                req_trace.finish("error")
            logger.exception("GetPodScores failed")
            context.abort(grpc.StatusCode.INTERNAL, str(exc))
            return indexer_pb2.GetPodScoresResponse()
        if req_trace is not None:
            req_trace.finish()
            try:
                context.send_initial_metadata(
                    (("traceparent", req_trace.traceparent()),)
                )
            except Exception as exc:  # noqa: BLE001 - echo is best-effort
                # Headers may already be on the wire; the trace itself
                # is recorded either way.
                logger.debug("traceparent metadata echo failed: %s", exc)
        response = indexer_pb2.GetPodScoresResponse()
        # Deterministic order: score desc, pod asc (stable for clients).
        for pod, score in sorted(
            scores.items(), key=lambda kv: (-kv[1], kv[0])
        ):
            response.scores.add(pod=pod, score=score)
        return response


def serve(
    indexer: Indexer,
    address: str = "[::]:50051",
    max_workers: int = 8,
    server: Optional[grpc.Server] = None,
) -> grpc.Server:
    """Build+start a server; returns it (caller owns lifetime)."""
    if server is None:
        server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=max_workers,
                thread_name_prefix="kvtpu-grpc",
            )
        )
    add_indexer_servicer(IndexerGrpcService(indexer), server)
    server.add_insecure_port(address)
    server.start()
    logger.info("indexer gRPC service listening on %s", address)
    return server


def new_client(address: str) -> IndexerServiceStub:
    return IndexerServiceStub(grpc.insecure_channel(address))
