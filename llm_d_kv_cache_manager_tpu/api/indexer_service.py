"""gRPC scoring service wrapping the Indexer.

TPU-native counterpart of the reference's index service
(examples/kv_cache_index_service/server/server.go:67-93): one RPC,
``GetPodScores``, delegating to ``Indexer.get_pod_scores``.  Serves TCP
or Unix-domain endpoints (``unix:///path.sock``).
"""

from __future__ import annotations

from concurrent import futures
from typing import Optional

import grpc

from llm_d_kv_cache_manager_tpu.api import indexer_pb2
from llm_d_kv_cache_manager_tpu.api.grpc_services import (
    IndexerServiceServicer,
    IndexerServiceStub,
    add_indexer_servicer,
)
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("api.indexer_service")


class IndexerGrpcService(IndexerServiceServicer):
    def __init__(self, indexer: Indexer) -> None:
        self.indexer = indexer

    def GetPodScores(self, request, context):
        try:
            scores = self.indexer.get_pod_scores(
                prompt=request.prompt,
                model_name=request.model_name,
                pod_identifiers=list(request.pod_identifiers) or None,
            )
        except Exception as exc:
            logger.exception("GetPodScores failed")
            context.abort(grpc.StatusCode.INTERNAL, str(exc))
            return indexer_pb2.GetPodScoresResponse()
        response = indexer_pb2.GetPodScoresResponse()
        # Deterministic order: score desc, pod asc (stable for clients).
        for pod, score in sorted(
            scores.items(), key=lambda kv: (-kv[1], kv[0])
        ):
            response.scores.add(pod=pod, score=score)
        return response


def serve(
    indexer: Indexer,
    address: str = "[::]:50051",
    max_workers: int = 8,
    server: Optional[grpc.Server] = None,
) -> grpc.Server:
    """Build+start a server; returns it (caller owns lifetime)."""
    if server is None:
        server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers)
        )
    add_indexer_servicer(IndexerGrpcService(indexer), server)
    server.add_insecure_port(address)
    server.start()
    logger.info("indexer gRPC service listening on %s", address)
    return server


def new_client(address: str) -> IndexerServiceStub:
    return IndexerServiceStub(grpc.insecure_channel(address))
