"""Replicated, consistent-hash-sharded index service.

The single-process indexer is fast, durable, and observable — and a
SPOF.  This package turns it into an N-replica service (ROADMAP item 1;
the inter-process analogue of the striped ``InMemoryIndex``):

* :mod:`ring` — deterministic, versioned rendezvous hashing over
  block-key space; adding/removing one replica moves ~1/N keys, never a
  full reshuffle.
* :mod:`remote_index` — an :class:`~..kvcache.kvblock.index.Index`
  implementation satisfying the existing ``lookup_chain`` /
  ``add_entries_batch`` / ``dump_entries`` contract that fans chunked
  lookups out to owner replicas (one RPC round per owner per chunk), so
  the read-path fast lane, score memo, analytics ledger, and tiering
  feed all work unchanged.
* :mod:`replica` — the replica-side apply surface (the RPC method
  table over a local backend, with a post-apply journal tap) plus the
  local and HTTP transports.
* :mod:`replication` — followers warm-sync from a primary's snapshot
  boundary and stay current by tailing its journal segments
  (``persistence.journal.tail``), so a killed replica's slice fails
  over to warm state with a bounded hit-rate dip.
* :mod:`membership` — static replica config + heartbeat health; a
  missed-heartbeat replica is removed from the ring (version bump,
  failover counter) and its keys route to their rendezvous runner-up.
* :mod:`ingest` — replica-local ingestion: the pod fleet's event
  streams are sliced over the same ring (pod-id rendezvous), each
  replica subscribing to only its slice, so write throughput scales
  with the replica count; ring bumps re-slice and takeover pods are
  resynced (docs/event-plane.md).

See docs/replication.md for the topology and the failover state
machine; ``CLUSTER_*`` env wiring lives in ``api/http_service.py``.
"""

from llm_d_kv_cache_manager_tpu.cluster.harness import (  # noqa: F401
    LocalCluster,
)
from llm_d_kv_cache_manager_tpu.cluster.ingest import (  # noqa: F401
    ReplicaIngestor,
    pod_owner,
    pod_slice_key,
    slice_pods,
)
from llm_d_kv_cache_manager_tpu.cluster.membership import (  # noqa: F401
    ClusterMembership,
    HeartbeatMonitor,
)
from llm_d_kv_cache_manager_tpu.cluster.remote_index import (  # noqa: F401
    RemoteIndex,
)
from llm_d_kv_cache_manager_tpu.cluster.replica import (  # noqa: F401
    ClusterReplica,
    HttpReplicaTransport,
    LocalReplicaTransport,
    ReplicaError,
    ReplicaUnavailable,
)
from llm_d_kv_cache_manager_tpu.cluster.replication import (  # noqa: F401
    ReplicationFollower,
    standby_record_filter,
)
from llm_d_kv_cache_manager_tpu.cluster.ring import HashRing  # noqa: F401
