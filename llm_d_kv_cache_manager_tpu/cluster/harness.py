"""In-process cluster harness: N replicas + membership + replication.

One constructor for every consumer that wants a real cluster without
processes: the contract-parity tests, the parity oracle, the
``replica_scaleout`` bench regime, and ``hack/cluster_smoke.py``.  The
replicas are genuine :class:`~.replica.ClusterReplica` instances (own
``InMemoryIndex`` slice, own journal directory) wired through
:class:`~.replica.LocalReplicaTransport` — the same method table the
HTTP endpoint serves, so nothing here is test-only behavior.

With ``journal_root`` set, every replica journals its applied ops and
runs one :class:`~.replication.ReplicationFollower` per peer, filtered
to its standby slice; ``sync_followers()`` drains every follower once
(deterministic alternative to the background threads).  ``kill()``
makes a replica's transport refuse calls — the next heartbeat (or the
first routed call that hits it) removes it from the ring and its slice
fails over warm.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from llm_d_kv_cache_manager_tpu.cluster.membership import (
    ClusterMembership,
    HeartbeatMonitor,
)
from llm_d_kv_cache_manager_tpu.cluster.remote_index import RemoteIndex
from llm_d_kv_cache_manager_tpu.cluster.replica import (
    ClusterReplica,
    LocalReplicaTransport,
)
from llm_d_kv_cache_manager_tpu.cluster.replication import (
    ReplicationFollower,
    standby_record_filter,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    InMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    InMemoryIndexConfig,
)
from llm_d_kv_cache_manager_tpu.persistence.journal import Journal


class LocalCluster:
    """See module docstring."""

    def __init__(
        self,
        replica_ids: Sequence[str] = ("replica-0", "replica-1", "replica-2"),
        journal_root: Optional[str] = None,
        index_config: Optional[InMemoryIndexConfig] = None,
        strict_wire: bool = False,
        heartbeat_interval_s: float = 0.5,
        follower_poll_s: float = 0.1,
        fanout_workers: Optional[int] = None,
        fanout_budget_s: Optional[float] = None,
        vv_ttl_s: Optional[float] = None,
        overlap_min_rpc_s: Optional[float] = None,
        transport_wrap=None,
    ) -> None:
        self.replicas: Dict[str, ClusterReplica] = {}
        self.transports: Dict[str, LocalReplicaTransport] = {}
        self.journal_dirs: Dict[str, str] = {}
        for replica_id in replica_ids:
            journal = None
            if journal_root is not None:
                directory = os.path.join(journal_root, replica_id)
                self.journal_dirs[replica_id] = directory
                journal = Journal(directory)
            replica = ClusterReplica(
                replica_id,
                index=InMemoryIndex(index_config),
                journal=journal,
            )
            self.replicas[replica_id] = replica
            self.transports[replica_id] = LocalReplicaTransport(
                replica, strict_wire=strict_wire
            )
        # transport_wrap(replica_id, transport) -> transport lets the
        # bench/chaos harnesses inject latency or faults on the wire
        # the ROUTER sees; kill()/revive() still drive the raw
        # transport underneath (shared killed-flag).
        routed = {
            replica_id: (
                transport
                if transport_wrap is None
                else transport_wrap(replica_id, transport)
            )
            for replica_id, transport in self.transports.items()
        }
        self.membership = ClusterMembership(routed)
        self.remote_index = RemoteIndex(
            self.membership,
            fanout_workers=fanout_workers,
            fanout_budget_s=fanout_budget_s,
            vv_ttl_s=vv_ttl_s,
            overlap_min_rpc_s=overlap_min_rpc_s,
        )
        self.heartbeat = HeartbeatMonitor(
            self.membership, interval_s=heartbeat_interval_s
        )
        self.followers: List[ReplicationFollower] = []
        if journal_root is not None:
            full_ring = self.membership.full_ring
            for replica_id, replica in self.replicas.items():
                for peer_id, peer_dir in self.journal_dirs.items():
                    if peer_id == replica_id:
                        continue
                    self.followers.append(
                        ReplicationFollower(
                            peer_id,
                            peer_dir,
                            replica.index,
                            record_filter=standby_record_filter(
                                full_ring, replica_id
                            ),
                            poll_interval_s=follower_poll_s,
                            # The peer's stream is authoritative for
                            # its primary slice only: its purges must
                            # not touch this replica's own slice.
                            purge_scope=(
                                lambda key, peer=peer_id: (
                                    full_ring.owner(key) == peer
                                )
                            ),
                        )
                    )

    # -- lifecycle ------------------------------------------------------

    def start(self, heartbeat: bool = True, followers: bool = True) -> None:
        if heartbeat:
            self.heartbeat.start()
        if followers:
            for follower in self.followers:
                follower.start()

    def close(self) -> None:
        self.heartbeat.close()
        self.remote_index.close()
        for follower in self.followers:
            follower.close()
        for transport in self.transports.values():
            transport.close()
        for replica in self.replicas.values():
            replica.close()

    # -- deterministic drivers (no sleep-polling in tests) --------------

    def sync_followers(self) -> int:
        """Drain every follower once; returns records read in total."""
        return sum(f.sync_once() for f in self.followers)

    def kill(self, replica_id: str, notice: bool = True) -> None:
        """Down a replica's transport; with ``notice`` the membership
        learns immediately (otherwise the next heartbeat or routed
        call discovers it)."""
        self.transports[replica_id].kill()
        if notice:
            self.membership.mark_dead(replica_id, "killed")

    def status(self) -> dict:
        """The /debug/cluster payload for an in-process cluster."""
        return {
            "membership": self.membership.status(),
            "replication": [f.status() for f in self.followers],
            "rpc": self.remote_index.rpc_stats(),
        }
