"""Replica-local ingestion: each replica subscribes to its pod slice.

Single-process ingestion funnels every pod's KVEvent stream through
ONE poller pool + ONE apply pool — fleet write throughput is capped by
one process however many replicas serve reads.  Replica-local
ingestion splits the *subscription* plane the same way PR 10 split the
index: the pod fleet is sliced over the alive ring (a deterministic
``pod -> replica`` rendezvous assignment, independent of the
block-key slicing reads use), and each replica runs its own poller
pool + kvevents pool over ONLY the pods it owns.  Aggregate ingest
throughput then scales with the replica count instead of one
process's ceiling (docs/event-plane.md has the topology diagram).

Correctness invariants:

* **Slicing is deterministic and process-independent** — FNV-64a of
  the pod id through the same rendezvous ring every replica computes
  (never Python's seeded ``hash()``), so N ingestors partition the
  fleet with no coordination: every pod has exactly one owner per
  ring version.
* **Applies route by KEY, not by slicer**: an ingestor digests its
  pods' events into whatever ``Index`` it was built over — in a
  cluster that is the ``RemoteIndex`` view, which routes each block
  key to the key's owner replica.  Pod-slicing the subscriptions and
  key-slicing the applies compose; routing truth is identical to the
  single-process pipeline (the cluster parity oracle stays
  bit-identical).
* **Ring version bumps re-slice subscriptions**: a
  :class:`~.membership.ClusterMembership` listener re-partitions the
  known fleet on every failover/rejoin.  Pods GAINED in a re-slice
  are resynced through the normal anti-entropy path (purge + inventory
  re-apply, ordered in the pod's shard lane, purge journaled before
  the re-applied claims — no purge-resurrection), because events
  published while nobody owned the pod are gone exactly like a seq
  gap's losses.
* **Gap/fairness/journal semantics are per replica**: each ingestor
  owns its channels' seq trackers, its pool's shard lanes and
  budgets, and its journal tap — the same contracts as the
  single-process plane, replicated N times over disjoint pod sets.
* **Event traces cross the replica boundary**: a sampled
  ``kvevents.message`` trace rides the pool worker into the
  ``RemoteIndex`` apply, whose per-owner RPCs record ``cluster.rpc``
  spans and stitch the replica-side ``replica.apply`` summaries off
  the wire — the ingest pipeline's write fan-out is attributable
  per owner exactly like the read path's
  (docs/observability.md "Fleet tracing").
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from llm_d_kv_cache_manager_tpu.cluster.membership import (
    ClusterMembership,
)
from llm_d_kv_cache_manager_tpu.cluster.ring import HashRing
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    fnv1a_64,
)
from llm_d_kv_cache_manager_tpu.kvevents.resync import ResyncManager
from llm_d_kv_cache_manager_tpu.kvevents.subscriber_manager import (
    SubscriberManager,
)
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("cluster.ingest")

# Subscription (de)registration happens under the ingestor lock so a
# concurrent re-slice and reconciler update cannot interleave into a
# doubly-owned or orphaned pod; the registry/attach locks below it are
# flag-flip cheap.
# kvlint: lock-order: ReplicaIngestor._lock < SubscriberManager._lock
lockorder.declare_order(
    "ReplicaIngestor._lock", "SubscriberManager._lock"
)


def pod_slice_key(pod_identifier: str) -> int:
    """Deterministic 64-bit slicing key for a pod id.

    FNV-64a over the identifier bytes — the same process-independent
    hash family the block chain uses, so every replica (and the
    bench's subprocess ingestors) computes the identical pod
    partition whatever its ``PYTHONHASHSEED``."""
    return fnv1a_64(pod_identifier.encode())


def pod_owner(ring: HashRing, pod_identifier: str) -> str:
    """The replica owning ``pod_identifier``'s event stream on ``ring``."""
    return ring.owner(pod_slice_key(pod_identifier))


def slice_pods(
    ring: HashRing, replica_id: str, pods
) -> List[str]:
    """The subset of ``pods`` that ``replica_id`` owns on ``ring``."""
    return [
        pod for pod in pods if pod_owner(ring, pod) == replica_id
    ]


class ReplicaIngestor:
    """One replica's slice-scoped subscription registry.

    Drop-in for the surface pod discovery drives
    (``ensure_subscriber`` / ``remove_subscriber``): the reconciler
    keeps announcing the WHOLE fleet, and the ingestor subscribes its
    :class:`~..kvevents.subscriber_manager.SubscriberManager` to only
    the owned slice, remembering the rest for re-slices.  Wire a
    ``membership`` to re-slice automatically on ring version bumps, or
    drive :meth:`apply_ring` manually (static replica-mode
    deployments).
    """

    def __init__(
        self,
        replica_id: str,
        manager: SubscriberManager,
        ring: Optional[HashRing] = None,
        membership: Optional[ClusterMembership] = None,
        resync: Optional[ResyncManager] = None,
    ) -> None:
        if not replica_id:
            raise ValueError("replica_id required")
        if ring is None and membership is None:
            raise ValueError("need a ring or a membership")
        self.replica_id = replica_id
        self._manager = manager
        self._resync = resync
        self._lock = lockorder.tracked(
            threading.Lock(), "ReplicaIngestor._lock"
        )
        self._ring = ring if ring is not None else membership.ring()
        # guarded-by: _lock — everything below.
        self._known: Dict[str, Tuple[str, Optional[str]]] = {}
        self._owned: set = set()
        self._takeovers = 0
        self._reslices = 0
        if membership is not None:
            membership.add_listener(self.apply_ring)
            # A statically-configured ring (replica-mode env) may
            # predate failovers that fired before this constructor
            # ran; adopt the live alive-ring if it is newer.  Ordered
            # AFTER add_listener so a bump in the gap cannot be lost:
            # apply_ring is version-guarded, newest wins either way.
            self.apply_ring(membership.ring())

    # -- discovery surface (reconciler-compatible) ----------------------

    def ensure_subscriber(
        self,
        pod_identifier: str,
        endpoint: str,
        topic_filter: Optional[str] = None,
    ) -> bool:
        """Record the pod and subscribe iff this replica owns it.
        Returns True when a new subscription was started."""
        with self._lock:
            self._known[pod_identifier] = (endpoint, topic_filter)
            if pod_owner(self._ring, pod_identifier) != self.replica_id:
                # Not ours (any more): make sure no stale channel
                # lingers from a previous slice.
                if pod_identifier in self._owned:
                    self._owned.discard(pod_identifier)
                    self._manager.remove_subscriber(pod_identifier)
                return False
            self._owned.add(pod_identifier)
            return self._manager.ensure_subscriber(
                pod_identifier, endpoint, topic_filter
            )

    def remove_subscriber(self, pod_identifier: str) -> bool:
        """Forget the pod (it left the fleet) and drop its channel."""
        with self._lock:
            self._known.pop(pod_identifier, None)
            self._owned.discard(pod_identifier)
            return self._manager.remove_subscriber(pod_identifier)

    # -- slicing --------------------------------------------------------

    def owns(self, pod_identifier: str) -> bool:
        with self._lock:
            return (
                pod_owner(self._ring, pod_identifier) == self.replica_id
            )

    def owned_pods(self) -> List[str]:
        with self._lock:
            return sorted(self._owned)

    def known_pods(self) -> List[str]:
        with self._lock:
            return sorted(self._known)

    def active_pods(self) -> List[str]:
        """The discovery surface's prune view: the KNOWN fleet, not
        just the owned slice — the reconciler prunes pods that left
        the cluster by diffing this against its list response, and a
        departed-but-unowned pod must still be forgotten here or a
        later re-slice would resubscribe a ghost."""
        return self.known_pods()

    def apply_ring(self, ring: HashRing) -> None:
        """Re-slice the known fleet onto ``ring`` (the membership
        listener).  Gained pods attach AND resync — events published
        while their previous owner was dying are lost exactly like a
        seq gap's, so their index claims are suspect until the
        anti-entropy purge + inventory re-apply lands."""
        gained: List[str] = []
        lost: List[str] = []
        with self._lock:
            if (
                ring.version == self._ring.version
                and ring.members == self._ring.members
            ):
                return  # identical ring — nothing to re-slice
            if ring.version < self._ring.version:
                # Membership notifies listeners OUTSIDE its lock, so
                # two near-simultaneous failovers can deliver their
                # rings out of order; adopting the older one would
                # leave this replica sliced on stale ownership (pods
                # unsubscribed everywhere, no takeover resync) until
                # the next bump.  Newest version wins, always.
                logger.info(
                    "replica %s ignoring stale ring v%d (have v%d)",
                    self.replica_id,
                    ring.version,
                    self._ring.version,
                )
                return
            self._ring = ring
            self._reslices += 1
            for pod, (endpoint, topic_filter) in self._known.items():
                owned_now = (
                    pod_owner(ring, pod) == self.replica_id
                )
                was_owned = pod in self._owned
                if owned_now and not was_owned:
                    self._owned.add(pod)
                    self._manager.ensure_subscriber(
                        pod, endpoint, topic_filter
                    )
                    gained.append(pod)
                elif not owned_now and was_owned:
                    self._owned.discard(pod)
                    self._manager.remove_subscriber(pod)
                    lost.append(pod)
            self._takeovers += len(gained)
        if gained or lost:
            logger.info(
                "replica %s re-sliced on ring v%d: +%d pods, -%d pods "
                "(%d owned)",
                self.replica_id,
                ring.version,
                len(gained),
                len(lost),
                len(self._owned),
            )
        if self._resync is not None:
            for pod in gained:
                self._resync.request_resync(pod)

    def status(self) -> dict:
        """The /healthz event_plane ingestion block."""
        with self._lock:
            return {
                "replica": self.replica_id,
                "ring_version": self._ring.version,
                "known_pods": len(self._known),
                "owned_pods": len(self._owned),
                "takeovers": self._takeovers,
                "reslices": self._reslices,
            }
