"""Cluster membership: static replica config + heartbeat health.

The membership owns the live :class:`~.ring.HashRing`: the configured
replica set is static (``CLUSTER_REPLICAS``), the ALIVE subset is
dynamic.  A replica leaves the ring when a heartbeat times out or the
router observes a transport failure mid-request (``mark_dead``), and
rejoins when a later heartbeat answers (``mark_alive``) — each change
produces a new ring version, so per-version ownership caches in the
router invalidate wholesale.

Failover is therefore just ring math: removing a member re-routes each
of its keys to its rendezvous runner-up (``ring.owners(key, 2)[1]`` on
the full ring), which is exactly the slice replication followers keep
warm (``replication.py``).  ``failover_count`` and the
``kvtpu_cluster_*`` metric families track the churn.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from llm_d_kv_cache_manager_tpu.cluster.replica import ReplicaUnavailable
from llm_d_kv_cache_manager_tpu.cluster.ring import HashRing
from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("cluster.membership")

# Leaf lock: membership state flips and ring rebuilds only — never a
# transport call or an index apply under it.
# kvlint: lock-order: ClusterMembership._lock ascending
lockorder.declare_ascending("ClusterMembership._lock")


class ClusterMembership:
    """Alive-set tracking + the versioned ring over it.

    ``transports`` maps replica id -> transport (an object with
    ``call(method, args)``); the full configured set never changes at
    runtime — only aliveness does.
    """

    def __init__(self, transports: Dict[str, object]) -> None:
        if not transports:
            raise ValueError("cluster needs at least one replica")
        self._transports = dict(transports)
        self._lock = lockorder.tracked(
            threading.Lock(), "ClusterMembership._lock"
        )
        self._alive = set(self._transports)  # guarded-by: _lock
        self._ring = HashRing(sorted(self._transports))  # guarded-by: _lock
        # Full ring over every CONFIGURED replica, version-frozen: the
        # standby assignment (owners(key, 2)[1]) must be stable across
        # failovers or followers would sync the wrong slice.
        self.full_ring = HashRing(sorted(self._transports))
        self._failover_count = 0  # guarded-by: _lock
        self._last_heartbeat: Dict[str, float] = {}  # guarded-by: _lock
        # replica -> (unix_ts, reason) of the last mark_dead — the
        # /debug/cluster "why did it leave the ring" context that
        # otherwise only existed as a log line.  guarded-by: _lock
        self._last_errors: Dict[str, Tuple[float, str]] = {}
        # Ring-change listeners (replica-local ingestion re-slices its
        # pod subscriptions on every version bump — cluster/ingest.py).
        # Invoked OUTSIDE the membership lock with the new ring.
        self._listeners: List[Callable[[HashRing], None]] = (
            []
        )  # guarded-by: _lock
        METRICS.cluster_ring_version.set(self._ring.version)
        METRICS.cluster_replicas_alive.set(len(self._alive))

    # -- reads ----------------------------------------------------------

    def ring(self) -> HashRing:
        """The current ring over alive replicas (immutable snapshot)."""
        with self._lock:
            return self._ring

    def transport(self, replica_id: str):
        return self._transports[replica_id]

    def members(self) -> List[str]:
        return sorted(self._transports)

    def alive(self) -> List[str]:
        with self._lock:
            return sorted(self._alive)

    def is_alive(self, replica_id: str) -> bool:
        with self._lock:
            return replica_id in self._alive

    def failover_count(self) -> int:
        with self._lock:
            return self._failover_count

    def status(self) -> dict:
        """The /debug/cluster membership block."""
        now = time.monotonic()
        wall = time.time()
        with self._lock:
            return {
                "members": sorted(self._transports),
                "alive": sorted(self._alive),
                "ring_version": self._ring.version,
                "failovers": self._failover_count,
                "heartbeat_age_s": {
                    replica: round(now - seen, 3)
                    for replica, seen in self._last_heartbeat.items()
                },
                "last_errors": {
                    replica: {
                        "age_s": round(wall - ts, 3),
                        "reason": reason,
                    }
                    for replica, (ts, reason) in self._last_errors.items()
                },
            }

    def add_listener(
        self, listener: Callable[[HashRing], None]
    ) -> None:
        """Register a ring-change listener, called with the NEW alive
        ring after every version bump (mark_dead/mark_alive), outside
        the membership lock.  Listener exceptions are swallowed (a
        broken consumer must not wedge failover)."""
        with self._lock:
            self._listeners.append(listener)

    def _notify_ring_change(self, ring: HashRing) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for listener in listeners:
            try:
                listener(ring)
            except Exception:  # noqa: BLE001 — consumer bugs stay theirs
                logger.exception("ring-change listener failed")

    # -- writes ---------------------------------------------------------

    def mark_dead(self, replica_id: str, reason: str = "") -> bool:
        """Remove a replica from the ring; True if it was alive.  The
        LAST alive replica is never removed — routing into an empty
        ring helps nobody; its calls keep failing loudly instead."""
        with self._lock:
            if replica_id not in self._alive:
                return False
            if len(self._alive) == 1:
                logger.error(
                    "replica %s unhealthy (%s) but it is the last one "
                    "alive; keeping it in the ring",
                    replica_id,
                    reason,
                )
                return False
            self._alive.discard(replica_id)
            self._ring = self._ring.without(replica_id)
            self._failover_count += 1
            self._last_errors[replica_id] = (
                time.time(),
                reason or "marked dead",
            )
            ring = self._ring
            version = ring.version
            alive = len(self._alive)
        METRICS.cluster_failovers.inc()
        METRICS.cluster_ring_version.set(version)
        METRICS.cluster_replicas_alive.set(alive)
        logger.warning(
            "replica %s removed from the ring (%s); ring v%d, %d alive",
            replica_id,
            reason or "marked dead",
            version,
            alive,
        )
        self._notify_ring_change(ring)
        return True

    def mark_alive(self, replica_id: str) -> bool:
        """(Re)admit a replica; True if it was dead.  A revived
        replica's slice routes back to it immediately — its index may
        be stale for the death window (heals via event flow / resync),
        which docs/replication.md calls out."""
        if replica_id not in self._transports:
            raise KeyError(f"unknown replica: {replica_id}")
        with self._lock:
            self._last_heartbeat[replica_id] = time.monotonic()
            if replica_id in self._alive:
                return False
            self._alive.add(replica_id)
            self._ring = self._ring.with_member(replica_id)
            ring = self._ring
            version = ring.version
            alive = len(self._alive)
        METRICS.cluster_ring_version.set(version)
        METRICS.cluster_replicas_alive.set(alive)
        logger.info(
            "replica %s rejoined the ring; ring v%d, %d alive",
            replica_id,
            version,
            alive,
        )
        self._notify_ring_change(ring)
        return True


class HeartbeatMonitor:
    """Background pinger: every ``interval_s`` each replica gets a
    ``ping``; ``misses`` consecutive failures mark it dead, one success
    marks it alive again.  Dead replicas keep being pinged — revival is
    how a restarted replica rejoins without operator action."""

    def __init__(
        self,
        membership: ClusterMembership,
        interval_s: float = 2.0,
        misses: int = 2,
    ) -> None:
        if interval_s <= 0:
            raise ValueError("heartbeat interval must be positive")
        self.membership = membership
        self.interval_s = interval_s
        self.misses = max(1, misses)
        self._miss_counts: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="kvtpu-cluster-heartbeat", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def beat_once(self) -> None:
        """One heartbeat round (the loop body; callable directly from
        tests and the smoke so they never sleep-poll)."""
        for replica_id in self.membership.members():
            transport = self.membership.transport(replica_id)
            try:
                transport.call("ping", [])
            except (ReplicaUnavailable, ConnectionError, OSError):
                count = self._miss_counts.get(replica_id, 0) + 1
                self._miss_counts[replica_id] = count
                if count >= self.misses:
                    self.membership.mark_dead(
                        replica_id,
                        f"heartbeat missed x{count}",
                    )
                continue
            self._miss_counts[replica_id] = 0
            self.membership.mark_alive(replica_id)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.beat_once()
            except Exception:  # noqa: BLE001 — the monitor must survive
                logger.exception("heartbeat round failed")
