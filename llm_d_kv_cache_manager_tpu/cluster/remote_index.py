"""RemoteIndex: the Index contract over a replica fleet.

An :class:`~..kvcache.kvblock.index.Index` implementation that routes
every operation to the rendezvous owner of its block key and fans
grouped operations out one RPC per owner — so the whole read/write
stack above it (fast-lane chunked ``lookup_chain``, the kvevents
pool's ``add_mappings`` + ``add_entries_batch`` batched apply, the
analytics ledger, the tiering feed, persistence dumps) works unchanged
against N replicas.

Routing discipline:

* **Reads** (``lookup`` / ``lookup_chain``): keys group per owner
  under ONE ring snapshot; one RPC per owner per call — the fast lane
  already chunks its chain, so a scoring request costs
  ``ceil(chain/chunk) x owners-touched`` round trips, not one per key.
* **Writes**: pod-entry admissions live at ``owner(request_key)``;
  engine->request mappings are published BOTH at
  ``owner(engine_key)`` (where ``get_request_key`` routes) and at
  ``owner(request_key)`` (whose local backend resolves them during
  ``evict``).  An eviction is two hops: resolve the request key at the
  engine-key owner, evict at the request-key owner.
* **Failover**: a transport failure marks the replica dead in the
  membership (ring version bump, failover counter) and the operation
  retries against the new owner — the rendezvous runner-up, whose
  replication follower has been keeping that slice warm
  (``replication.py``).  Application errors propagate; only transport
  failures fail over.

Not provided: ``version_vector`` / ``touch_chain`` — the indexer's
exact-prompt score memo detects their absence and disables itself (a
cross-process memo validator would need a coherence protocol the
advisory index doesn't warrant).  ``dump_entries`` concatenates every
alive replica's dump; standby slices may duplicate keys, which
``restore_entries`` absorbs idempotently.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

from llm_d_kv_cache_manager_tpu.cluster.membership import ClusterMembership
from llm_d_kv_cache_manager_tpu.cluster.replica import (
    ReplicaUnavailable,
    decode_entries,
    encode_entries,
)
from llm_d_kv_cache_manager_tpu.cluster.ring import HashRing
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import Index, PodEntry
from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("cluster.remote_index")


class RemoteIndex(Index):
    """See module docstring."""

    _OWNER_CACHE_MAX = 65536

    def __init__(self, membership: ClusterMembership) -> None:
        self.membership = membership
        # key -> (ring, owner), validated by ring IDENTITY on read: a
        # membership change produces a new immutable ring object, so a
        # stale entry can never validate (same single-key-dict-op
        # pattern as InMemoryIndex._group_cache; benign under the GIL).
        self._owner_cache: Dict[int, Tuple[HashRing, str]] = {}

    # -- routing plumbing ----------------------------------------------

    def _owner(self, ring: HashRing, key: int) -> str:
        cached = self._owner_cache.get(key)
        if cached is not None and cached[0] is ring:
            return cached[1]
        owner = ring.owner(key)
        cache = self._owner_cache
        if len(cache) >= self._OWNER_CACHE_MAX:
            cache.clear()
        cache[key] = (ring, owner)
        return owner

    def _max_attempts(self) -> int:
        return len(self.membership.members()) + 1

    def _call(self, replica_id: str, method: str, args: list):
        """One transport call with latency/error accounting; transport
        failures mark the replica dead (the failover trigger) before
        re-raising for the caller's re-route loop."""
        transport = self.membership.transport(replica_id)
        start = time.perf_counter()
        try:
            result = transport.call(method, args)
        except (ReplicaUnavailable, ConnectionError, OSError) as exc:
            METRICS.cluster_remote_errors.labels(op=method).inc()
            self.membership.mark_dead(
                replica_id, f"{method} failed: {exc}"
            )
            raise ReplicaUnavailable(str(exc)) from exc
        METRICS.cluster_remote_latency.labels(op=method).observe(
            time.perf_counter() - start
        )
        return result

    def _call_routed(self, key: int, method: str, args: list):
        """Single-key op with failover re-route."""
        last_exc: Optional[Exception] = None
        for _ in range(self._max_attempts()):
            ring = self.membership.ring()
            owner = self._owner(ring, key)
            try:
                return self._call(owner, method, args)
            except ReplicaUnavailable as exc:
                last_exc = exc
                if self.membership.ring() is ring:
                    # mark_dead refused (last replica alive): re-routing
                    # would loop on the same owner forever.
                    break
        assert last_exc is not None
        raise last_exc

    def _group_by_owner(
        self, ring: HashRing, keys: Sequence[int]
    ) -> Dict[str, List[int]]:
        groups: Dict[str, List[int]] = {}
        for key in keys:
            groups.setdefault(self._owner(ring, key), []).append(key)
        return groups

    def _fanout(self, pending: list, plan, on_result=None) -> None:
        """THE failover fan-out loop, shared by every grouped op.

        ``plan(ring, pending)`` returns ``[(owner, method, args,
        items)]`` — one RPC per owner, ``items`` being the subset of
        ``pending`` that re-enters the retry set if that owner's
        transport fails (the failed owner was marked dead by
        ``_call``, so the re-plan runs on the NEW ring and routes to
        the failover owner).  The loop stops when everything landed,
        when the ring identity did not change after a failure (the
        last-replica refusal — re-planning would loop on the same
        owner forever), or when attempts exhaust; undeliverable items
        re-raise the last transport error.  An item that rode more
        than one failed owner's call retries once (value-dedup for
        hashable items, identity for the rest).
        """
        last_exc: Optional[Exception] = None
        for _ in range(self._max_attempts()):
            if not pending:
                return
            ring = self.membership.ring()
            failed: list = []
            for owner, method, args, items in plan(ring, pending):
                try:
                    result = self._call(owner, method, args)
                except ReplicaUnavailable as exc:
                    last_exc = exc
                    failed.extend(items)
                    continue
                if on_result is not None:
                    on_result(result)
            if not failed:
                return
            if self.membership.ring() is ring:
                break
            seen = set()
            pending = []
            for item in failed:
                marker = (
                    item if isinstance(item, (int, tuple)) else id(item)
                )
                if marker in seen:
                    continue
                seen.add(marker)
                pending.append(item)
        if last_exc is not None:
            raise last_exc

    # -- read path ------------------------------------------------------

    def lookup(
        self,
        request_keys: Sequence[int],
        pod_identifier_set: Optional[Set[str]] = None,
    ) -> Dict[int, List[PodEntry]]:
        if not request_keys:
            raise ValueError("no request keys provided for lookup")
        pods_arg = sorted(pod_identifier_set) if pod_identifier_set else None
        result: Dict[int, List[PodEntry]] = {}

        def plan(ring, pending):
            return [
                (owner, "lookup", [keys, pods_arg], keys)
                for owner, keys in self._group_by_owner(
                    ring, pending
                ).items()
            ]

        def on_result(pairs):
            for key, raw_entries in pairs:
                result[key] = list(decode_entries(raw_entries))

        self._fanout(list(request_keys), plan, on_result)
        return result

    def lookup_chain(
        self, request_keys: Sequence[int]
    ) -> List[Sequence[PodEntry]]:
        """Aligned per-key pod snapshots (the fast-lane shape): group
        the chunk's keys per owner, ONE ``lookup`` RPC per owner, then
        truncate at the first key with no resident pods.  A replica's
        own present-but-empty early stop reads as "no pods" for its
        later keys, which can only move the truncation point EARLIER
        than or equal to the true break — never report residency past
        a dead chain (scores stay parity-exact; property-pinned)."""
        if not request_keys:
            return []
        found = self.lookup(request_keys, None)
        out: List[Sequence[PodEntry]] = []
        for key in request_keys:
            pods = found.get(key)
            if not pods:
                break
            out.append(pods)
        return out

    # -- write path -----------------------------------------------------

    def add(
        self,
        engine_keys: Sequence[int],
        request_keys: Sequence[int],
        entries: Sequence[PodEntry],
    ) -> None:
        if not engine_keys or not request_keys or not entries:
            raise ValueError("no keys or entries provided for add")
        if len(engine_keys) != len(request_keys):
            raise ValueError("engine/request key length mismatch")
        wire_entries = encode_entries(entries)

        def plan(ring, pending):
            # Aligned pairs grouped by request-key owner.
            groups: Dict[str, List[Tuple[int, int]]] = {}
            for pair in pending:
                groups.setdefault(
                    self._owner(ring, pair[1]), []
                ).append(pair)
            return [
                (
                    owner,
                    "add",
                    [
                        [ek for ek, _ in pairs],
                        [rk for _, rk in pairs],
                        wire_entries,
                    ],
                    pairs,
                )
                for owner, pairs in groups.items()
            ]

        self._fanout(list(zip(engine_keys, request_keys)), plan)
        # Mappings published for EVERY pair, not just cross-owner ones:
        # besides serving get_request_key at the engine-key owner, the
        # add_mappings RPC journals a mappings-only record whose
        # standby filter keys on EITHER side — a same-owner pair's
        # engine-key standby can differ from its request-key standby,
        # and without the record that standby would miss the mapping
        # and classify post-failover evictions as "already gone".
        # Idempotent where it duplicates the full add's mapping.
        self.add_mappings(engine_keys, request_keys)

    def add_mappings(
        self, engine_keys: Sequence[int], request_keys: Sequence[int]
    ) -> None:
        """Publish engine->request mappings at BOTH owners: the
        engine-key owner serves ``get_request_key``; the request-key
        owner's local backend resolves the mapping during ``evict``.
        A pair that failed on one of its two owners re-routes
        wholesale (idempotent on the surviving owner)."""

        def plan(ring, pending):
            groups: Dict[str, List[Tuple[int, int]]] = {}
            for pair in pending:
                for owner in {
                    self._owner(ring, pair[0]),
                    self._owner(ring, pair[1]),
                }:
                    groups.setdefault(owner, []).append(pair)
            return [
                (
                    owner,
                    "add_mappings",
                    [
                        [ek for ek, _ in pairs],
                        [rk for _, rk in pairs],
                    ],
                    pairs,
                )
                for owner, pairs in groups.items()
            ]

        self._fanout(list(zip(engine_keys, request_keys)), plan)

    def add_entries_batch(
        self,
        items: Sequence[Tuple[Sequence[int], Sequence[PodEntry]]],
    ) -> None:
        """The kvevents batched-apply surface: request keys group per
        owner across the whole batch — one RPC per owner per flush.
        An item whose keys straddled a failed owner retries whole on
        the re-planned ring; its slices that landed re-apply
        idempotently."""
        pending = [
            [list(request_keys), encode_entries(entries)]
            for request_keys, entries in items
            if request_keys
        ]

        def plan(ring, pending):
            # owner -> ([per-owner wire items], [source items]).
            groups: Dict[str, Tuple[List[list], List[list]]] = {}
            for item in pending:
                request_keys, wire_entries = item
                by_owner: Dict[str, List[int]] = {}
                for rk in request_keys:
                    by_owner.setdefault(
                        self._owner(ring, rk), []
                    ).append(rk)
                for owner, rks in by_owner.items():
                    bucket = groups.setdefault(owner, ([], []))
                    bucket[0].append([rks, wire_entries])
                    bucket[1].append(item)
            return [
                (owner, "add_entries_batch", [owner_items], sources)
                for owner, (owner_items, sources) in groups.items()
            ]

        self._fanout(pending, plan)

    def evict(self, engine_key: int, entries: Sequence[PodEntry]) -> None:
        """Two hops: resolve the request key at the engine-key owner,
        evict at the request-key owner.  When the eviction empties the
        key (the owner pruned its mapping), the mapping stub at the
        engine-key owner is evicted too, so ``get_request_key`` raises
        exactly like a local backend's would."""
        if not entries:
            raise ValueError("no entries provided for eviction")
        try:
            request_key = self.get_request_key(engine_key)
        except KeyError:
            return  # mapping already gone — same no-op as local backends
        wire_entries = encode_entries(entries)
        pruned = self._call_routed(
            request_key, "evict", [engine_key, wire_entries]
        )
        if pruned:
            ring = self.membership.ring()
            ek_owner = self._owner(ring, engine_key)
            if ek_owner != self._owner(ring, request_key):
                try:
                    self._call(
                        ek_owner, "evict", [engine_key, wire_entries]
                    )
                except ReplicaUnavailable:
                    # Stub cleanup is best-effort: the dead replica's
                    # stale mapping lingers exactly like a local LRU
                    # leftover would.
                    pass

    def get_request_key(self, engine_key: int) -> int:
        found, value = self._call_routed(
            engine_key, "get_request_key", [engine_key]
        )
        if not found:
            raise KeyError(f"engine key not found: {engine_key:#x}")
        return value

    # -- persistence / admin --------------------------------------------

    def dump_entries(
        self,
    ) -> Tuple[List[Tuple[int, List[PodEntry]]], List[Tuple[int, int]]]:
        """Concatenated dumps of every ALIVE replica.  Standby slices
        (replication followers warm peers' keys) may duplicate request
        keys across replicas; restore absorbs duplicates idempotently.
        An unreachable replica is skipped (and marked dead) — the dump
        is a best-effort snapshot, the journal covers the gap."""
        block_entries: List[Tuple[int, List[PodEntry]]] = []
        engine_map: List[Tuple[int, int]] = []
        for replica_id in self.membership.alive():
            try:
                raw_blocks, raw_map = self._call(
                    replica_id, "dump_entries", []
                )
            except ReplicaUnavailable:
                continue
            for key, raw_entries in raw_blocks:
                block_entries.append(
                    (key, list(decode_entries(raw_entries)))
                )
            engine_map.extend((ek, rk) for ek, rk in raw_map)
        return block_entries, engine_map

    def restore_entries(
        self,
        block_entries: Sequence[Tuple[int, Sequence[PodEntry]]],
        engine_map: Sequence[Tuple[int, int]],
    ) -> int:
        ring = self.membership.ring()
        blocks_by_owner: Dict[str, List[list]] = {}
        for request_key, entries in block_entries:
            blocks_by_owner.setdefault(
                self._owner(ring, request_key), []
            ).append([request_key, encode_entries(entries)])
        maps_by_owner: Dict[str, List[list]] = {}
        for ek, rk in engine_map:
            for owner in {self._owner(ring, ek), self._owner(ring, rk)}:
                maps_by_owner.setdefault(owner, []).append([ek, rk])
        restored = 0
        for owner in sorted(set(blocks_by_owner) | set(maps_by_owner)):
            try:
                restored += self._call(
                    owner,
                    "restore_entries",
                    [
                        blocks_by_owner.get(owner, []),
                        maps_by_owner.get(owner, []),
                    ],
                )
            except ReplicaUnavailable:
                logger.warning(
                    "restore skipped unreachable replica %s", owner
                )
        return restored

    def purge_pod(self, pod_identifier: str) -> int:
        removed = 0
        for replica_id in self.membership.alive():
            try:
                removed += self._call(
                    replica_id, "purge_pod", [pod_identifier]
                )
            except ReplicaUnavailable:
                continue  # dead replica holds no servable entries now
        return removed
