"""RemoteIndex: the Index contract over a replica fleet.

An :class:`~..kvcache.kvblock.index.Index` implementation that routes
every operation to the rendezvous owner of its block key and fans
grouped operations out one RPC per owner — so the whole read/write
stack above it (fast-lane chunked ``lookup_chain``, the kvevents
pool's ``add_mappings`` + ``add_entries_batch`` batched apply, the
analytics ledger, the tiering feed, persistence dumps) works unchanged
against N replicas.

Routing discipline:

* **Reads** (``lookup`` / ``lookup_chain``): keys group per owner
  under ONE ring snapshot; one RPC per owner per call — the fast lane
  already chunks its chain, so a scoring request costs
  ``ceil(chain/chunk) x owners-touched`` round trips, not one per key.
* **Writes**: pod-entry admissions live at ``owner(request_key)``;
  engine->request mappings are published BOTH at
  ``owner(engine_key)`` (where ``get_request_key`` routes) and at
  ``owner(request_key)`` (whose local backend resolves them during
  ``evict``).  An eviction is two hops: resolve the request key at the
  engine-key owner, evict at the request-key owner.
* **Failover**: a transport failure marks the replica dead in the
  membership (ring version bump, failover counter) and the operation
  retries against the new owner — the rendezvous runner-up, whose
  replication follower has been keeping that slice warm
  (``replication.py``).  Application errors propagate; only transport
  failures fail over.
* **Observability** (docs/observability.md "Fleet tracing"): when the
  calling context carries a sampled trace, every owner RPC records a
  ``cluster.rpc`` span (replica + method attrs) and forwards the
  trace context on the wire; span summaries piggybacked on the reply
  are stitched back in as children — ONE trace covers the whole
  fan-out, including a failed RPC and its re-routed retry.  Always-on
  fan-out attribution (``rpc_stats()``, the ``/debug/cluster`` rpc
  panel) tallies per-replica latency/error/retry counters plus the
  sequential critical-path breakdown (owner RPCs per lookup) that
  baselines the read-path pipelining work (ROADMAP item 3).

Not provided: ``version_vector`` / ``touch_chain`` — the indexer's
exact-prompt score memo detects their absence and disables itself (a
cross-process memo validator would need a coherence protocol the
advisory index doesn't warrant).  ``dump_entries`` concatenates every
alive replica's dump; standby slices may duplicate keys, which
``restore_entries`` absorbs idempotently.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

from llm_d_kv_cache_manager_tpu.cluster.membership import ClusterMembership
from llm_d_kv_cache_manager_tpu.cluster.replica import (
    ReplicaUnavailable,
    decode_entries,
    encode_entries,
    resolve_trace_piggyback_env,
)
from llm_d_kv_cache_manager_tpu.cluster.ring import HashRing
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import Index, PodEntry
from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS, safe_label
from llm_d_kv_cache_manager_tpu.obs.trace import (
    Span,
    current_trace,
    shield_trace,
)
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("cluster.remote_index")

# Leaf lock: per-replica RPC tallies only — never a transport call or
# a membership flip under it.
# kvlint: lock-order: RemoteIndex._stats_lock ascending
lockorder.declare_ascending("RemoteIndex._stats_lock")


class RemoteIndex(Index):
    """See module docstring."""

    _OWNER_CACHE_MAX = 65536

    # Stitched cluster.rpc spans nest under the stage whose time they
    # attribute: read fan-out inside the fast lane's "index_lookup",
    # everything else inside the event plane's "kvevents.apply".
    _RPC_TRACE_PARENT = {
        "lookup": "index_lookup",
        "lookup_chain": "index_lookup",
    }

    def __init__(
        self,
        membership: ClusterMembership,
        trace_rpcs: Optional[bool] = None,
        rpc_accounting: bool = True,
    ) -> None:
        self.membership = membership
        # Trace-context forwarding + span stitching on traced calls
        # (None -> CLUSTER_TRACE_PIGGYBACK, default on; untraced calls
        # never pay for it either way).
        self.trace_rpcs = (
            resolve_trace_piggyback_env()
            if trace_rpcs is None
            else trace_rpcs
        )
        # Per-replica tallies + the kvtpu_cluster_rpc_* families; the
        # bench's trace A/B cell flips this off to price the whole
        # observability plane on the untraced path.
        self.rpc_accounting = rpc_accounting
        self._stats_lock = lockorder.tracked(
            threading.Lock(), "RemoteIndex._stats_lock"
        )
        self._rpc_tallies: Dict[str, dict] = {}  # guarded-by: _stats_lock
        self._reroutes = 0  # guarded-by: _stats_lock
        # Outstanding transport calls right now — the timeline's
        # cluster_rpc_in_flight series (obs/timeline.py).  Locked,
        # unlike the shard version counters: those only ever advance
        # (a lost bump merely lags), but a PAIRED inc/dec gauge
        # drifts permanently on one lost store.  Two leaf-lock ops
        # per RPC are noise next to the transport call itself.
        self._in_flight = 0  # guarded-by: _stats_lock
        self._lookup_calls = 0  # guarded-by: _stats_lock
        self._lookup_owner_rpcs = 0  # guarded-by: _stats_lock
        self._lookup_owner_max = 0  # guarded-by: _stats_lock
        self._lookup_rpc_s = 0.0  # guarded-by: _stats_lock
        # method -> labeled histogram child (labels() does a lock +
        # dict lookup per call; the method set is tiny and fixed).
        self._latency_children: Dict[str, object] = {}
        # key -> (ring, owner), validated by ring IDENTITY on read: a
        # membership change produces a new immutable ring object, so a
        # stale entry can never validate (same single-key-dict-op
        # pattern as InMemoryIndex._group_cache; benign under the GIL).
        self._owner_cache: Dict[int, Tuple[HashRing, str]] = {}

    # -- routing plumbing ----------------------------------------------

    def _owner(self, ring: HashRing, key: int) -> str:
        cached = self._owner_cache.get(key)
        if cached is not None and cached[0] is ring:
            return cached[1]
        owner = ring.owner(key)
        cache = self._owner_cache
        if len(cache) >= self._OWNER_CACHE_MAX:
            cache.clear()
        cache[key] = (ring, owner)
        return owner

    def _max_attempts(self) -> int:
        return len(self.membership.members()) + 1

    def _rpc_latency(self, method: str):
        child = self._latency_children.get(method)
        if child is None:
            child = METRICS.cluster_rpc_latency.labels(method=method)
            # gil-atomic: idempotent memo; racing put re-derives the same value
            self._latency_children[method] = child
        return child

    def _tally(
        self,
        replica_id: str,
        method: str,
        elapsed: float,
        error: Optional[Tuple[str, str]] = None,
    ) -> None:
        """Per-replica fan-out attribution (the /debug/cluster rpc
        panel): call/error counts, latency totals, per-method split,
        and the last transport error's context."""
        with self._stats_lock:
            entry = self._rpc_tallies.get(replica_id)
            if entry is None:
                entry = self._rpc_tallies[replica_id] = {
                    "calls": 0,
                    "errors": 0,
                    "total_s": 0.0,
                    "max_s": 0.0,
                    "methods": {},
                    "last_error": None,
                }
            entry["calls"] += 1
            entry["total_s"] += elapsed
            if elapsed > entry["max_s"]:
                entry["max_s"] = elapsed
            methods = entry["methods"]
            methods[method] = methods.get(method, 0) + 1
            if method in self._RPC_TRACE_PARENT:
                self._lookup_rpc_s += elapsed
            if error is not None:
                entry["errors"] += 1
                entry["last_error"] = {
                    "kind": error[0],
                    "method": method,
                    "detail": error[1][:200],
                    "unix": time.time(),
                }

    def _stitch(
        self, trace, wire_spans: list, anchor: float, replica_id: str
    ) -> None:
        """Re-anchor piggybacked server-side span records inside the
        RPC window (their clocks are replica-relative).  Malformed
        records never fail the call — the piggyback is advisory."""
        try:
            for record in wire_spans:
                name, parent, start_us, dur_us, status, attrs = record
                span = Span(
                    str(name),
                    str(parent) or "cluster.rpc",
                    anchor + float(start_us) / 1e6,
                )
                span.end = span.start + max(0.0, float(dur_us)) / 1e6
                span.status = str(status)
                for pair in attrs:
                    span.attrs[str(pair[0])] = pair[1]
                span.attrs.setdefault("replica", replica_id)
                trace.append_span(span)
        except Exception:  # noqa: BLE001 — advisory, never fails the RPC
            logger.debug(
                "garbled span piggyback from replica %s",
                replica_id,
                exc_info=True,
            )

    def _call_traced(
        self, trace, transport, replica_id: str, method: str,
        args: list, start: float,
    ):
        """Traced transport call: a cluster.rpc span per owner RPC,
        trace context on the wire, reply spans stitched back in."""
        with trace.span(
            "cluster.rpc",
            parent=self._RPC_TRACE_PARENT.get(method, "kvevents.apply"),
        ) as rpc:
            rpc.set_attr("replica", replica_id)
            rpc.set_attr("method", method)
            call_ex = getattr(transport, "call_ex", None)
            if call_ex is None:
                # Foreign transport without the traced surface: the
                # RPC span still attributes the hop.
                return transport.call(method, args)
            result, spans = call_ex(
                method, args, traceparent=trace.traceparent()
            )
            if spans:
                rpc.set_attr("server_spans", len(spans))
                self._stitch(trace, spans, start, replica_id)
            return result

    def _call(self, replica_id: str, method: str, args: list):
        """One transport call with latency/error accounting; transport
        failures mark the replica dead (the failover trigger) before
        re-raising for the caller's re-route loop."""
        transport = self.membership.transport(replica_id)
        ambient = current_trace()
        trace = ambient if self.trace_rpcs else None
        start = time.perf_counter()
        with self._stats_lock:
            self._in_flight += 1
        try:
            try:
                if trace is None:
                    if ambient is not None:
                        # trace_rpcs off with a live trace: shield the
                        # in-process transport so the replica's direct
                        # context-var record cannot leak orphan
                        # replica.* spans under a cluster.rpc parent
                        # that was never opened — the knob disables
                        # the WHOLE plane.
                        with shield_trace():
                            result = transport.call(method, args)
                    else:
                        result = transport.call(method, args)
                else:
                    result = self._call_traced(
                        trace, transport, replica_id, method, args,
                        start,
                    )
            except (ReplicaUnavailable, ConnectionError, OSError) as exc:
                elapsed = time.perf_counter() - start
                kind = getattr(exc, "kind", None) or "io"
                METRICS.cluster_rpc_errors.labels(
                    replica=safe_label(replica_id),
                    kind=safe_label(kind),
                ).inc()
                if self.rpc_accounting:
                    self._tally(
                        replica_id, method, elapsed,
                        error=(kind, str(exc)),
                    )
                self.membership.mark_dead(
                    replica_id, f"{method} failed: {exc}"
                )
                raise ReplicaUnavailable(str(exc), kind=kind) from exc
        finally:
            with self._stats_lock:
                self._in_flight -= 1
        elapsed = time.perf_counter() - start
        self._rpc_latency(method).observe(elapsed)
        if self.rpc_accounting:
            self._tally(replica_id, method, elapsed)
        return result

    def in_flight(self) -> int:
        """Transport calls currently outstanding (gauge; see
        obs/timeline.py's cluster_rpc_in_flight series)."""
        with self._stats_lock:
            return self._in_flight

    def _call_routed(self, key: int, method: str, args: list):
        """Single-key op with failover re-route."""
        last_exc: Optional[Exception] = None
        for _ in range(self._max_attempts()):
            ring = self.membership.ring()
            owner = self._owner(ring, key)
            try:
                return self._call(owner, method, args)
            except ReplicaUnavailable as exc:
                last_exc = exc
                if self.membership.ring() is ring:
                    # mark_dead refused (last replica alive): re-routing
                    # would loop on the same owner forever.
                    break
                with self._stats_lock:
                    self._reroutes += 1
        assert last_exc is not None
        raise last_exc

    def _group_by_owner(
        self, ring: HashRing, keys: Sequence[int]
    ) -> Dict[str, List[int]]:
        groups: Dict[str, List[int]] = {}
        for key in keys:
            groups.setdefault(self._owner(ring, key), []).append(key)
        return groups

    def _fanout(self, pending: list, plan, on_result=None) -> None:
        """THE failover fan-out loop, shared by every grouped op.

        ``plan(ring, pending)`` returns ``[(owner, method, args,
        items)]`` — one RPC per owner, ``items`` being the subset of
        ``pending`` that re-enters the retry set if that owner's
        transport fails (the failed owner was marked dead by
        ``_call``, so the re-plan runs on the NEW ring and routes to
        the failover owner).  The loop stops when everything landed,
        when the ring identity did not change after a failure (the
        last-replica refusal — re-planning would loop on the same
        owner forever), or when attempts exhaust; undeliverable items
        re-raise the last transport error.  An item that rode more
        than one failed owner's call retries once (value-dedup for
        hashable items, identity for the rest).
        """
        last_exc: Optional[Exception] = None
        for _ in range(self._max_attempts()):
            if not pending:
                return
            ring = self.membership.ring()
            failed: list = []
            for owner, method, args, items in plan(ring, pending):
                try:
                    result = self._call(owner, method, args)
                except ReplicaUnavailable as exc:
                    last_exc = exc
                    failed.extend(items)
                    continue
                if on_result is not None:
                    on_result(result)
            if not failed:
                return
            if self.membership.ring() is ring:
                break
            with self._stats_lock:
                self._reroutes += len(failed)
            seen = set()
            pending = []
            for item in failed:
                marker = (
                    item if isinstance(item, (int, tuple)) else id(item)
                )
                if marker in seen:
                    continue
                seen.add(marker)
                pending.append(item)
        if last_exc is not None:
            raise last_exc

    # -- read path ------------------------------------------------------

    def lookup(
        self,
        request_keys: Sequence[int],
        pod_identifier_set: Optional[Set[str]] = None,
    ) -> Dict[int, List[PodEntry]]:
        if not request_keys:
            raise ValueError("no request keys provided for lookup")
        pods_arg = sorted(pod_identifier_set) if pod_identifier_set else None
        result: Dict[int, List[PodEntry]] = {}
        rounds: List[int] = []

        def plan(ring, pending):
            plans = [
                (owner, "lookup", [keys, pods_arg], keys)
                for owner, keys in self._group_by_owner(
                    ring, pending
                ).items()
            ]
            rounds.append(len(plans))
            return plans

        def on_result(pairs):
            for key, raw_entries in pairs:
                result[key] = list(decode_entries(raw_entries))

        self._fanout(list(request_keys), plan, on_result)
        if self.rpc_accounting:
            # Sequential critical path: the fan-out loop issues one RPC
            # per owner per round, back to back — first-round width is
            # the per-chunk serial depth item 3's pipelining attacks.
            with self._stats_lock:
                self._lookup_calls += 1
                self._lookup_owner_rpcs += sum(rounds)
                if rounds and rounds[0] > self._lookup_owner_max:
                    self._lookup_owner_max = rounds[0]
        return result

    def rpc_stats(self) -> dict:
        """The /debug/cluster per-replica rpc panel: fan-out
        attribution tallies plus the sequential-owner critical-path
        breakdown (the read-path pipelining baseline)."""
        with self._stats_lock:
            replicas: Dict[str, dict] = {}
            for replica_id, entry in sorted(self._rpc_tallies.items()):
                calls = entry["calls"]
                view = {
                    "calls": calls,
                    "errors": entry["errors"],
                    "total_ms": round(entry["total_s"] * 1e3, 3),
                    "avg_ms": (
                        round(entry["total_s"] / calls * 1e3, 3)
                        if calls
                        else 0.0
                    ),
                    "max_ms": round(entry["max_s"] * 1e3, 3),
                    "methods": dict(entry["methods"]),
                }
                if entry["last_error"] is not None:
                    view["last_error"] = dict(entry["last_error"])
                replicas[replica_id] = view
            lookups = self._lookup_calls
            return {
                "replicas": replicas,
                "in_flight": self._in_flight,
                "reroutes": self._reroutes,
                "critical_path": {
                    "lookup_calls": lookups,
                    "owner_rpcs": self._lookup_owner_rpcs,
                    "avg_owners_per_lookup": (
                        round(self._lookup_owner_rpcs / lookups, 3)
                        if lookups
                        else 0.0
                    ),
                    "max_owners_per_lookup": self._lookup_owner_max,
                    "sequential_rpc_s": round(self._lookup_rpc_s, 6),
                },
            }

    def lookup_chain(
        self, request_keys: Sequence[int]
    ) -> List[Sequence[PodEntry]]:
        """Aligned per-key pod snapshots (the fast-lane shape): group
        the chunk's keys per owner, ONE ``lookup`` RPC per owner, then
        truncate at the first key with no resident pods.  A replica's
        own present-but-empty early stop reads as "no pods" for its
        later keys, which can only move the truncation point EARLIER
        than or equal to the true break — never report residency past
        a dead chain (scores stay parity-exact; property-pinned)."""
        if not request_keys:
            return []
        found = self.lookup(request_keys, None)
        out: List[Sequence[PodEntry]] = []
        for key in request_keys:
            pods = found.get(key)
            if not pods:
                break
            out.append(pods)
        return out

    # -- write path -----------------------------------------------------

    def add(
        self,
        engine_keys: Sequence[int],
        request_keys: Sequence[int],
        entries: Sequence[PodEntry],
    ) -> None:
        if not engine_keys or not request_keys or not entries:
            raise ValueError("no keys or entries provided for add")
        if len(engine_keys) != len(request_keys):
            raise ValueError("engine/request key length mismatch")
        wire_entries = encode_entries(entries)

        def plan(ring, pending):
            # Aligned pairs grouped by request-key owner.
            groups: Dict[str, List[Tuple[int, int]]] = {}
            for pair in pending:
                groups.setdefault(
                    self._owner(ring, pair[1]), []
                ).append(pair)
            return [
                (
                    owner,
                    "add",
                    [
                        [ek for ek, _ in pairs],
                        [rk for _, rk in pairs],
                        wire_entries,
                    ],
                    pairs,
                )
                for owner, pairs in groups.items()
            ]

        self._fanout(list(zip(engine_keys, request_keys)), plan)
        # Mappings published for EVERY pair, not just cross-owner ones:
        # besides serving get_request_key at the engine-key owner, the
        # add_mappings RPC journals a mappings-only record whose
        # standby filter keys on EITHER side — a same-owner pair's
        # engine-key standby can differ from its request-key standby,
        # and without the record that standby would miss the mapping
        # and classify post-failover evictions as "already gone".
        # Idempotent where it duplicates the full add's mapping.
        self.add_mappings(engine_keys, request_keys)

    def add_mappings(
        self, engine_keys: Sequence[int], request_keys: Sequence[int]
    ) -> None:
        """Publish engine->request mappings at BOTH owners: the
        engine-key owner serves ``get_request_key``; the request-key
        owner's local backend resolves the mapping during ``evict``.
        A pair that failed on one of its two owners re-routes
        wholesale (idempotent on the surviving owner)."""

        def plan(ring, pending):
            groups: Dict[str, List[Tuple[int, int]]] = {}
            for pair in pending:
                for owner in {
                    self._owner(ring, pair[0]),
                    self._owner(ring, pair[1]),
                }:
                    groups.setdefault(owner, []).append(pair)
            return [
                (
                    owner,
                    "add_mappings",
                    [
                        [ek for ek, _ in pairs],
                        [rk for _, rk in pairs],
                    ],
                    pairs,
                )
                for owner, pairs in groups.items()
            ]

        self._fanout(list(zip(engine_keys, request_keys)), plan)

    def add_entries_batch(
        self,
        items: Sequence[Tuple[Sequence[int], Sequence[PodEntry]]],
    ) -> None:
        """The kvevents batched-apply surface: request keys group per
        owner across the whole batch — one RPC per owner per flush.
        An item whose keys straddled a failed owner retries whole on
        the re-planned ring; its slices that landed re-apply
        idempotently."""
        pending = [
            [list(request_keys), encode_entries(entries)]
            for request_keys, entries in items
            if request_keys
        ]

        def plan(ring, pending):
            # owner -> ([per-owner wire items], [source items]).
            groups: Dict[str, Tuple[List[list], List[list]]] = {}
            for item in pending:
                request_keys, wire_entries = item
                by_owner: Dict[str, List[int]] = {}
                for rk in request_keys:
                    by_owner.setdefault(
                        self._owner(ring, rk), []
                    ).append(rk)
                for owner, rks in by_owner.items():
                    bucket = groups.setdefault(owner, ([], []))
                    bucket[0].append([rks, wire_entries])
                    bucket[1].append(item)
            return [
                (owner, "add_entries_batch", [owner_items], sources)
                for owner, (owner_items, sources) in groups.items()
            ]

        self._fanout(pending, plan)

    def evict(self, engine_key: int, entries: Sequence[PodEntry]) -> None:
        """Two hops: resolve the request key at the engine-key owner,
        evict at the request-key owner.  When the eviction empties the
        key (the owner pruned its mapping), the mapping stub at the
        engine-key owner is evicted too, so ``get_request_key`` raises
        exactly like a local backend's would."""
        if not entries:
            raise ValueError("no entries provided for eviction")
        try:
            request_key = self.get_request_key(engine_key)
        except KeyError:
            return  # mapping already gone — same no-op as local backends
        wire_entries = encode_entries(entries)
        pruned = self._call_routed(
            request_key, "evict", [engine_key, wire_entries]
        )
        if pruned:
            ring = self.membership.ring()
            ek_owner = self._owner(ring, engine_key)
            if ek_owner != self._owner(ring, request_key):
                try:
                    self._call(
                        ek_owner, "evict", [engine_key, wire_entries]
                    )
                except ReplicaUnavailable:
                    # Stub cleanup is best-effort: the dead replica's
                    # stale mapping lingers exactly like a local LRU
                    # leftover would.
                    pass

    def get_request_key(self, engine_key: int) -> int:
        found, value = self._call_routed(
            engine_key, "get_request_key", [engine_key]
        )
        if not found:
            raise KeyError(f"engine key not found: {engine_key:#x}")
        return value

    # -- persistence / admin --------------------------------------------

    def dump_entries(
        self,
    ) -> Tuple[List[Tuple[int, List[PodEntry]]], List[Tuple[int, int]]]:
        """Concatenated dumps of every ALIVE replica.  Standby slices
        (replication followers warm peers' keys) may duplicate request
        keys across replicas; restore absorbs duplicates idempotently.
        An unreachable replica is skipped (and marked dead) — the dump
        is a best-effort snapshot, the journal covers the gap."""
        block_entries: List[Tuple[int, List[PodEntry]]] = []
        engine_map: List[Tuple[int, int]] = []
        for replica_id in self.membership.alive():
            try:
                raw_blocks, raw_map = self._call(
                    replica_id, "dump_entries", []
                )
            except ReplicaUnavailable:
                continue
            for key, raw_entries in raw_blocks:
                block_entries.append(
                    (key, list(decode_entries(raw_entries)))
                )
            engine_map.extend((ek, rk) for ek, rk in raw_map)
        return block_entries, engine_map

    def restore_entries(
        self,
        block_entries: Sequence[Tuple[int, Sequence[PodEntry]]],
        engine_map: Sequence[Tuple[int, int]],
    ) -> int:
        ring = self.membership.ring()
        blocks_by_owner: Dict[str, List[list]] = {}
        for request_key, entries in block_entries:
            blocks_by_owner.setdefault(
                self._owner(ring, request_key), []
            ).append([request_key, encode_entries(entries)])
        maps_by_owner: Dict[str, List[list]] = {}
        for ek, rk in engine_map:
            for owner in {self._owner(ring, ek), self._owner(ring, rk)}:
                maps_by_owner.setdefault(owner, []).append([ek, rk])
        restored = 0
        for owner in sorted(set(blocks_by_owner) | set(maps_by_owner)):
            try:
                restored += self._call(
                    owner,
                    "restore_entries",
                    [
                        blocks_by_owner.get(owner, []),
                        maps_by_owner.get(owner, []),
                    ],
                )
            except ReplicaUnavailable:
                logger.warning(
                    "restore skipped unreachable replica %s", owner
                )
        return restored

    def purge_pod(self, pod_identifier: str) -> int:
        removed = 0
        for replica_id in self.membership.alive():
            try:
                removed += self._call(
                    replica_id, "purge_pod", [pod_identifier]
                )
            except ReplicaUnavailable:
                continue  # dead replica holds no servable entries now
        return removed
