"""RemoteIndex: the Index contract over a replica fleet.

An :class:`~..kvcache.kvblock.index.Index` implementation that routes
every operation to the rendezvous owner of its block key and fans
grouped operations out one RPC per owner — so the whole read/write
stack above it (fast-lane chunked ``lookup_chain``, the kvevents
pool's ``add_mappings`` + ``add_entries_batch`` batched apply, the
analytics ledger, the tiering feed, persistence dumps) works unchanged
against N replicas.

Routing discipline:

* **Reads** (``lookup`` / ``lookup_chain``): keys group per owner
  under ONE ring snapshot; one RPC per owner per call, and with the
  fan-out executor armed (``CLUSTER_FANOUT_WORKERS``, default on) the
  owners of a chunk are dispatched CONCURRENTLY — a chunk costs ~one
  RTT instead of ``owners x RTT``.  ``lookup_chain_async`` additionally
  lets the fast lane keep chunk N+1 in flight while chunk N resolves
  (docs/replication.md "Pipelined read path"); merge order is plan
  order either way, so results are bit-identical to the sequential
  path (``CLUSTER_FANOUT_WORKERS=0``).  Arming is latency-adaptive:
  both overlap and pipelining engage only once the observed per-RPC
  latency EWMA reaches ``CLUSTER_OVERLAP_MIN_RPC_S`` (default 250us)
  — against an in-process or loopback transport cheaper than a pool
  handoff they stay sequential, and real network transports cross the
  threshold on the first call.  0 forces always-armed.
* **Writes**: pod-entry admissions live at ``owner(request_key)``;
  engine->request mappings are published BOTH at
  ``owner(engine_key)`` (where ``get_request_key`` routes) and at
  ``owner(request_key)`` (whose local backend resolves them during
  ``evict``).  An eviction is two hops: resolve the request key at the
  engine-key owner, evict at the request-key owner.
* **Failover**: a transport failure marks the replica dead in the
  membership (ring version bump, failover counter) and the operation
  retries against the new owner — the rendezvous runner-up, whose
  replication follower has been keeping that slice warm
  (``replication.py``).  Application errors propagate; only transport
  failures fail over.
* **Observability** (docs/observability.md "Fleet tracing"): when the
  calling context carries a sampled trace, every owner RPC records a
  ``cluster.rpc`` span (replica + method attrs) and forwards the
  trace context on the wire; span summaries piggybacked on the reply
  are stitched back in as children — ONE trace covers the whole
  fan-out, including a failed RPC and its re-routed retry.  Always-on
  fan-out attribution (``rpc_stats()``, the ``/debug/cluster`` rpc
  panel) tallies per-replica latency/error/retry counters plus the
  sequential critical-path breakdown (owner RPCs per lookup) that
  baselines the read-path pipelining work (ROADMAP item 3).

Cluster score memo (``version_vector`` / ``touch_chain``): every
successful replica reply piggybacks the backend's per-shard version
snapshot (``replica.py``), which the router folds — elementwise-max,
so late replies cannot regress a counter — into a per-replica vector
cache.  ``version_vector()`` composes ``(ring.version, ((replica,
vector), ...))`` over the current ring; a replica whose vector is
missing or older than ``CLUSTER_VV_TTL_S`` contributes a unique
never-equal sentinel, so the indexer's exact-prompt memo simply
misses (and the recompute's own replies refresh the cache) rather
than ever validating against stale state.  Router-driven mutations
(add / evict / purge) refresh the mutated owner's vector on their own
reply, so the memo invalidates synchronously; out-of-band writes
(replication followers, ``CLUSTER_LOCAL_INGEST``) are bounded by the
TTL plus the hit path's own ``touch_chain`` RPCs, whose replies
re-arm validation — an advisory-index coherence bound, documented in
docs/replication.md.  ``touch_chain`` fans recency touches to the
keys' owners off-thread (never journaled, never on the hit path's
critical path).

Deadline budget: each fan-out (and each routed single-key op) gets
one wall-clock budget (``CLUSTER_FANOUT_BUDGET_S``); a re-routed
retry after ``mark_dead`` runs against the budget's REMAINDER rather
than restarting the full transport timeout, so p99 under a dead
replica is bounded by ~one timeout.  ``dump_entries`` concatenates
every alive replica's dump; standby slices may duplicate keys, which
``restore_entries`` absorbs idempotently.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Set, Tuple

from llm_d_kv_cache_manager_tpu.cluster.membership import ClusterMembership
from llm_d_kv_cache_manager_tpu.cluster.replica import (
    ReplicaUnavailable,
    decode_entries,
    encode_entries,
    resolve_trace_piggyback_env,
)
from llm_d_kv_cache_manager_tpu.cluster.ring import HashRing
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import Index, PodEntry
from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS, safe_label
from llm_d_kv_cache_manager_tpu.obs.trace import (
    Span,
    current_trace,
    shield_trace,
)
from llm_d_kv_cache_manager_tpu.utils import lockorder
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("cluster.remote_index")

# Leaf lock: per-replica RPC tallies only — never a transport call or
# a membership flip under it.
# kvlint: lock-order: RemoteIndex._stats_lock ascending
lockorder.declare_ascending("RemoteIndex._stats_lock")
# Leaf lock: the per-replica version-vector cache only — noted on the
# RPC completion path (fan-out workers included), so nothing blocking
# may ever run under it.
# kvlint: lock-order: RemoteIndex._vv_lock ascending
lockorder.declare_ascending("RemoteIndex._vv_lock")
# Leaf lock: executor lazy-create/close handshake (the fan-out
# executor's completion lock) — pool construction only, never an RPC.
# kvlint: lock-order: RemoteIndex._exec_lock ascending
lockorder.declare_ascending("RemoteIndex._exec_lock")


def resolve_fanout_workers_env() -> int:
    """CLUSTER_FANOUT_WORKERS: size of the per-RemoteIndex RPC
    executor that overlaps owner RPCs within a fan-out round (each
    worker reuses its own HttpReplicaTransport connection).  0 forces
    the sequential dispatch path (the bit-identical parity oracle and
    the pre-pipelining behavior).  Default 4."""
    raw = os.environ.get("CLUSTER_FANOUT_WORKERS")
    if raw is None:
        return 4
    try:
        return max(0, int(raw))
    except ValueError:
        return 4


def resolve_fanout_budget_env() -> float:
    """CLUSTER_FANOUT_BUDGET_S: wall-clock budget for one whole
    fan-out including failover retries — a re-routed retry spends the
    remainder, not a fresh transport timeout.  0 disables (each
    attempt gets the transport's own timeout).  Default 5.0, matching
    HttpReplicaTransport's construction-time timeout."""
    raw = os.environ.get("CLUSTER_FANOUT_BUDGET_S")
    if raw is None:
        return 5.0
    try:
        return max(0.0, float(raw))
    except ValueError:
        return 5.0


def resolve_vv_ttl_env() -> float:
    """CLUSTER_VV_TTL_S: how long a replica's piggybacked version
    vector stays valid for score-memo validation.  Bounds the
    staleness window for OUT-OF-BAND mutations (replication followers,
    CLUSTER_LOCAL_INGEST) — router-driven mutations invalidate
    synchronously regardless.  0 keeps every composed vector a
    sentinel, i.e. disables the cluster score memo.  Default 2.0."""
    raw = os.environ.get("CLUSTER_VV_TTL_S")
    if raw is None:
        return 2.0
    try:
        return max(0.0, float(raw))
    except ValueError:
        return 2.0


def resolve_overlap_min_rpc_env() -> float:
    """CLUSTER_OVERLAP_MIN_RPC_S: adaptive-arming threshold for the
    overlapped fan-out and the pipelined chunk drive.  Thread-pool
    handoff costs a few hundred microseconds per dispatch; against an
    in-process or same-host transport whose whole "RPC" is cheaper
    than that, overlapping is a net loss.  The fan-out arms only once
    the observed per-RPC latency EWMA reaches this threshold — real
    network transports cross it on the first call, free local
    transports never do.  0 forces always-armed (tests pin the
    overlapped paths this way).  Default 250e-6."""
    raw = os.environ.get("CLUSTER_OVERLAP_MIN_RPC_S")
    if raw is None:
        return 0.00025
    try:
        return max(0.0, float(raw))
    except ValueError:
        return 0.00025


class _CompletedLookup:
    """Degenerate async-lookup handle: the sequential fallback resolves
    inline, so ``result()`` is just the stored value (keeps the fast
    lane's pipelined drive shape-agnostic)."""

    __slots__ = ("_value",)

    def __init__(self, value) -> None:
        self._value = value

    def result(self, timeout=None):
        return self._value


class RemoteIndex(Index):
    """See module docstring."""

    _OWNER_CACHE_MAX = 65536

    # Stitched cluster.rpc spans nest under the stage whose time they
    # attribute: read fan-out inside the fast lane's "index_lookup",
    # everything else inside the event plane's "kvevents.apply".
    _RPC_TRACE_PARENT = {
        "lookup": "index_lookup",
        "lookup_chain": "index_lookup",
    }

    # Chunk-level async lookups get their own small pool so a task
    # waiting on fan-out futures can never starve the leaf RPCs it
    # depends on (two-level pools make the wait graph acyclic).
    _PIPE_WORKERS = 4

    def __init__(
        self,
        membership: ClusterMembership,
        trace_rpcs: Optional[bool] = None,
        rpc_accounting: bool = True,
        fanout_workers: Optional[int] = None,
        fanout_budget_s: Optional[float] = None,
        vv_ttl_s: Optional[float] = None,
        overlap_min_rpc_s: Optional[float] = None,
    ) -> None:
        self.membership = membership
        # Owner-RPC overlap (None -> CLUSTER_FANOUT_WORKERS, default
        # 4; 0 = sequential parity path).
        self.fanout_workers = (
            resolve_fanout_workers_env()
            if fanout_workers is None
            else max(0, int(fanout_workers))
        )
        # Whole-fan-out deadline budget (None ->
        # CLUSTER_FANOUT_BUDGET_S, default 5.0; 0 disables).
        self.fanout_budget_s = (
            resolve_fanout_budget_env()
            if fanout_budget_s is None
            else max(0.0, float(fanout_budget_s))
        )
        # Version-vector freshness bound (None -> CLUSTER_VV_TTL_S).
        self.vv_ttl_s = (
            resolve_vv_ttl_env()
            if vv_ttl_s is None
            else max(0.0, float(vv_ttl_s))
        )
        # Adaptive-arming threshold (None ->
        # CLUSTER_OVERLAP_MIN_RPC_S, default 250us; 0 = always armed).
        self.overlap_min_rpc_s = (
            resolve_overlap_min_rpc_env()
            if overlap_min_rpc_s is None
            else max(0.0, float(overlap_min_rpc_s))
        )
        # Trace-context forwarding + span stitching on traced calls
        # (None -> CLUSTER_TRACE_PIGGYBACK, default on; untraced calls
        # never pay for it either way).
        self.trace_rpcs = (
            resolve_trace_piggyback_env()
            if trace_rpcs is None
            else trace_rpcs
        )
        # Per-replica tallies + the kvtpu_cluster_rpc_* families; the
        # bench's trace A/B cell flips this off to price the whole
        # observability plane on the untraced path.
        self.rpc_accounting = rpc_accounting
        self._stats_lock = lockorder.tracked(
            threading.Lock(), "RemoteIndex._stats_lock"
        )
        self._rpc_tallies: Dict[str, dict] = {}  # guarded-by: _stats_lock
        self._reroutes = 0  # guarded-by: _stats_lock
        # Outstanding transport calls right now — the timeline's
        # cluster_rpc_in_flight series (obs/timeline.py).  Locked,
        # unlike the shard version counters: those only ever advance
        # (a lost bump merely lags), but a PAIRED inc/dec gauge
        # drifts permanently on one lost store.  Two leaf-lock ops
        # per RPC are noise next to the transport call itself.
        self._in_flight = 0  # guarded-by: _stats_lock
        self._lookup_calls = 0  # guarded-by: _stats_lock
        self._lookup_owner_rpcs = 0  # guarded-by: _stats_lock
        self._lookup_owner_max = 0  # guarded-by: _stats_lock
        self._lookup_rpc_s = 0.0  # guarded-by: _stats_lock
        # Overlap/speculation attribution (/debug/cluster rpc panel):
        # high-water of concurrently outstanding transport calls, and
        # the fast lane's speculative chunk dispatches vs the ones a
        # dead chain dropped on the floor.
        self._overlap_depth = 0  # guarded-by: _stats_lock
        self._speculative_rpcs = 0  # guarded-by: _stats_lock
        self._speculative_wasted = 0  # guarded-by: _stats_lock
        self._budget_exhausted = 0  # guarded-by: _stats_lock
        # Observed per-RPC latency EWMA (0.8/0.2, seeded by the first
        # call) — the adaptive-arming signal.  Only ever compared
        # against overlap_min_rpc_s; never affects results.
        self._rpc_ewma_s = 0.0  # guarded-by: _stats_lock
        # Per-replica piggybacked version vectors:
        # replica -> (vector tuple, monotonic note time).
        self._vv_lock = lockorder.tracked(
            threading.Lock(), "RemoteIndex._vv_lock"
        )
        self._vectors: Dict[str, Tuple[Tuple[int, ...], float]] = (
            {}
        )  # guarded-by: _vv_lock
        self._vv_unknown_seq = 0  # guarded-by: _vv_lock
        # Fan-out executor completion lock: lazy pool creation and the
        # close() handshake only.
        self._exec_lock = lockorder.tracked(
            threading.Lock(), "RemoteIndex._exec_lock"
        )
        self._rpc_pool: Optional[ThreadPoolExecutor] = None
        self._pipe_pool: Optional[ThreadPoolExecutor] = None
        self._closed = False
        # Ring changes invalidate the composed vector by construction
        # (ring.version is part of it); the listener additionally
        # drops departed members' vectors and refreshes the rest.
        self.membership.add_listener(self._on_ring_change)
        # method -> labeled histogram child (labels() does a lock +
        # dict lookup per call; the method set is tiny and fixed).
        self._latency_children: Dict[str, object] = {}
        # key -> (ring, owner), validated by ring IDENTITY on read: a
        # membership change produces a new immutable ring object, so a
        # stale entry can never validate (same single-key-dict-op
        # pattern as InMemoryIndex._group_cache; benign under the GIL).
        self._owner_cache: Dict[int, Tuple[HashRing, str]] = {}

    # -- executors ------------------------------------------------------

    def _rpc_pool_get(self) -> Optional[ThreadPoolExecutor]:
        """The leaf owner-RPC pool, lazily created (None when overlap
        is off or the index is closed)."""
        if self.fanout_workers <= 0 or self._closed:
            return None
        # gil-atomic: single ref read; creation races resolve under _exec_lock
        pool = self._rpc_pool
        if pool is None:
            with self._exec_lock:
                pool = self._rpc_pool
                if pool is None and not self._closed:
                    pool = ThreadPoolExecutor(
                        max_workers=self.fanout_workers,
                        thread_name_prefix="kvtpu-cluster-rpc",
                    )
                    self._rpc_pool = pool
        return pool

    def _overlap_armed(self) -> bool:
        """Whether overlapping/pipelining is worth its handoff cost
        right now: armed once the per-RPC latency EWMA reaches
        ``overlap_min_rpc_s`` (0 = always).  Arming never changes
        results, only which dispatch path computes them."""
        threshold = self.overlap_min_rpc_s
        if threshold <= 0.0:
            return True
        with self._stats_lock:
            return self._rpc_ewma_s >= threshold

    def _pipe_pool_get(self) -> Optional[ThreadPoolExecutor]:
        """The chunk-level pipeline pool (lookup_chain_async tasks);
        armed only when owner overlap is — with workers=0 the whole
        async surface degenerates to the sequential path."""
        if self.fanout_workers <= 0 or self._closed:
            return None
        # gil-atomic: single ref read; creation races resolve under _exec_lock
        pool = self._pipe_pool
        if pool is None:
            with self._exec_lock:
                pool = self._pipe_pool
                if pool is None and not self._closed:
                    pool = ThreadPoolExecutor(
                        max_workers=self._PIPE_WORKERS,
                        thread_name_prefix="kvtpu-cluster-pipe",
                    )
                    self._pipe_pool = pool
        return pool

    def close(self) -> None:
        """Shut both executors down (speculative futures are dropped,
        not awaited); subsequent calls fall back to the sequential
        path, so a racing scorer still completes correctly."""
        with self._exec_lock:
            self._closed = True
            rpc_pool, self._rpc_pool = self._rpc_pool, None
            pipe_pool, self._pipe_pool = self._pipe_pool, None
        for pool in (pipe_pool, rpc_pool):
            if pool is not None:
                pool.shutdown(wait=True, cancel_futures=True)

    # -- version-vector cache -------------------------------------------

    def _note_vector(self, replica_id: str, vector) -> None:
        """Fold one reply's piggybacked vector into the cache.
        Elementwise max: replies complete out of order (the fan-out
        executor), and a late reply must never regress a shard counter
        — counters only ever advance, so max is exact."""
        try:
            vec = tuple(int(v) for v in vector)
        except (TypeError, ValueError):
            return
        now = time.monotonic()
        with self._vv_lock:
            cached = self._vectors.get(replica_id)
            if cached is not None and len(cached[0]) == len(vec):
                vec = tuple(
                    a if a > b else b for a, b in zip(cached[0], vec)
                )
            self._vectors[replica_id] = (vec, now)

    def _on_ring_change(self, ring: HashRing) -> None:
        """Membership listener: departed members' vectors are dropped
        (they may rejoin with rebuilt, i.e. regressed, counters), and
        the survivors are refreshed best-effort off-thread so the memo
        re-validates quickly after a failover."""
        members = set(ring.members)
        with self._vv_lock:
            for replica_id in list(self._vectors):
                if replica_id not in members:
                    del self._vectors[replica_id]
        pool = self._rpc_pool_get()
        if pool is None:
            return
        for replica_id in ring.members:
            try:
                pool.submit(self._refresh_vector, replica_id)
            except RuntimeError:  # pool shut down under us
                return

    def _refresh_vector(self, replica_id: str) -> None:
        """Best-effort explicit vector fetch.  Bypasses ``_call`` on
        purpose: a refresh failure must not mark_dead (and so re-fire
        this listener) — the heartbeat monitor owns liveness here."""
        try:
            transport = self.membership.transport(replica_id)
            call_vv = getattr(transport, "call_vv", None)
            if call_vv is None:
                return
            payload, _, vector = call_vv("version_vector", [])
            vec = vector if vector is not None else payload
            if vec:
                self._note_vector(replica_id, vec)
        except Exception:  # noqa: BLE001 advisory refresh; kvlint: disable=KV005
            # Deliberately silent: the vector stays sentinel (memo
            # misses) and the heartbeat monitor owns liveness.
            pass

    def version_vector(self) -> tuple:
        """The cluster-wide memo validator: ``(ring.version,
        ((replica, vector), ...))`` over the current ring's members.
        A member with no fresh vector (never heard from, or older than
        ``vv_ttl_s``) contributes a unique sentinel that can never
        compare equal — the memo misses instead of trusting stale
        state, and the recompute's replies repopulate the cache."""
        ring = self.membership.ring()
        ttl = self.vv_ttl_s
        now = time.monotonic()
        parts = []
        with self._vv_lock:
            for replica_id in ring.members:
                cached = self._vectors.get(replica_id)
                if (
                    cached is None
                    or ttl <= 0.0
                    or now - cached[1] > ttl
                ):
                    self._vv_unknown_seq += 1
                    parts.append(
                        (replica_id, ("?", self._vv_unknown_seq))
                    )
                else:
                    parts.append((replica_id, cached[0]))
        return (ring.version, tuple(parts))

    def touch_chain(self, request_keys: Sequence[int]) -> None:
        """Recency refresh for a memo hit's keys, fanned to their
        owners off-thread (inline when overlap is off).  Best-effort:
        a lost touch costs at worst one early LRU eviction on one
        replica — never worth blocking the hit path.  The touch
        replies' piggybacked vectors also re-arm memo validation, so a
        hit stream stays coherent without lookups."""
        keys = [int(k) for k in request_keys]
        if not keys:
            return
        ring = self.membership.ring()
        pool = self._rpc_pool_get()
        for owner, owner_keys in self._group_by_owner(
            ring, keys
        ).items():
            if pool is None:
                self._touch_one(owner, owner_keys)
            else:
                try:
                    pool.submit(self._touch_one, owner, owner_keys)
                except RuntimeError:  # pool shut down under us
                    self._touch_one(owner, owner_keys)

    def _touch_one(self, owner: str, keys: List[int]) -> None:
        try:
            self._call(owner, "touch_chain", [keys])
        except Exception:  # noqa: BLE001 advisory touch; kvlint: disable=KV005
            # _call already did the mark_dead/metrics work for
            # transport failures; nothing to propagate to.
            pass

    # -- routing plumbing ----------------------------------------------

    def _owner(self, ring: HashRing, key: int) -> str:
        cached = self._owner_cache.get(key)
        if cached is not None and cached[0] is ring:
            return cached[1]
        owner = ring.owner(key)
        cache = self._owner_cache
        if len(cache) >= self._OWNER_CACHE_MAX:
            cache.clear()
        cache[key] = (ring, owner)
        return owner

    def _max_attempts(self) -> int:
        return len(self.membership.members()) + 1

    def _rpc_latency(self, method: str):
        child = self._latency_children.get(method)
        if child is None:
            child = METRICS.cluster_rpc_latency.labels(method=method)
            # gil-atomic: idempotent memo; racing put re-derives the same value
            self._latency_children[method] = child
        return child

    def _tally(
        self,
        replica_id: str,
        method: str,
        elapsed: float,
        error: Optional[Tuple[str, str]] = None,
    ) -> None:
        """Per-replica fan-out attribution (the /debug/cluster rpc
        panel): call/error counts, latency totals, per-method split,
        and the last transport error's context."""
        with self._stats_lock:
            entry = self._rpc_tallies.get(replica_id)
            if entry is None:
                entry = self._rpc_tallies[replica_id] = {
                    "calls": 0,
                    "errors": 0,
                    "total_s": 0.0,
                    "max_s": 0.0,
                    "methods": {},
                    "last_error": None,
                }
            entry["calls"] += 1
            entry["total_s"] += elapsed
            if elapsed > entry["max_s"]:
                entry["max_s"] = elapsed
            methods = entry["methods"]
            methods[method] = methods.get(method, 0) + 1
            if method in self._RPC_TRACE_PARENT:
                self._lookup_rpc_s += elapsed
            if error is not None:
                entry["errors"] += 1
                entry["last_error"] = {
                    "kind": error[0],
                    "method": method,
                    "detail": error[1][:200],
                    "unix": time.time(),
                }

    def _stitch(
        self, trace, wire_spans: list, anchor: float, replica_id: str
    ) -> None:
        """Re-anchor piggybacked server-side span records inside the
        RPC window (their clocks are replica-relative).  Malformed
        records never fail the call — the piggyback is advisory."""
        try:
            for record in wire_spans:
                name, parent, start_us, dur_us, status, attrs = record
                span = Span(
                    str(name),
                    str(parent) or "cluster.rpc",
                    anchor + float(start_us) / 1e6,
                )
                span.end = span.start + max(0.0, float(dur_us)) / 1e6
                span.status = str(status)
                for pair in attrs:
                    span.attrs[str(pair[0])] = pair[1]
                span.attrs.setdefault("replica", replica_id)
                trace.append_span(span)
        except Exception:  # noqa: BLE001 — advisory, never fails the RPC
            logger.debug(
                "garbled span piggyback from replica %s",
                replica_id,
                exc_info=True,
            )

    def _call_traced(
        self, trace, transport, replica_id: str, method: str,
        args: list, start: float, timeout: Optional[float],
    ):
        """Traced transport call: a cluster.rpc span per owner RPC,
        trace context on the wire, reply spans stitched back in.
        Returns ``(result, piggybacked_vector_or_None)``."""
        with trace.span(
            "cluster.rpc",
            parent=self._RPC_TRACE_PARENT.get(method, "kvevents.apply"),
        ) as rpc:
            rpc.set_attr("replica", replica_id)
            rpc.set_attr("method", method)
            call_vv = getattr(transport, "call_vv", None)
            if call_vv is not None:
                result, spans, vector = call_vv(
                    method,
                    args,
                    traceparent=trace.traceparent(),
                    timeout=timeout,
                )
            else:
                call_ex = getattr(transport, "call_ex", None)
                if call_ex is None:
                    # Foreign transport without the traced surface:
                    # the RPC span still attributes the hop.
                    return transport.call(method, args), None
                result, spans = call_ex(
                    method, args, traceparent=trace.traceparent()
                )
                vector = None
            if spans:
                rpc.set_attr("server_spans", len(spans))
                self._stitch(trace, spans, start, replica_id)
            return result, vector

    def _call(
        self,
        replica_id: str,
        method: str,
        args: list,
        timeout: Optional[float] = None,
    ):
        """One transport call with latency/error accounting; transport
        failures mark the replica dead (the failover trigger) before
        re-raising for the caller's re-route loop.  ``timeout`` is the
        fan-out deadline budget's remainder — forwarded to transports
        that support per-call deadlines, so a retry never restarts the
        full transport timeout.  A piggybacked version vector on the
        reply is folded into the memo-validation cache."""
        transport = self.membership.transport(replica_id)
        if timeout is not None and not getattr(
            transport, "supports_deadline", False
        ):
            timeout = None
        ambient = current_trace()
        trace = ambient if self.trace_rpcs else None
        vector = None
        start = time.perf_counter()
        with self._stats_lock:
            self._in_flight += 1
            if self._in_flight > self._overlap_depth:
                self._overlap_depth = self._in_flight
        try:
            try:
                if trace is None:
                    call_vv = getattr(transport, "call_vv", None)
                    if ambient is not None:
                        # trace_rpcs off with a live trace: shield the
                        # in-process transport so the replica's direct
                        # context-var record cannot leak orphan
                        # replica.* spans under a cluster.rpc parent
                        # that was never opened — the knob disables
                        # the WHOLE plane.
                        with shield_trace():
                            if call_vv is not None:
                                result, _, vector = call_vv(
                                    method, args, timeout=timeout
                                )
                            else:
                                result = transport.call(method, args)
                    elif call_vv is not None:
                        result, _, vector = call_vv(
                            method, args, timeout=timeout
                        )
                    else:
                        result = transport.call(method, args)
                else:
                    result, vector = self._call_traced(
                        trace, transport, replica_id, method, args,
                        start, timeout,
                    )
            except (ReplicaUnavailable, ConnectionError, OSError) as exc:
                elapsed = time.perf_counter() - start
                kind = getattr(exc, "kind", None) or "io"
                METRICS.cluster_rpc_errors.labels(
                    replica=safe_label(replica_id),
                    kind=safe_label(kind),
                ).inc()
                if self.rpc_accounting:
                    self._tally(
                        replica_id, method, elapsed,
                        error=(kind, str(exc)),
                    )
                self.membership.mark_dead(
                    replica_id, f"{method} failed: {exc}"
                )
                raise ReplicaUnavailable(str(exc), kind=kind) from exc
        finally:
            with self._stats_lock:
                # Paired -- with the += above; the overlap-depth read
                # between them is a high-water stat, not a decision.
                self._in_flight -= 1  # kvlint: atomic-ok
        elapsed = time.perf_counter() - start
        with self._stats_lock:
            self._rpc_ewma_s = (
                elapsed
                if self._rpc_ewma_s == 0.0
                else 0.8 * self._rpc_ewma_s + 0.2 * elapsed
            )
        self._rpc_latency(method).observe(elapsed)
        if self.rpc_accounting:
            self._tally(replica_id, method, elapsed)
        if vector is not None:
            self._note_vector(replica_id, vector)
        return result

    def in_flight(self) -> int:
        """Transport calls currently outstanding (gauge; see
        obs/timeline.py's cluster_rpc_in_flight series)."""
        with self._stats_lock:
            return self._in_flight

    def _deadline(self) -> Optional[float]:
        budget = self.fanout_budget_s
        if budget <= 0.0:
            return None
        return time.monotonic() + budget

    def _remaining(
        self, deadline: Optional[float], last_exc
    ) -> Optional[float]:
        """Budget remainder for the next attempt.  The FIRST attempt
        always runs (remainder floored, never refused); an exhausted
        budget after a failure re-raises instead of retrying — p99
        under a dead replica is bounded by ~one timeout, not one per
        re-route."""
        if deadline is None:
            return None
        remaining = deadline - time.monotonic()
        if remaining <= 0.0 and last_exc is not None:
            with self._stats_lock:
                self._budget_exhausted += 1
            raise last_exc
        return max(remaining, 0.05)

    def _call_routed(self, key: int, method: str, args: list):
        """Single-key op with failover re-route under one deadline
        budget."""
        last_exc: Optional[Exception] = None
        deadline = self._deadline()
        for _ in range(self._max_attempts()):
            timeout = self._remaining(deadline, last_exc)
            ring = self.membership.ring()
            owner = self._owner(ring, key)
            try:
                return self._call(owner, method, args, timeout=timeout)
            except ReplicaUnavailable as exc:
                last_exc = exc
                if self.membership.ring() is ring:
                    # mark_dead refused (last replica alive): re-routing
                    # would loop on the same owner forever.
                    break
                with self._stats_lock:
                    self._reroutes += 1
        assert last_exc is not None
        raise last_exc

    def _group_by_owner(
        self, ring: HashRing, keys: Sequence[int]
    ) -> Dict[str, List[int]]:
        groups: Dict[str, List[int]] = {}
        for key in keys:
            groups.setdefault(self._owner(ring, key), []).append(key)
        return groups

    def _fanout(self, pending: list, plan, on_result=None) -> None:
        """THE failover fan-out loop, shared by every grouped op.

        ``plan(ring, pending)`` returns ``[(owner, method, args,
        items)]`` — one RPC per owner, ``items`` being the subset of
        ``pending`` that re-enters the retry set if that owner's
        transport fails (the failed owner was marked dead by
        ``_call``, so the re-plan runs on the NEW ring and routes to
        the failover owner).  The loop stops when everything landed,
        when the ring identity did not change after a failure (the
        last-replica refusal — re-planning would loop on the same
        owner forever), when the deadline budget ran dry after a
        failure, or when attempts exhaust; undeliverable items
        re-raise the last transport error.  An item that rode more
        than one failed owner's call retries once (value-dedup for
        hashable items, identity for the rest).

        With the RPC executor armed, a round's owner RPCs dispatch
        concurrently; results are consumed (and ``on_result`` runs, on
        this thread) in PLAN ORDER, so merges are bit-identical to the
        sequential path and the failover/refusal invariants above are
        unchanged — overlap happens strictly within one round.
        """
        last_exc: Optional[Exception] = None
        deadline = self._deadline()
        for _ in range(self._max_attempts()):
            if not pending:
                return
            timeout = self._remaining(deadline, last_exc)
            ring = self.membership.ring()
            failed: list = []
            plans = plan(ring, pending)
            pool = (
                self._rpc_pool_get()
                if len(plans) > 1 and self._overlap_armed()
                else None
            )
            if pool is None:
                for owner, method, args, items in plans:
                    try:
                        result = self._call(
                            owner, method, args, timeout=timeout
                        )
                    except ReplicaUnavailable as exc:
                        last_exc = exc
                        failed.extend(items)
                        continue
                    if on_result is not None:
                        on_result(result)
            else:
                dispatched = []
                for owner, method, args, items in plans:
                    # Fresh context copy per task: the ambient trace
                    # rides into the worker (Trace appends are
                    # locked), and one Context can't be entered twice
                    # concurrently.
                    ctx = contextvars.copy_context()
                    try:
                        future = pool.submit(
                            ctx.run,
                            self._call,
                            owner,
                            method,
                            args,
                            timeout,
                        )
                    except RuntimeError:  # pool shut down under us
                        future = None
                    dispatched.append((owner, method, args, items, future))
                for owner, method, args, items, future in dispatched:
                    try:
                        if future is None:
                            result = self._call(
                                owner, method, args, timeout=timeout
                            )
                        else:
                            result = future.result()
                    except ReplicaUnavailable as exc:
                        last_exc = exc
                        failed.extend(items)
                        continue
                    if on_result is not None:
                        on_result(result)
            if not failed:
                return
            if self.membership.ring() is ring:
                break
            with self._stats_lock:
                self._reroutes += len(failed)
            seen = set()
            pending = []
            for item in failed:
                marker = (
                    item if isinstance(item, (int, tuple)) else id(item)
                )
                if marker in seen:
                    continue
                seen.add(marker)
                pending.append(item)
        if last_exc is not None:
            raise last_exc

    # -- read path ------------------------------------------------------

    def lookup(
        self,
        request_keys: Sequence[int],
        pod_identifier_set: Optional[Set[str]] = None,
    ) -> Dict[int, List[PodEntry]]:
        if not request_keys:
            raise ValueError("no request keys provided for lookup")
        pods_arg = sorted(pod_identifier_set) if pod_identifier_set else None
        result: Dict[int, List[PodEntry]] = {}
        rounds: List[int] = []

        def plan(ring, pending):
            plans = [
                (owner, "lookup", [keys, pods_arg], keys)
                for owner, keys in self._group_by_owner(
                    ring, pending
                ).items()
            ]
            rounds.append(len(plans))
            return plans

        def on_result(pairs):
            for key, raw_entries in pairs:
                result[key] = list(decode_entries(raw_entries))

        self._fanout(list(request_keys), plan, on_result)
        if self.rpc_accounting:
            # Sequential critical path: the fan-out loop issues one RPC
            # per owner per round, back to back — first-round width is
            # the per-chunk serial depth item 3's pipelining attacks.
            with self._stats_lock:
                self._lookup_calls += 1
                self._lookup_owner_rpcs += sum(rounds)
                if rounds and rounds[0] > self._lookup_owner_max:
                    self._lookup_owner_max = rounds[0]
        return result

    def rpc_stats(self) -> dict:
        """The /debug/cluster per-replica rpc panel: fan-out
        attribution tallies plus the sequential-owner critical-path
        breakdown (the read-path pipelining baseline)."""
        with self._stats_lock:
            replicas: Dict[str, dict] = {}
            for replica_id, entry in sorted(self._rpc_tallies.items()):
                calls = entry["calls"]
                view = {
                    "calls": calls,
                    "errors": entry["errors"],
                    "total_ms": round(entry["total_s"] * 1e3, 3),
                    "avg_ms": (
                        round(entry["total_s"] / calls * 1e3, 3)
                        if calls
                        else 0.0
                    ),
                    "max_ms": round(entry["max_s"] * 1e3, 3),
                    "methods": dict(entry["methods"]),
                }
                if entry["last_error"] is not None:
                    view["last_error"] = dict(entry["last_error"])
                replicas[replica_id] = view
            lookups = self._lookup_calls
            return {
                "replicas": replicas,
                "in_flight": self._in_flight,
                "reroutes": self._reroutes,
                "critical_path": {
                    "lookup_calls": lookups,
                    "owner_rpcs": self._lookup_owner_rpcs,
                    "avg_owners_per_lookup": (
                        round(self._lookup_owner_rpcs / lookups, 3)
                        if lookups
                        else 0.0
                    ),
                    "max_owners_per_lookup": self._lookup_owner_max,
                    "sequential_rpc_s": round(self._lookup_rpc_s, 6),
                    "overlap_depth": self._overlap_depth,
                    "speculative_rpcs": self._speculative_rpcs,
                    "speculative_wasted": self._speculative_wasted,
                },
                "fanout": {
                    "workers": self.fanout_workers,
                    "budget_s": self.fanout_budget_s,
                    "budget_exhausted": self._budget_exhausted,
                    "rpc_ewma_us": round(self._rpc_ewma_s * 1e6, 3),
                    "overlap_min_rpc_us": round(
                        self.overlap_min_rpc_s * 1e6, 3
                    ),
                    "armed": (
                        self.overlap_min_rpc_s <= 0.0
                        or self._rpc_ewma_s >= self.overlap_min_rpc_s
                    ),
                },
            }

    def lookup_chain(
        self, request_keys: Sequence[int]
    ) -> List[Sequence[PodEntry]]:
        """Aligned per-key pod snapshots (the fast-lane shape): group
        the chunk's keys per owner, ONE ``lookup`` RPC per owner, then
        truncate at the first key with no resident pods.  A replica's
        own present-but-empty early stop reads as "no pods" for its
        later keys, which can only move the truncation point EARLIER
        than or equal to the true break — never report residency past
        a dead chain (scores stay parity-exact; property-pinned)."""
        if not request_keys:
            return []
        found = self.lookup(request_keys, None)
        out: List[Sequence[PodEntry]] = []
        for key in request_keys:
            pods = found.get(key)
            if not pods:
                break
            out.append(pods)
        return out

    def lookup_chain_async(self, request_keys: Sequence[int]):
        """Dispatch one chunk's ``lookup_chain`` without blocking: the
        fast lane's pipelined drive keeps chunk N+1 (and speculated
        deeper chunks) in flight while it consumes chunk N.  Returns a
        handle whose ``result()`` yields exactly what ``lookup_chain``
        would (same fan-out, failover, and accounting — the task runs
        on the chunk-level pipe pool, its owner RPCs on the leaf RPC
        pool, so waiting tasks can never starve the RPCs they need).
        With overlap off — or not yet armed (the per-RPC latency EWMA
        below ``overlap_min_rpc_s``) — the chunk resolves inline
        (sequential parity).
        """
        keys = list(request_keys)
        pool = (
            self._pipe_pool_get() if self._overlap_armed() else None
        )
        if pool is None or not keys:
            return _CompletedLookup(self.lookup_chain(keys))
        ctx = contextvars.copy_context()
        try:
            return pool.submit(ctx.run, self.lookup_chain, keys)
        except RuntimeError:  # pool shut down under us
            return _CompletedLookup(self.lookup_chain(keys))

    def record_speculation(self, dispatched: int, wasted: int) -> None:
        """Fast-lane speculation attribution: chunks dispatched before
        their predecessor resolved, and the subset a dead chain then
        dropped unconsumed (the /debug/cluster rpc panel's
        ``speculative_rpcs`` / ``speculative_wasted``)."""
        with self._stats_lock:
            self._speculative_rpcs += int(dispatched)
            self._speculative_wasted += int(wasted)

    # -- write path -----------------------------------------------------

    def add(
        self,
        engine_keys: Sequence[int],
        request_keys: Sequence[int],
        entries: Sequence[PodEntry],
    ) -> None:
        if not engine_keys or not request_keys or not entries:
            raise ValueError("no keys or entries provided for add")
        if len(engine_keys) != len(request_keys):
            raise ValueError("engine/request key length mismatch")
        wire_entries = encode_entries(entries)

        def plan(ring, pending):
            # Aligned pairs grouped by request-key owner.
            groups: Dict[str, List[Tuple[int, int]]] = {}
            for pair in pending:
                groups.setdefault(
                    self._owner(ring, pair[1]), []
                ).append(pair)
            return [
                (
                    owner,
                    "add",
                    [
                        [ek for ek, _ in pairs],
                        [rk for _, rk in pairs],
                        wire_entries,
                    ],
                    pairs,
                )
                for owner, pairs in groups.items()
            ]

        self._fanout(list(zip(engine_keys, request_keys)), plan)
        # Mappings published for EVERY pair, not just cross-owner ones:
        # besides serving get_request_key at the engine-key owner, the
        # add_mappings RPC journals a mappings-only record whose
        # standby filter keys on EITHER side — a same-owner pair's
        # engine-key standby can differ from its request-key standby,
        # and without the record that standby would miss the mapping
        # and classify post-failover evictions as "already gone".
        # Idempotent where it duplicates the full add's mapping.
        self.add_mappings(engine_keys, request_keys)

    def add_mappings(
        self, engine_keys: Sequence[int], request_keys: Sequence[int]
    ) -> None:
        """Publish engine->request mappings at BOTH owners: the
        engine-key owner serves ``get_request_key``; the request-key
        owner's local backend resolves the mapping during ``evict``.
        A pair that failed on one of its two owners re-routes
        wholesale (idempotent on the surviving owner)."""

        def plan(ring, pending):
            groups: Dict[str, List[Tuple[int, int]]] = {}
            for pair in pending:
                for owner in {
                    self._owner(ring, pair[0]),
                    self._owner(ring, pair[1]),
                }:
                    groups.setdefault(owner, []).append(pair)
            return [
                (
                    owner,
                    "add_mappings",
                    [
                        [ek for ek, _ in pairs],
                        [rk for _, rk in pairs],
                    ],
                    pairs,
                )
                for owner, pairs in groups.items()
            ]

        self._fanout(list(zip(engine_keys, request_keys)), plan)

    def add_entries_batch(
        self,
        items: Sequence[Tuple[Sequence[int], Sequence[PodEntry]]],
    ) -> None:
        """The kvevents batched-apply surface: request keys group per
        owner across the whole batch — one RPC per owner per flush.
        An item whose keys straddled a failed owner retries whole on
        the re-planned ring; its slices that landed re-apply
        idempotently."""
        pending = [
            [list(request_keys), encode_entries(entries)]
            for request_keys, entries in items
            if request_keys
        ]

        def plan(ring, pending):
            # owner -> ([per-owner wire items], [source items]).
            groups: Dict[str, Tuple[List[list], List[list]]] = {}
            for item in pending:
                request_keys, wire_entries = item
                by_owner: Dict[str, List[int]] = {}
                for rk in request_keys:
                    by_owner.setdefault(
                        self._owner(ring, rk), []
                    ).append(rk)
                for owner, rks in by_owner.items():
                    bucket = groups.setdefault(owner, ([], []))
                    bucket[0].append([rks, wire_entries])
                    bucket[1].append(item)
            return [
                (owner, "add_entries_batch", [owner_items], sources)
                for owner, (owner_items, sources) in groups.items()
            ]

        self._fanout(pending, plan)

    def evict(self, engine_key: int, entries: Sequence[PodEntry]) -> None:
        """Two hops: resolve the request key at the engine-key owner,
        evict at the request-key owner.  When the eviction empties the
        key (the owner pruned its mapping), the mapping stub at the
        engine-key owner is evicted too, so ``get_request_key`` raises
        exactly like a local backend's would."""
        if not entries:
            raise ValueError("no entries provided for eviction")
        try:
            request_key = self.get_request_key(engine_key)
        except KeyError:
            return  # mapping already gone — same no-op as local backends
        wire_entries = encode_entries(entries)
        pruned = self._call_routed(
            request_key, "evict", [engine_key, wire_entries]
        )
        if pruned:
            ring = self.membership.ring()
            ek_owner = self._owner(ring, engine_key)
            if ek_owner != self._owner(ring, request_key):
                try:
                    self._call(
                        ek_owner, "evict", [engine_key, wire_entries]
                    )
                except ReplicaUnavailable:
                    # Stub cleanup is best-effort: the dead replica's
                    # stale mapping lingers exactly like a local LRU
                    # leftover would.
                    pass

    def get_request_key(self, engine_key: int) -> int:
        found, value = self._call_routed(
            engine_key, "get_request_key", [engine_key]
        )
        if not found:
            raise KeyError(f"engine key not found: {engine_key:#x}")
        return value

    # -- persistence / admin --------------------------------------------

    def dump_entries(
        self,
    ) -> Tuple[List[Tuple[int, List[PodEntry]]], List[Tuple[int, int]]]:
        """Concatenated dumps of every ALIVE replica.  Standby slices
        (replication followers warm peers' keys) may duplicate request
        keys across replicas; restore absorbs duplicates idempotently.
        An unreachable replica is skipped (and marked dead) — the dump
        is a best-effort snapshot, the journal covers the gap."""
        block_entries: List[Tuple[int, List[PodEntry]]] = []
        engine_map: List[Tuple[int, int]] = []
        for replica_id in self.membership.alive():
            try:
                raw_blocks, raw_map = self._call(
                    replica_id, "dump_entries", []
                )
            except ReplicaUnavailable:
                continue
            for key, raw_entries in raw_blocks:
                block_entries.append(
                    (key, list(decode_entries(raw_entries)))
                )
            engine_map.extend((ek, rk) for ek, rk in raw_map)
        return block_entries, engine_map

    def restore_entries(
        self,
        block_entries: Sequence[Tuple[int, Sequence[PodEntry]]],
        engine_map: Sequence[Tuple[int, int]],
    ) -> int:
        ring = self.membership.ring()
        blocks_by_owner: Dict[str, List[list]] = {}
        for request_key, entries in block_entries:
            blocks_by_owner.setdefault(
                self._owner(ring, request_key), []
            ).append([request_key, encode_entries(entries)])
        maps_by_owner: Dict[str, List[list]] = {}
        for ek, rk in engine_map:
            for owner in {self._owner(ring, ek), self._owner(ring, rk)}:
                maps_by_owner.setdefault(owner, []).append([ek, rk])
        restored = 0
        for owner in sorted(set(blocks_by_owner) | set(maps_by_owner)):
            try:
                restored += self._call(
                    owner,
                    "restore_entries",
                    [
                        blocks_by_owner.get(owner, []),
                        maps_by_owner.get(owner, []),
                    ],
                )
            except ReplicaUnavailable:
                logger.warning(
                    "restore skipped unreachable replica %s", owner
                )
        return restored

    def purge_pod(self, pod_identifier: str) -> int:
        removed = 0
        for replica_id in self.membership.alive():
            try:
                removed += self._call(
                    replica_id, "purge_pod", [pod_identifier]
                )
            except ReplicaUnavailable:
                continue  # dead replica holds no servable entries now
        return removed
