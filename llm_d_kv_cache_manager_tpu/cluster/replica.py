"""Replica-side apply surface + the router->replica transports.

A :class:`ClusterReplica` wraps one local ``Index`` backend behind a
method table — the single dispatch surface shared by every transport:
the in-process :class:`LocalReplicaTransport` (tests, bench, the
cluster smoke) and the HTTP endpoint (``POST /replica`` in
``api/http_service.py``) both land in :meth:`ClusterReplica.handle`.

Wire format (canonical CBOR, the house serialization — lists only, no
maps): request ``[method, args]`` or ``[method, args, traceparent]``,
response ``[status, payload]``, ``[status, payload, spans]``, or
``[status, payload, spans, vector]`` with status 0=ok /
1=application error (payload is the message).  Transport failures
raise :class:`ReplicaUnavailable`; application errors raise
:class:`ReplicaError` — the router treats only the former as a
failover trigger.

Version piggyback (docs/replication.md "Pipelined read path"): every
successful reply may carry the replica backend's per-shard version
snapshot as a fourth element (the lists-only codec has no None, so a
reply with a vector but no spans carries ``[]`` in the spans slot).
The router folds the vectors into its cluster-wide
``version_vector()`` so the exact-prompt score memo can validate
against the whole cluster without extra RPCs.
``CLUSTER_VERSION_PIGGYBACK=0`` keeps replies at three elements for
rolling upgrades past routers whose decoder predates the fourth slot.

Trace piggyback (docs/observability.md "Fleet tracing"): a request
whose third element is a sampled W3C ``traceparent`` makes the replica
record server-side spans (wire decode + the method's lookup/apply
split) and return their summaries as the response's third element —
``[name, parent, start_us, dur_us, status, [[attr, value], ...]]``
records relative to the replica's receive time.  The router
(``remote_index.py``) stitches them under its own ``cluster.rpc`` span
so ONE trace covers the whole fan-out with no collector process.
Two-element frames remain valid in both directions (mixed-version
fleets, untraced requests pay zero bytes); ``CLUSTER_TRACE_PIGGYBACK=0``
disables the server-side harvest outright.

Journal tap (replication feed): every mutating call is appended to the
replica's own journal AFTER the local apply succeeds — the same
applied-ops discipline as the kvevents pool's persistence tap, so a
follower replays records as exact index calls.  Batched admissions
arrive without engine keys (the router publishes mappings eagerly via
``add_mappings``, which is journaled as a mappings-only record), so
the record stream splits one logical add into a mappings record plus
an entries record; replay is idempotent and order-preserved within one
router worker (RPCs from one worker are synchronous).
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cbor_canonical import (
    CborDecodeError,
    decode_canonical,
    encode_canonical,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    InMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import Index, PodEntry
from llm_d_kv_cache_manager_tpu.obs.trace import (
    TRACER,
    Trace,
    _new_span_id,
    parse_traceparent,
    shield_trace,
    span as obs_span,
    use_trace,
)
from llm_d_kv_cache_manager_tpu.utils.logging import get_logger

logger = get_logger("cluster.replica")


def resolve_trace_piggyback_env() -> bool:
    """CLUSTER_TRACE_PIGGYBACK: "0"/"false"/"off" disables carrying
    span summaries on replica replies; unset/anything else keeps the
    piggyback on (docs/observability.md)."""
    raw = os.environ.get("CLUSTER_TRACE_PIGGYBACK")
    if raw is None:
        return True
    return raw.strip().lower() not in ("0", "false", "off", "no")


def resolve_version_piggyback_env() -> bool:
    """CLUSTER_VERSION_PIGGYBACK: "0"/"false"/"off" keeps replies at
    three elements (mixed-version fleets whose routers predate the
    vector slot); unset/anything else piggybacks the backend's version
    snapshot on every successful reply (docs/replication.md)."""
    raw = os.environ.get("CLUSTER_VERSION_PIGGYBACK")
    if raw is None:
        return True
    return raw.strip().lower() not in ("0", "false", "off", "no")


class ReplicaError(RuntimeError):
    """The replica executed the call and reports an application error."""


class ReplicaUnavailable(ConnectionError):
    """The replica could not be reached (transport-level failure).

    ``kind`` classifies the failure for the
    ``kvtpu_cluster_rpc_errors_total{replica,kind}`` attribution:
    ``timeout`` / ``refused`` / ``wire_decode`` / ``http_status`` /
    ``killed`` / ``io``.
    """

    def __init__(self, message: str, kind: str = "io") -> None:
        super().__init__(message)
        self.kind = kind


# -- wire helpers -------------------------------------------------------


def encode_entries(entries: Sequence[PodEntry]) -> List[List[str]]:
    return [[e.pod_identifier, e.device_tier] for e in entries]


def decode_entries(raw) -> Tuple[PodEntry, ...]:
    return tuple(PodEntry(str(p), str(t)) for p, t in raw)


def encode_request(
    method: str, args: list, traceparent: Optional[str] = None
) -> bytes:
    """Two elements untraced, three with a trace context — untraced
    requests pay zero extra wire bytes."""
    if traceparent is None:
        return encode_canonical([method, args])
    return encode_canonical([method, args, traceparent])


def decode_request(data: bytes) -> Tuple[str, list, Optional[str]]:
    doc = decode_canonical(data)
    if not isinstance(doc, list) or len(doc) not in (2, 3):
        raise CborDecodeError("unexpected replica request shape")
    method, args = doc[0], doc[1]
    traceparent = doc[2] if len(doc) == 3 else None
    if not isinstance(method, str) or not isinstance(args, list):
        raise CborDecodeError("unexpected replica request shape")
    if traceparent is not None and not isinstance(traceparent, str):
        raise CborDecodeError("unexpected replica request shape")
    return method, args, traceparent


def encode_response(
    status: int,
    payload,
    spans: Optional[list] = None,
    vector: Optional[list] = None,
) -> bytes:
    """Shortest frame that carries what is present: the lists-only
    codec has no None, so a vector with no spans rides behind an empty
    spans placeholder (decoders map ``[]`` back to "no spans")."""
    if vector is not None:
        return encode_canonical([status, payload, spans or [], vector])
    if spans is None:
        return encode_canonical([status, payload])
    return encode_canonical([status, payload, spans])


def _decode_response_frame(
    data: bytes,
) -> Tuple[object, Optional[list], Optional[list]]:
    doc = decode_canonical(data)
    if not isinstance(doc, list) or len(doc) not in (2, 3, 4):
        raise CborDecodeError("unexpected replica response shape")
    status, payload = doc[0], doc[1]
    spans = doc[2] if len(doc) >= 3 else None
    vector = doc[3] if len(doc) == 4 else None
    if spans is not None and not isinstance(spans, list):
        raise CborDecodeError("unexpected replica response shape")
    if vector is not None and not isinstance(vector, list):
        raise CborDecodeError("unexpected replica response shape")
    if status:
        raise ReplicaError(str(payload))
    # [] in the spans slot is the "vector but no spans" placeholder.
    if spans is not None and not spans and vector is not None:
        spans = None
    return payload, spans, vector


def decode_response_vv(
    data: bytes,
) -> Tuple[object, Optional[list], Optional[list]]:
    """(payload, piggybacked spans or None, piggybacked version vector
    or None); raises :class:`ReplicaError` on a status-1 frame."""
    return _decode_response_frame(data)


def decode_response_ex(data: bytes) -> Tuple[object, Optional[list]]:
    """(payload, piggybacked span records or None); raises
    :class:`ReplicaError` on a status-1 frame.  Tolerates (and drops)
    the four-element vector frame so pre-vector callers keep working
    against new replicas."""
    payload, spans, _ = _decode_response_frame(data)
    return payload, spans


def decode_response(data: bytes):
    """Payload-only view (the pre-piggyback contract, kept for every
    caller that does not stitch spans)."""
    payload, _ = decode_response_ex(data)
    return payload


def _wire_attr(value):
    """Span attribute values on the canonical-CBOR wire: ints and
    strings pass, everything else is stringified (lists-only codec —
    no floats, no maps)."""
    if isinstance(value, bool):
        return int(value)
    if isinstance(value, (int, str)):
        return value
    return str(value)


def encode_harvest_spans(harvest: Trace) -> list:
    """Serialize a server-side span harvest for the reply piggyback:
    ``[name, parent, start_us, dur_us, status, [[attr, value], ...]]``
    with times relative to the harvest's start (the replica's receive
    point) — the router re-anchors them inside its RPC span."""
    out: list = []
    for view in harvest.to_dict(include_spans=True)["spans"]:
        out.append(
            [
                view["name"],
                view["parent"] or "",
                int(view["start_ms"] * 1000),
                int(view["duration_ms"] * 1000),
                view["status"],
                [
                    [str(key), _wire_attr(value)]
                    for key, value in view["attributes"].items()
                ],
            ]
        )
    return out


class ClusterReplica:
    """One replica: a local index slice + the RPC method table.

    ``journal`` (a ``persistence.Journal``) enables replication: every
    applied mutation is appended post-apply, and ``sync_snapshot``
    serves the follower-bootstrap boundary (rotate + watermarks + dump)
    — the exact shape ``PersistenceManager.snapshot`` uses, without the
    file layer.
    """

    # Server-side span vocabulary (docs/observability.md): the method
    # table split into the lookup/apply/admin stages a stitched trace
    # shows, all children of the router's "cluster.rpc" span.
    _READ_METHODS = frozenset({"lookup", "lookup_chain"})
    _ADMIN_METHODS = frozenset(
        {
            "ping",
            "get_request_key",
            "dump_entries",
            "sync_snapshot",
            "version_vector",
            "touch_chain",
        }
    )

    def __init__(
        self,
        replica_id: str,
        index: Optional[Index] = None,
        journal=None,
        journal_retain_segments: int = 64,
        trace_piggyback: Optional[bool] = None,
        version_piggyback: Optional[bool] = None,
    ) -> None:
        if not replica_id:
            raise ValueError("replica_id required")
        self.replica_id = replica_id
        self.index = index if index is not None else InMemoryIndex()
        self.journal = journal
        # Piggyback server-side spans on traced requests' replies
        # (None -> CLUSTER_TRACE_PIGGYBACK, default on).
        self.trace_piggyback = (
            resolve_trace_piggyback_env()
            if trace_piggyback is None
            else trace_piggyback
        )
        # Piggyback the backend's per-shard version snapshot on every
        # successful reply (None -> CLUSTER_VERSION_PIGGYBACK, default
        # on).  Off keeps the three-element reply frame for rolling
        # upgrades past pre-vector routers.
        self.version_piggyback = (
            resolve_version_piggyback_env()
            if version_piggyback is None
            else version_piggyback
        )
        # Replication journals have no snapshot boundary to compact
        # against, so they get size-based retention: the newest N
        # segments survive (~N x segment_max_bytes on disk), checked
        # every few hundred appends.  0 disables.  A follower lagging
        # past the window re-bootstraps (docs/replication.md).
        self.journal_retain_segments = journal_retain_segments
        self._journal_appends = 0  # racy-benign tick counter
        self._methods: Dict[str, Callable] = {
            "ping": self._ping,
            "lookup": self._lookup,
            "lookup_chain": self._lookup_chain,
            "add": self._add,
            "add_mappings": self._add_mappings,
            "add_entries_batch": self._add_entries_batch,
            "evict": self._evict,
            "get_request_key": self._get_request_key,
            "dump_entries": self._dump_entries,
            "restore_entries": self._restore_entries,
            "purge_pod": self._purge_pod,
            "sync_snapshot": self._sync_snapshot,
            "version_vector": self._version_vector,
            "touch_chain": self._touch_chain,
        }

    def vector_snapshot(self) -> Optional[List[int]]:
        """The backend's per-shard version snapshot as wire-ready ints,
        or None when the backend has no ``version_vector`` surface (the
        reply then stays vector-free and the router's memo treats this
        replica as unknown)."""
        version_vector = getattr(self.index, "version_vector", None)
        if not callable(version_vector):
            return None
        try:
            return [int(v) for v in version_vector()]
        except Exception:  # noqa: BLE001 piggyback is advisory; kvlint: disable=KV005
            # A backend whose snapshot raises just ships a vector-free
            # reply; the router's memo treats the replica as unknown.
            return None

    def close(self) -> None:
        if self.journal is not None:
            self.journal.close()

    def _journal_tick(self) -> None:
        """Periodic retention pass after an append (see __init__)."""
        if self.journal is None or self.journal_retain_segments <= 0:
            return
        self._journal_appends += 1
        if self._journal_appends % 256 == 0:
            self.journal.compact_keep_last(self.journal_retain_segments)

    # -- dispatch -------------------------------------------------------

    def _stage_for(self, method: str) -> str:
        if method in self._READ_METHODS:
            return "replica.lookup"
        if method in self._ADMIN_METHODS:
            return "replica.admin"
        return "replica.apply"

    def handle(self, method: str, args: list):
        """Execute one RPC; raises ``ReplicaError`` for unknown methods
        (application-level: the replica IS reachable).

        The dispatch records a server-side span on whatever trace is
        active in the context — the wire path's harvest trace, or (for
        the in-process transport) the router's own trace directly; a
        free no-op when nothing is traced, and ``trace_piggyback``
        disables server-side spans on EVERY path (the in-process
        direct record included, so the knob means the same thing over
        both transports)."""
        handler = self._methods.get(method)
        if handler is None:
            raise ReplicaError(f"unknown replica method: {method!r}")
        if not self.trace_piggyback:
            return handler(args)
        with obs_span(self._stage_for(method), parent="cluster.rpc") as s:
            s.set_attr("replica", self.replica_id)
            s.set_attr("method", method)
            return handler(args)

    def handle_wire(self, data: bytes) -> bytes:
        """Decode request bytes, execute, encode response bytes — the
        HTTP endpoint's whole body.  Application errors (including
        malformed requests) become status-1 responses, never transport
        failures.  A sampled traceparent in the request frame turns on
        the span harvest: server-side spans ride back on the reply."""
        received = time.perf_counter()
        try:
            method, args, traceparent = decode_request(data)
        except Exception as exc:  # noqa: BLE001 — becomes a wire error
            return encode_response(1, repr(exc))
        harvest: Optional[Trace] = None
        if traceparent is not None and self.trace_piggyback:
            parent = parse_traceparent(traceparent)
            if parent is not None and parent.sampled:
                # Never finished/recorded locally: the spans exist only
                # to ride the reply; the ROUTER's stitched trace is the
                # single record (no collector, no double counting).
                harvest = Trace(
                    f"replica.{self.replica_id}",
                    parent.trace_id,
                    _new_span_id(),
                    TRACER.recorder,
                    parent_span_id=parent.span_id,
                )
                harvest.add_completed(
                    "replica.decode", received, parent="cluster.rpc"
                )
        try:
            # shield_trace makes the in-process strict-wire transport
            # behave exactly like the HTTP one: server spans travel
            # only via the piggyback, never by context-var leakage.
            with shield_trace():
                if harvest is not None:
                    with use_trace(harvest):
                        payload = self.handle(method, args)
                else:
                    payload = self.handle(method, args)
        except Exception as exc:  # noqa: BLE001 — becomes a wire error
            if not isinstance(exc, ReplicaError):
                logger.exception(
                    "replica %s RPC failed", self.replica_id
                )
            return encode_response(1, repr(exc))
        spans = None
        if harvest is not None:
            try:
                spans = encode_harvest_spans(harvest)
            except Exception:  # noqa: BLE001 — piggyback is advisory
                logger.exception(
                    "replica %s span piggyback failed", self.replica_id
                )
        vector = (
            self.vector_snapshot() if self.version_piggyback else None
        )
        return encode_response(0, payload, spans, vector)

    # -- methods --------------------------------------------------------

    def _ping(self, args):
        return self.replica_id

    def _lookup(self, args):
        keys, pods = args
        pod_set = set(str(p) for p in pods) if pods else None
        found = self.index.lookup([int(k) for k in keys], pod_set)
        return [
            [key, encode_entries(entries)]
            for key, entries in found.items()
        ]

    def _lookup_chain(self, args):
        (keys,) = args
        chain = self.index.lookup_chain([int(k) for k in keys])
        return [encode_entries(entries) for entries in chain]

    def _add(self, args):
        engine_keys, request_keys, raw_entries = args
        entries = decode_entries(raw_entries)
        self.index.add(engine_keys, request_keys, entries)
        if self.journal is not None and entries:
            self.journal.record_add(
                entries[0].pod_identifier,
                0,
                engine_keys,
                request_keys,
                entries,
            )
            self._journal_tick()
        return None

    def _add_mappings(self, args):
        engine_keys, request_keys = args
        add_mappings = getattr(self.index, "add_mappings", None)
        if callable(add_mappings):
            add_mappings(engine_keys, request_keys)
        else:
            raise ReplicaError(
                "backend lacks add_mappings: "
                f"{type(self.index).__name__}"
            )
        if self.journal is not None:
            # Mappings-only record (empty entries): replayed via
            # add_mappings, never as an admission.
            self.journal.record_add(
                "", 0, engine_keys, request_keys, []
            )
            self._journal_tick()
        return None

    def _add_entries_batch(self, args):
        (items,) = args
        decoded = [
            (request_keys, decode_entries(raw_entries))
            for request_keys, raw_entries in items
        ]
        add_batch = getattr(self.index, "add_entries_batch", None)
        if callable(add_batch):
            add_batch(decoded)
        else:
            # Contract fallback (backends without the batched surface):
            # per-key add with an identity engine mapping.  Evictions
            # for these keys arrive under the real engine key and miss
            # (stale entries heal by churn); backends meant for replica
            # duty implement add_entries_batch.
            for request_keys, entries in decoded:
                self.index.add(request_keys, request_keys, entries)
        if self.journal is not None:
            for request_keys, entries in decoded:
                if entries:
                    self.journal.record_add(
                        entries[0].pod_identifier,
                        0,
                        [],
                        request_keys,
                        entries,
                    )
                    self._journal_tick()
        return None

    def _evict(self, args):
        engine_key, raw_entries = args
        entries = decode_entries(raw_entries)
        self.index.evict(int(engine_key), entries)
        if self.journal is not None and entries:
            self.journal.record_evict(
                entries[0].pod_identifier, 0, [int(engine_key)], entries
            )
            self._journal_tick()
        # Pruned flag: did this eviction empty the key (the local
        # backend then dropped the engine mapping)?  The router uses it
        # to clean the mapping stub at the engine-key owner, keeping
        # get_request_key's post-eviction KeyError contract exact
        # across the cluster.
        try:
            self.index.get_request_key(int(engine_key))
        except KeyError:
            return 1
        return 0

    def _get_request_key(self, args):
        (engine_key,) = args
        try:
            return [1, self.index.get_request_key(int(engine_key))]
        except KeyError:
            return [0, 0]

    def _dump_entries(self, args):
        block_entries, engine_map = self.index.dump_entries()
        return [
            [
                [key, encode_entries(entries)]
                for key, entries in block_entries
            ],
            [[ek, rk] for ek, rk in engine_map],
        ]

    def _restore_entries(self, args):
        raw_block_entries, engine_map = args
        block_entries = [
            (key, decode_entries(raw)) for key, raw in raw_block_entries
        ]
        return self.index.restore_entries(
            block_entries, [(ek, rk) for ek, rk in engine_map]
        )

    def _purge_pod(self, args):
        (pod,) = args
        removed = self.index.purge_pod(str(pod))
        if self.journal is not None:
            # Journaled even when removed == 0: a standby slice may
            # hold entries the primary never did, and replay order must
            # still drop them.
            self.journal.record_purge(str(pod))
            self._journal_tick()
        return removed

    def _version_vector(self, args):
        """Explicit vector fetch (ring-change refresh and the local
        transport's non-wire path); [] when the backend has no
        version surface — the router's memo then never validates
        against this replica."""
        return self.vector_snapshot() or []

    def _touch_chain(self, args):
        """Recency-only LRU touch for a memoized chain's keys.  Never
        journaled: followers rebuild recency from their own traffic,
        and a lost touch costs at worst one early eviction."""
        (keys,) = args
        touch = getattr(self.index, "touch_chain", None)
        if callable(touch):
            touch([int(k) for k in keys])
        return None

    def _sync_snapshot(self, args):
        """Follower bootstrap: journal boundary (rotate + per-pod
        watermarks) then a dump taken AFTER it — every record below the
        boundary is covered by the dump, so the follower tails from
        ``TailPosition(boundary, 0)`` and skips numbered records below
        the watermarks (mirroring recovery's replay rule)."""
        if self.journal is not None:
            boundary, watermarks, _ = self.journal.snapshot_boundary()
        else:
            boundary, watermarks = 0, {}
        dump = self._dump_entries([])
        return [
            boundary,
            [[pod, seq] for pod, seq in watermarks.items()],
            dump[0],
            dump[1],
        ]


# -- transports ---------------------------------------------------------


class LocalReplicaTransport:
    """In-process transport: calls ``ClusterReplica.handle`` directly.

    ``strict_wire=True`` round-trips every call through the CBOR codec
    (the contract-parity tests use it so the in-process and HTTP paths
    cannot drift); the default skips the codec for speed.  ``kill()``
    makes every subsequent call raise :class:`ReplicaUnavailable` — the
    failover trigger for tests, the bench, and the smoke.
    """

    # In-process calls either succeed immediately or fail immediately
    # (kill()), so a per-call deadline is accepted and trivially met —
    # the router's budget accounting stays uniform across transports.
    supports_deadline = True

    def __init__(
        self, replica: ClusterReplica, strict_wire: bool = False
    ) -> None:
        self.replica = replica
        self.strict_wire = strict_wire
        self._killed = threading.Event()

    def kill(self) -> None:
        self._killed.set()

    def revive(self) -> None:
        self._killed.clear()

    def call(self, method: str, args: list):
        payload, _, _ = self.call_vv(method, args)
        return payload

    def call_ex(
        self,
        method: str,
        args: list,
        traceparent: Optional[str] = None,
    ) -> Tuple[object, Optional[list]]:
        payload, spans, _ = self.call_vv(method, args, traceparent)
        return payload, spans

    def call_vv(
        self,
        method: str,
        args: list,
        traceparent: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> Tuple[object, Optional[list], Optional[list]]:
        """(payload, piggybacked spans, piggybacked version vector).
        The non-strict path runs the handler on the CALLER's thread, so
        an active trace receives the replica-side spans directly
        through the context var — no piggyback needed (None) — and the
        vector is read off the backend post-apply; the strict path
        round-trips the full wire contract including the trace context
        and the vector frame."""
        if self._killed.is_set():
            raise ReplicaUnavailable(
                f"replica {self.replica.replica_id} is down",
                kind="killed",
            )
        if not self.strict_wire:
            payload = self.replica.handle(method, args)
            vector = (
                self.replica.vector_snapshot()
                if self.replica.version_piggyback
                else None
            )
            return payload, None, vector
        response = self.replica.handle_wire(
            encode_request(method, args, traceparent)
        )
        return decode_response_vv(response)

    def close(self) -> None:
        return None


class HttpReplicaTransport:
    """HTTP transport: ``POST /replica`` with a CBOR body.

    One ``http.client`` connection per calling thread (the router's
    scoring threads, fan-out executor workers, and kvevents workers
    call concurrently — each worker reuses its own connection); any
    transport-level failure closes the connection and raises
    :class:`ReplicaUnavailable` — retries are the router's decision,
    not the transport's.
    """

    # Per-call timeouts tighten (never extend) the construction-time
    # timeout so a re-routed retry spends only the fan-out budget's
    # remainder (docs/replication.md "Deadline budget").
    supports_deadline = True

    def __init__(
        self,
        base_url: str,
        timeout: float = 5.0,
        token: Optional[str] = None,
    ) -> None:
        from urllib.parse import urlsplit

        parsed = urlsplit(base_url)
        if parsed.scheme not in ("http", ""):
            raise ValueError(
                f"unsupported replica URL scheme: {parsed.scheme!r}"
            )
        netloc = parsed.netloc or parsed.path
        host, _, port = netloc.partition(":")
        self._host = host or "127.0.0.1"
        self._port = int(port) if port else 8080
        self._timeout = timeout
        # The replica endpoint shares the admin gate; cluster
        # deployments pass ADMIN_TOKEN here (docs/replication.md).
        self._headers = {"Content-Type": "application/cbor"}
        if token:
            self._headers["Authorization"] = f"Bearer {token}"
        self._local = threading.local()

    def _connection(self):
        import http.client

        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = http.client.HTTPConnection(
                self._host, self._port, timeout=self._timeout
            )
            self._local.conn = conn
        return conn

    def _drop_connection(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except OSError:  # pragma: no cover - best-effort teardown
                pass
            self._local.conn = None

    @staticmethod
    def _failure_kind(exc: BaseException) -> str:
        """Classify a transport failure for the per-replica error
        attribution (``kvtpu_cluster_rpc_errors_total{replica,kind}``):
        a timeout, a refused connect, and garbled bytes are three
        different operational stories."""
        if isinstance(exc, (TimeoutError, socket.timeout)):
            return "timeout"
        if isinstance(exc, ConnectionRefusedError):
            return "refused"
        return "io"

    def call(self, method: str, args: list):
        payload, _, _ = self.call_vv(method, args)
        return payload

    def call_ex(
        self,
        method: str,
        args: list,
        traceparent: Optional[str] = None,
    ) -> Tuple[object, Optional[list]]:
        payload, spans, _ = self.call_vv(method, args, traceparent)
        return payload, spans

    def _apply_timeout(self, conn, timeout: Optional[float]) -> None:
        """Clamp this call's socket timeout to the remaining deadline
        budget (never above the construction-time timeout); the
        connection is thread-local, so resetting it every call keeps
        reuse safe."""
        effective = self._timeout
        if timeout is not None:
            effective = max(0.05, min(timeout, self._timeout))
        conn.timeout = effective
        if conn.sock is not None:
            conn.sock.settimeout(effective)

    def call_vv(
        self,
        method: str,
        args: list,
        traceparent: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> Tuple[object, Optional[list], Optional[list]]:
        body = encode_request(method, args, traceparent)
        try:
            conn = self._connection()
            self._apply_timeout(conn, timeout)
            conn.request(
                "POST", "/replica", body=body, headers=self._headers
            )
            response = conn.getresponse()
            data = response.read()
        except (OSError, ConnectionError) as exc:
            self._drop_connection()
            raise ReplicaUnavailable(
                f"replica at {self._host}:{self._port} unreachable: "
                f"{exc}",
                kind=self._failure_kind(exc),
            ) from exc
        if response.status != 200:
            self._drop_connection()
            raise ReplicaUnavailable(
                f"replica at {self._host}:{self._port} returned HTTP "
                f"{response.status}",
                kind="http_status",
            )
        try:
            return decode_response_vv(data)
        except CborDecodeError as exc:
            self._drop_connection()
            raise ReplicaUnavailable(
                f"garbled replica response: {exc}",
                kind="wire_decode",
            ) from exc

    def close(self) -> None:
        self._drop_connection()
